"""Crash recovery: replay a WAL directory into a live provider.

Semantics (the tentpole contract of ISSUE 3):

- the newest checkpoint's per-doc snapshots are applied first, then the
  tail segments it does not cover, in order — snapshot-then-tail;
- a torn write (short or checksum-failing record) on the FINAL segment
  truncates the log at the first bad byte: that is the crash frontier,
  everything before it is intact by CRC;
- a corrupt record in the MIDDLE of the log (a sealed segment or the
  checkpoint file — at-rest damage, not a crash artifact) is routed
  through ``validate_update`` into the dead-letter queue and the reader
  resynchronizes on the next record magic — recovery never aborts;
- replay is idempotent by the CRDT merge contract: applying a snapshot
  plus an overlapping tail, or replaying the same log twice, converges
  to the same state (pinned by tests/test_persistence.py).
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

from .records import (
    KIND_ACK,
    KIND_ADM,
    KIND_DLQ,
    KIND_GEO,
    KIND_MIGRATE,
    KIND_RELEASE,
    KIND_REPL,
    KIND_SNAPSHOT,
    KIND_TIER,
    KIND_UPDATE,
    SEG_HEADER,
    SNAP_HEADER,
    decode_tier_payload,
    resync,
    try_decode_at,
)
from .wal import list_checkpoints, list_segments

# cap on the bytes of an unparseable region preserved in a dead letter
_SLICE_CAP = 1 << 16


def iter_file_events(path, final: bool):
    """Decode one segment/checkpoint file into a stream of events:
    ``("record", WalRecord)``, ``("corrupt", payload_bytes, note)``, or
    ``("torn", offset)``.  ``final=True`` applies the torn-write rule:
    the first anomaly ends the stream (truncation point = its offset);
    sealed files instead surface anomalies as corrupt events and keep
    reading from the next record magic."""
    data = Path(path).read_bytes()
    if not data:
        return
    if data[:8] not in (SEG_HEADER, SNAP_HEADER):
        if final:
            yield ("torn", 0)
        else:
            yield ("corrupt", data[:_SLICE_CAP], "bad segment header")
        return
    pos = 8
    n = len(data)
    while pos < n:
        status, val, end = try_decode_at(data, pos)
        if status == "ok":
            yield ("record", val)
            pos = end
            continue
        if final:
            yield ("torn", pos)
            return
        if status == "bad_crc":
            yield ("corrupt", val, "crc mismatch")
            pos = end
            continue
        # bad_header / short inside a sealed file: scan forward for the
        # next record magic; the skipped region is preserved (capped)
        nxt = resync(data, pos + 1)
        yield ("corrupt", data[pos : min(nxt, pos + _SLICE_CAP)],
               "unparseable bytes")
        pos = nxt


def scan_wal(path):
    """(newest checkpoint | None, uncovered tail segments) of a dir."""
    path = Path(path)
    if not path.is_dir():
        return None, []
    ckpts = list_checkpoints(path)
    ckpt = ckpts[-1] if ckpts else None
    upto = ckpt[0] if ckpt else 0
    segs = [(i, p) for i, p in list_segments(path) if i >= upto]
    return ckpt, segs


def count_guids(path, exclude_from: int | None = None) -> int:
    """Distinct doc guids named anywhere in the log — the default fleet
    size for ``TpuProvider.recover`` when the caller gives none."""
    ckpt, segs = scan_wal(path)
    if exclude_from is not None:
        segs = [(i, p) for i, p in segs if i < exclude_from]
    guids: set[str] = set()
    sources = ([ckpt[1]] if ckpt else []) + [p for _, p in segs]
    for j, p in enumerate(sources):
        for ev in iter_file_events(p, final=(j == len(sources) - 1)):
            if ev[0] == "record" and ev[1].kind not in (
                KIND_DLQ, KIND_ADM, KIND_GEO
            ):
                # KIND_ADM/KIND_GEO records are fleet/region-scoped
                # (empty guid) and must not inflate the recovered fleet
                # size
                guids.add(ev[1].guid)
    return len(guids)


def replay_wal(
    provider,
    path,
    exclude_from: int | None = None,
    truncate_torn: bool = True,
) -> dict:
    """Replay a WAL directory into ``provider`` and flush.

    ``exclude_from`` skips segments at or past that index (the
    provider's own live appends during self-recovery);
    ``truncate_torn=False`` reads without modifying files (the
    idempotence property tests re-read prefixes non-destructively).
    Returns the recovery stats dict (also stored by
    ``TpuProvider.recover`` as ``last_recovery``)."""
    from ..updates import validate_update

    t0 = time.perf_counter()
    m = provider._wal_metrics
    eng = provider.engine
    stats = {
        "checkpoint": None,
        "segments": 0,
        "snapshots_applied": 0,
        "records_applied": 0,
        "dead_lettered": 0,
        "overflowed": 0,
        "dlq_restored": 0,
        "released": 0,
        "session_acks": 0,
        "migration_intents": 0,
        "migrations_pending": {},
        "repl_markers": 0,
        "repl_roles": {},
        "adm_transitions": 0,
        "adm_level": None,
        "geo_links": 0,
        "geo_floors": {},
        "tier_records": 0,
        "tier_placements": {},
        "corrupt_records": 0,
        "torn_truncations": 0,
        "duration_s": 0.0,
        "outcome": "empty",
    }
    ckpt, segs = scan_wal(path)
    if exclude_from is not None:
        segs = [(i, p) for i, p in segs if i < exclude_from]
    sources: list[tuple[Path, bool]] = []
    if ckpt is not None:
        stats["checkpoint"] = str(ckpt[1])
        sources.append((ckpt[1], False))
    sources += [(p, j == len(segs) - 1) for j, (_i, p) in enumerate(segs)]
    stats["segments"] = len(segs)

    def doc_of(guid: str) -> int:
        from ..provider import ProviderFullError

        try:
            return provider.doc_id(guid)
        except ProviderFullError:
            return -1

    saw_records = False
    # KIND_TIER placement markers (ISSUE 7): the LAST marker for a guid
    # stands — a "hot" promotion marker or a release clears it.  State
    # replay and placement are separate: tier-record updates apply like
    # snapshots as they stream by, and placement happens once, after
    # the final flush, via TierManager.place_recovered.
    tier_markers: dict[str, dict] = {}
    for fpath, final in sources:
        for ev in iter_file_events(fpath, final=final):
            if ev[0] == "torn":
                stats["torn_truncations"] += 1
                m.torn.inc()
                if truncate_torn:
                    off = ev[1]
                    os.truncate(
                        fpath, 0 if off <= len(SEG_HEADER) else off
                    )
                continue
            if ev[0] == "corrupt":
                payload, note = ev[1] or b"", ev[2]
                # the ISSUE contract: mid-log corruption is routed
                # through validate_update into the DLQ, never applied
                # and never fatal.  Bytes whose CRC failed are refused
                # even if they happen to still decode — an unverifiable
                # update is a Byzantine input.
                try:
                    validate_update(payload)
                except Exception as ve:
                    reason = f"wal-corrupt: {note} ({type(ve).__name__})"
                else:
                    reason = f"wal-corrupt: {note} (decodes; refused)"
                eng._dead_letter(-1, payload, False, reason)
                stats["corrupt_records"] += 1
                stats["dead_lettered"] += 1
                m.corrupt.inc()
                m.replayed.labels(disposition="dead_lettered").inc()
                continue
            rec = ev[1]
            saw_records = True
            if rec.kind in (KIND_UPDATE, KIND_SNAPSHOT):
                doc = doc_of(rec.guid)
                if doc < 0:
                    # the provider is full: the doc's durably-journaled
                    # state must NOT vanish.  The record rides the DLQ
                    # with its guid in the reason so an operator (or a
                    # fleet rebalancer) can re-route it to a shard with
                    # room.
                    eng._dead_letter(
                        doc, rec.payload, rec.v2,
                        f"wal-overflow: no free slot for {rec.guid!r}",
                    )
                    stats["overflowed"] += 1
                    stats["dead_lettered"] += 1
                    m.overflow.inc()
                    m.replayed.labels(disposition="overflow").inc()
                    continue
                try:
                    validate_update(rec.payload, rec.v2)
                except Exception as ve:
                    eng._dead_letter(
                        doc, rec.payload, rec.v2,
                        f"wal-invalid: {type(ve).__name__}: {ve}",
                    )
                    stats["dead_lettered"] += 1
                    m.replayed.labels(disposition="dead_lettered").inc()
                    continue
                if eng.queue_update(doc, rec.payload, v2=rec.v2):
                    # mark dirty NOW, not after the loop: a tiered
                    # provider's mid-replay auto-eviction flushes
                    # before exporting, and a gated no-op flush would
                    # leave every slot ineligible (queued updates)
                    provider._dirty = True
                    key = (
                        "snapshots_applied"
                        if rec.kind == KIND_SNAPSHOT
                        else "records_applied"
                    )
                    stats[key] += 1
                    m.replayed.labels(
                        disposition="snapshot"
                        if rec.kind == KIND_SNAPSHOT
                        else "applied"
                    ).inc()
                else:
                    # queue_update already dead-lettered (quarantine)
                    stats["dead_lettered"] += 1
                    m.replayed.labels(disposition="dead_lettered").inc()
            elif rec.kind == KIND_DLQ:
                try:
                    state = json.loads(rec.payload.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    state = None
                if isinstance(state, dict):
                    stats["dlq_restored"] += provider._restore_dlq(state)
                    m.replayed.labels(disposition="dlq_restored").inc()
            elif rec.kind == KIND_RELEASE:
                provider._apply_release_record(rec.guid)
                # a release after a migration intent marks the handoff
                # complete: the doc left this shard on purpose
                stats["migrations_pending"].pop(rec.guid, None)
                stats["repl_roles"].pop(rec.guid, None)
                tier_markers.pop(rec.guid, None)
                stats["released"] += 1
                m.replayed.labels(disposition="released").inc()
            elif rec.kind == KIND_TIER:
                try:
                    meta, update = decode_tier_payload(rec.payload)
                except ValueError as ve:
                    eng._dead_letter(
                        -1, rec.payload, False,
                        f"wal-tier-invalid: {ve} ({rec.guid!r})",
                    )
                    stats["dead_lettered"] += 1
                    m.replayed.labels(disposition="dead_lettered").inc()
                    continue
                stats["tier_records"] += 1
                m.replayed.labels(disposition="tier").inc()
                if meta["tier"] == "hot":
                    # promotion marker: the earlier demote no longer
                    # stands (the doc's state lives in later records)
                    tier_markers.pop(rec.guid, None)
                    continue
                # demote marker: its payload is the doc's full state at
                # demotion time — replay it like a snapshot, placement
                # comes after the final flush
                if update:
                    doc = doc_of(rec.guid)
                    if doc < 0:
                        eng._dead_letter(
                            doc, update, False,
                            f"wal-overflow: no free slot for "
                            f"{rec.guid!r}",
                        )
                        stats["overflowed"] += 1
                        stats["dead_lettered"] += 1
                        m.overflow.inc()
                        m.replayed.labels(disposition="overflow").inc()
                        continue
                    try:
                        validate_update(update)
                    except Exception as ve:
                        eng._dead_letter(
                            doc, update, False,
                            f"wal-invalid: {type(ve).__name__}: {ve}",
                        )
                        stats["dead_lettered"] += 1
                        m.replayed.labels(
                            disposition="dead_lettered"
                        ).inc()
                        continue
                    if eng.queue_update(doc, update):
                        provider._dirty = True
                        stats["snapshots_applied"] += 1
                    else:
                        stats["dead_lettered"] += 1
                        m.replayed.labels(
                            disposition="dead_lettered"
                        ).inc()
                        continue
                tier_markers[rec.guid] = meta
            elif rec.kind == KIND_MIGRATE:
                # migration intent (ISSUE 6): journaled by the source
                # shard before any state reached the destination.  An
                # intent with no later release means the crash landed
                # mid-migration; FleetRouter.recover resolves ownership
                # (destination owns iff its own WAL admitted the doc).
                try:
                    intent = json.loads(rec.payload.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    intent = None
                if isinstance(intent, dict) and "dst" in intent:
                    try:
                        stats["migrations_pending"][rec.guid] = {
                            "dst": int(intent["dst"]),
                            "epoch": int(intent.get("epoch", 0)),
                        }
                    except (TypeError, ValueError):
                        pass
                    else:
                        stats["migration_intents"] += 1
                        m.replayed.labels(disposition="migrate").inc()
            elif rec.kind == KIND_REPL:
                # replication role marker (ISSUE 8): "this WAL holds the
                # doc as a replica copy" or "this shard won ownership at
                # fencing epoch N".  The LAST marker stands (a promotion
                # overwrites the replica claim); a release clears it.
                # FleetRouter.recover reads the surfaced roles to keep
                # replica journals from looking like split-brain owners
                # and to fence stale-primary claims behind newer epochs.
                try:
                    info = json.loads(rec.payload.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    info = None
                if isinstance(info, dict) and info.get("role") in (
                    "replica", "primary"
                ):
                    try:
                        stats["repl_roles"][rec.guid] = {
                            "role": str(info["role"]),
                            "epoch": int(info.get("epoch", 0)),
                        }
                    except (TypeError, ValueError):
                        pass
                    else:
                        stats["repl_markers"] += 1
                        m.replayed.labels(disposition="repl").inc()
            elif rec.kind == KIND_ADM:
                # brownout transition marker (ISSUE 10): forensic record
                # of when/why service degraded.  Surfaced in stats only;
                # the live brownout level always restarts at "normal"
                # (post-crash load may look nothing like pre-crash).
                try:
                    info = json.loads(rec.payload.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    info = None
                if isinstance(info, dict) and "level" in info:
                    stats["adm_transitions"] += 1
                    stats["adm_level"] = str(info["level"])
                    m.replayed.labels(disposition="adm").inc()
            elif rec.kind == KIND_GEO:
                # geo link floor (ISSUE 17): "our WAN session with
                # region <peer> holds <sid> up to <seq> at fencing
                # epoch <epoch>".  The LAST record per peer stands;
                # the rebuilt region's GeoReplicator HELLOs each link
                # with these floors so a kill -9'd region RESUMES its
                # WAN retransmission windows instead of full-resyncing
                # the whole doc space across every link.
                try:
                    info = json.loads(rec.payload.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    info = None
                hints = getattr(provider, "_recovered_geo", None)
                if isinstance(info, dict) and hints is not None:
                    try:
                        floor = {
                            "sid": int(info["sid"]),
                            "seq": int(info["seq"]),
                            "epoch": int(info.get("epoch", 0)),
                        }
                        peer = str(info["peer"])
                    except (KeyError, TypeError, ValueError):
                        pass
                    else:
                        hints[peer] = floor
                        stats["geo_floors"][peer] = floor
                        stats["geo_links"] = len(hints)
                        m.replayed.labels(disposition="geo").inc()
            elif rec.kind == KIND_ACK:
                # session ack floor (ISSUE 5): the journaled "we hold
                # peer session <sid> up to <seq>" fact.  Later records
                # win (floors only advance); the rebuilt provider's
                # sessions HELLO with these so the surviving peer
                # resumes retransmission instead of a full resync.
                try:
                    ack = json.loads(rec.payload.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    ack = None
                hints = getattr(provider, "_recovered_acks", None)
                if isinstance(ack, dict) and hints is not None:
                    try:
                        hints[(rec.guid, str(ack["peer"]))] = (
                            int(ack["sid"]), int(ack["seq"])
                        )
                    except (KeyError, TypeError, ValueError):
                        pass
                    else:
                        stats["session_acks"] += 1
                        m.replayed.labels(disposition="ack").inc()
    if stats["snapshots_applied"] or stats["records_applied"]:
        # queue_update was called below the provider's dirty-tracking
        # seam; without this, device-backed engines would leave the
        # replayed records queued-but-uningested until unrelated new
        # traffic happened to trigger a flush
        provider._dirty = True
    provider.flush()
    if tier_markers:
        tiers = getattr(provider, "tiers", None)
        if tiers is not None and tiers.enabled:
            stats["tier_placements"] = tiers.place_recovered(tier_markers)
        else:
            # tiering off on the recovering provider: every doc stays
            # hot, but the letters that rode the demote markers must
            # not vanish
            import base64

            for guid, meta in sorted(tier_markers.items()):
                doc = provider._guids.get(guid, -1)
                for d in meta.get("letters") or []:
                    eng._dead_letter(
                        doc,
                        base64.b64decode(d.get("update", "")),
                        bool(d.get("v2")),
                        str(d.get("reason", "tiered")),
                    )
    dt = time.perf_counter() - t0
    stats["duration_s"] = round(dt, 6)
    if stats["corrupt_records"]:
        stats["outcome"] = "corrupt_records"
    elif stats["torn_truncations"]:
        stats["outcome"] = "torn_tail"
    elif saw_records:
        stats["outcome"] = "clean"
    m.recoveries.labels(outcome=stats["outcome"]).inc()
    m.replay_seconds.observe(dt)
    return stats
