"""Lamport-timestamp identity: ``ID(client, clock)``.

Mirrors the semantics of reference src/utils/ID.js:8-69.  Every CRDT struct is
addressed by the pair (client, clock); clocks are per-client, contiguous, and
count UTF-16 content units.
"""

from __future__ import annotations

from .lib0 import decoding, encoding


class ID:
    __slots__ = ("client", "clock")

    def __init__(self, client: int, clock: int):
        self.client = client
        self.clock = clock

    def __repr__(self):
        return f"ID({self.client},{self.clock})"

    def __eq__(self, other):
        return (
            isinstance(other, ID)
            and other.client == self.client
            and other.clock == self.clock
        )

    def __hash__(self):
        return hash((self.client, self.clock))


def create_id(client: int, clock: int) -> ID:
    return ID(client, clock)


def compare_ids(a: ID | None, b: ID | None) -> bool:
    return a is b or (
        a is not None and b is not None and a.client == b.client and a.clock == b.clock
    )


def write_id(encoder: encoding.Encoder, id: ID) -> None:
    encoding.write_var_uint(encoder, id.client)
    encoding.write_var_uint(encoder, id.clock)


def read_id(decoder: decoding.Decoder) -> ID:
    return ID(decoding.read_var_uint(decoder), decoding.read_var_uint(decoder))


def find_root_type_key(type_) -> str:
    """Reverse lookup of a root type's key in ``doc.share``
    (reference src/utils/ID.js:82-90)."""
    for key, value in type_.doc.share.items():
        if value is type_:
            return key
    raise RuntimeError("root type not found in doc.share")
