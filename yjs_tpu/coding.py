"""Update/DeleteSet wire encoders and decoders, V1 and V2.

Byte-compatible with the reference encoder hierarchy:
- V1: plain varints (reference src/utils/UpdateEncoder.js:110-227)
- V2: 9 independent columnar streams, each RLE/diff-RLE compressed and
  length-prefixed, plus an uncompressed "rest" stream appended at the end
  (reference src/utils/UpdateEncoder.js:264-408, UpdateDecoder.js:245-392).

The V2 layout *is* the struct-of-arrays format the TPU batch engine
(yjs_tpu.ops) consumes directly.
"""

from __future__ import annotations

import json

from .ids import ID
from .lib0 import decoding, encoding
from .lib0.decoding import (
    Decoder,
    IntDiffOptRleDecoder,
    RleDecoder,
    StringDecoder,
    UintOptRleDecoder,
)
from .lib0.encoding import (
    Encoder,
    IntDiffOptRleEncoder,
    RleEncoder,
    StringEncoder,
    UintOptRleEncoder,
)


# ---------------------------------------------------------------------------
# DeleteSet coders
# ---------------------------------------------------------------------------

class DSEncoderV1:
    def __init__(self):
        self.rest_encoder = Encoder()

    def to_bytes(self) -> bytes:
        return self.rest_encoder.to_bytes()

    def reset_ds_cur_val(self) -> None:
        pass

    def write_ds_clock(self, clock: int) -> None:
        encoding.write_var_uint(self.rest_encoder, clock)

    def write_ds_len(self, ln: int) -> None:
        encoding.write_var_uint(self.rest_encoder, ln)


class DSDecoderV1:
    def __init__(self, decoder: Decoder):
        self.rest_decoder = decoder

    def reset_ds_cur_val(self) -> None:
        pass

    def read_ds_clock(self) -> int:
        return decoding.read_var_uint(self.rest_decoder)

    def read_ds_len(self) -> int:
        return decoding.read_var_uint(self.rest_decoder)


class DSEncoderV2:
    """Delta-encodes DS clocks within each client
    (reference src/utils/UpdateEncoder.js:229-262)."""

    def __init__(self):
        self.rest_encoder = Encoder()
        self.ds_curr_val = 0

    def to_bytes(self) -> bytes:
        return self.rest_encoder.to_bytes()

    def reset_ds_cur_val(self) -> None:
        self.ds_curr_val = 0

    def write_ds_clock(self, clock: int) -> None:
        diff = clock - self.ds_curr_val
        self.ds_curr_val = clock
        encoding.write_var_uint(self.rest_encoder, diff)

    def write_ds_len(self, ln: int) -> None:
        if ln == 0:
            raise ValueError("delete-set range length must be > 0")
        encoding.write_var_uint(self.rest_encoder, ln - 1)
        self.ds_curr_val += ln


class DSDecoderV2:
    def __init__(self, decoder: Decoder):
        self.rest_decoder = decoder
        self.ds_curr_val = 0

    def reset_ds_cur_val(self) -> None:
        self.ds_curr_val = 0

    def read_ds_clock(self) -> int:
        self.ds_curr_val += decoding.read_var_uint(self.rest_decoder)
        return self.ds_curr_val

    def read_ds_len(self) -> int:
        diff = decoding.read_var_uint(self.rest_decoder) + 1
        self.ds_curr_val += diff
        return diff


# ---------------------------------------------------------------------------
# Update coders, V1
# ---------------------------------------------------------------------------

class UpdateEncoderV1(DSEncoderV1):
    def write_left_id(self, id: ID) -> None:
        encoding.write_var_uint(self.rest_encoder, id.client)
        encoding.write_var_uint(self.rest_encoder, id.clock)

    def write_right_id(self, id: ID) -> None:
        encoding.write_var_uint(self.rest_encoder, id.client)
        encoding.write_var_uint(self.rest_encoder, id.clock)

    def write_client(self, client: int) -> None:
        encoding.write_var_uint(self.rest_encoder, client)

    def write_info(self, info: int) -> None:
        encoding.write_uint8(self.rest_encoder, info)

    def write_string(self, s: str) -> None:
        encoding.write_var_string(self.rest_encoder, s)

    def write_parent_info(self, is_ykey: bool) -> None:
        encoding.write_var_uint(self.rest_encoder, 1 if is_ykey else 0)

    def write_type_ref(self, info: int) -> None:
        encoding.write_var_uint(self.rest_encoder, info)

    def write_len(self, ln: int) -> None:
        encoding.write_var_uint(self.rest_encoder, ln)

    def write_any(self, any_) -> None:
        encoding.write_any(self.rest_encoder, any_)

    def write_buf(self, buf: bytes) -> None:
        encoding.write_var_uint8_array(self.rest_encoder, buf)

    def write_json(self, embed) -> None:
        # V1 keeps legacy JSON-string encoding (UpdateEncoder.js:217-219)
        encoding.write_var_string(self.rest_encoder, _json_stringify(embed))

    def write_key(self, key: str) -> None:
        encoding.write_var_string(self.rest_encoder, key)


class UpdateDecoderV1(DSDecoderV1):
    def read_left_id(self) -> ID:
        return ID(
            decoding.read_var_uint(self.rest_decoder),
            decoding.read_var_uint(self.rest_decoder),
        )

    def read_right_id(self) -> ID:
        return self.read_left_id()

    def read_client(self) -> int:
        return decoding.read_var_uint(self.rest_decoder)

    def read_info(self) -> int:
        return decoding.read_uint8(self.rest_decoder)

    def read_string(self) -> str:
        return decoding.read_var_string(self.rest_decoder)

    def read_parent_info(self) -> bool:
        return decoding.read_var_uint(self.rest_decoder) == 1

    def read_type_ref(self) -> int:
        return decoding.read_var_uint(self.rest_decoder)

    def read_len(self) -> int:
        return decoding.read_var_uint(self.rest_decoder)

    def read_any(self):
        return decoding.read_any(self.rest_decoder)

    def read_buf(self) -> bytes:
        return decoding.read_var_uint8_array(self.rest_decoder)

    def read_json(self):
        return _json_parse(decoding.read_var_string(self.rest_decoder))

    def read_key(self) -> str:
        return decoding.read_var_string(self.rest_decoder)


# ---------------------------------------------------------------------------
# Update coders, V2 (columnar)
# ---------------------------------------------------------------------------

class UpdateEncoderV2(DSEncoderV2):
    def __init__(self):
        super().__init__()
        self.key_clock = 0
        self.key_map: dict[str, int] = {}
        self.key_clock_encoder = IntDiffOptRleEncoder()
        self.client_encoder = UintOptRleEncoder()
        self.left_clock_encoder = IntDiffOptRleEncoder()
        self.right_clock_encoder = IntDiffOptRleEncoder()
        self.info_encoder = RleEncoder()
        self.string_encoder = StringEncoder()
        self.parent_info_encoder = RleEncoder()
        self.type_ref_encoder = UintOptRleEncoder()
        self.len_encoder = UintOptRleEncoder()

    def to_bytes(self) -> bytes:
        encoder = Encoder()
        encoding.write_uint8(encoder, 0)  # feature flag, always 0 in v13.4
        encoding.write_var_uint8_array(encoder, self.key_clock_encoder.to_bytes())
        encoding.write_var_uint8_array(encoder, self.client_encoder.to_bytes())
        encoding.write_var_uint8_array(encoder, self.left_clock_encoder.to_bytes())
        encoding.write_var_uint8_array(encoder, self.right_clock_encoder.to_bytes())
        encoding.write_var_uint8_array(encoder, self.info_encoder.to_bytes())
        encoding.write_var_uint8_array(encoder, self.string_encoder.to_bytes())
        encoding.write_var_uint8_array(encoder, self.parent_info_encoder.to_bytes())
        encoding.write_var_uint8_array(encoder, self.type_ref_encoder.to_bytes())
        encoding.write_var_uint8_array(encoder, self.len_encoder.to_bytes())
        # the rest stream is appended raw (no length prefix)
        encoding.write_uint8_array(encoder, self.rest_encoder.to_bytes())
        return encoder.to_bytes()

    def write_left_id(self, id: ID) -> None:
        self.client_encoder.write(id.client)
        self.left_clock_encoder.write(id.clock)

    def write_right_id(self, id: ID) -> None:
        self.client_encoder.write(id.client)
        self.right_clock_encoder.write(id.clock)

    def write_client(self, client: int) -> None:
        self.client_encoder.write(client)

    def write_info(self, info: int) -> None:
        self.info_encoder.write(info)

    def write_string(self, s: str) -> None:
        self.string_encoder.write(s)

    def write_parent_info(self, is_ykey: bool) -> None:
        self.parent_info_encoder.write(1 if is_ykey else 0)

    def write_type_ref(self, info: int) -> None:
        self.type_ref_encoder.write(info)

    def write_len(self, ln: int) -> None:
        self.len_encoder.write(ln)

    def write_any(self, any_) -> None:
        encoding.write_any(self.rest_encoder, any_)

    def write_buf(self, buf: bytes) -> None:
        encoding.write_var_uint8_array(self.rest_encoder, buf)

    def write_json(self, embed) -> None:
        encoding.write_any(self.rest_encoder, embed)

    def write_key(self, key: str) -> None:
        # Quirk preserved from the v13.4.9 encoder (UpdateEncoder.js:399-407):
        # key_map is consulted but never populated, so every key write emits a
        # fresh keyClock AND the key string.  The decoder's cache makes this
        # correct; we must reproduce it for byte-identical output.
        if self.key_map.get(key) is None:
            self.key_clock_encoder.write(self.key_clock)
            self.key_clock += 1
            self.string_encoder.write(key)
        else:
            self.key_clock_encoder.write(self.key_clock)
            self.key_clock += 1


class UpdateDecoderV2(DSDecoderV2):
    def __init__(self, decoder: Decoder):
        super().__init__(decoder)
        self.keys: list[str] = []
        decoding.read_uint8(decoder)  # feature flag
        self.key_clock_decoder = IntDiffOptRleDecoder(decoding.read_var_uint8_array(decoder))
        self.client_decoder = UintOptRleDecoder(decoding.read_var_uint8_array(decoder))
        self.left_clock_decoder = IntDiffOptRleDecoder(decoding.read_var_uint8_array(decoder))
        self.right_clock_decoder = IntDiffOptRleDecoder(decoding.read_var_uint8_array(decoder))
        self.info_decoder = RleDecoder(decoding.read_var_uint8_array(decoder))
        self.string_decoder = StringDecoder(decoding.read_var_uint8_array(decoder))
        self.parent_info_decoder = RleDecoder(decoding.read_var_uint8_array(decoder))
        self.type_ref_decoder = UintOptRleDecoder(decoding.read_var_uint8_array(decoder))
        self.len_decoder = UintOptRleDecoder(decoding.read_var_uint8_array(decoder))

    def read_left_id(self) -> ID:
        return ID(self.client_decoder.read(), self.left_clock_decoder.read())

    def read_right_id(self) -> ID:
        return ID(self.client_decoder.read(), self.right_clock_decoder.read())

    def read_client(self) -> int:
        return self.client_decoder.read()

    def read_info(self) -> int:
        return self.info_decoder.read()

    def read_string(self) -> str:
        return self.string_decoder.read()

    def read_parent_info(self) -> bool:
        return self.parent_info_decoder.read() == 1

    def read_type_ref(self) -> int:
        return self.type_ref_decoder.read()

    def read_len(self) -> int:
        return self.len_decoder.read()

    def read_any(self):
        return decoding.read_any(self.rest_decoder)

    def read_buf(self) -> bytes:
        return decoding.read_var_uint8_array(self.rest_decoder)

    def read_json(self):
        return decoding.read_any(self.rest_decoder)

    def read_key(self) -> str:
        key_clock = self.key_clock_decoder.read()
        if key_clock < len(self.keys):
            return self.keys[key_clock]
        key = self.string_decoder.read()
        self.keys.append(key)
        return key


# ---------------------------------------------------------------------------
# JSON helpers matching JS JSON.stringify/parse for the V1 embed encoding.
# Single source of truth — core.py imports these for ContentJSON.
# ---------------------------------------------------------------------------

def _json_stringify(value) -> str:
    return json.dumps(value, separators=(",", ":"), ensure_ascii=False)


def _json_parse(s: str):
    return json.loads(s)


# module-global default coder selection (reference src/utils/encoding.js:44-61)
_defaults = {
    "ds_encoder": DSEncoderV1,
    "ds_decoder": DSDecoderV1,
    "update_encoder": UpdateEncoderV1,
    "update_decoder": UpdateDecoderV1,
}


def use_v1_encoding() -> None:
    _defaults.update(
        ds_encoder=DSEncoderV1,
        ds_decoder=DSDecoderV1,
        update_encoder=UpdateEncoderV1,
        update_decoder=UpdateDecoderV1,
    )


def use_v2_encoding() -> None:
    _defaults.update(
        ds_encoder=DSEncoderV2,
        ds_decoder=DSDecoderV2,
        update_encoder=UpdateEncoderV2,
        update_decoder=UpdateDecoderV2,
    )


def default_ds_encoder():
    return _defaults["ds_encoder"]()


def default_ds_decoder(decoder):
    return _defaults["ds_decoder"](decoder)


def default_update_encoder():
    return _defaults["update_encoder"]()


def default_update_decoder(decoder):
    return _defaults["update_decoder"](decoder)
