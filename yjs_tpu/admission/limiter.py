"""Token buckets and weighted-fair queuing for the admission seam.

Two small deterministic primitives used by the fleet-wide admission
controller (ISSUE 10):

- ``TokenBucket`` — tick-based rate limiter with lazy refill (no per-tick
  sweep over idle buckets; refill is computed from the tick delta at the
  moment of use, so 100k mostly-idle doc buckets cost nothing).
- ``WeightedFairQueue`` — classic virtual-finish-time WFQ over tenants.
  Deterministic: ties broken by arrival sequence number, never by dict
  order or object identity, so a seeded overload run drains in exactly
  the same order every time.

``AdmissionRejected`` is the typed veto outcome: callers either handle it
(session paths convert it into a BUSY frame) or it propagates to the
client that offered the update — it is never silently dropped.
"""

from __future__ import annotations

import heapq
from typing import Any

__all__ = ["AdmissionRejected", "TokenBucket", "WeightedFairQueue"]


class AdmissionRejected(RuntimeError):
    """An inbound update was refused by admission control.

    Carries enough structure for callers to respond cooperatively:
    ``reason`` is one of ``"rate-limit"``/``"queue-full"``/
    ``"reject-writes"`` and ``retry_after`` is the suggested backoff in
    ticks (rides the wire inside the BUSY envelope frame).
    """

    def __init__(
        self, guid: str, tenant: str, reason: str, retry_after: int
    ) -> None:
        super().__init__(
            f"admission rejected update for {guid!r} "
            f"(tenant {tenant!r}): {reason}; retry after "
            f"{int(retry_after)} ticks"
        )
        self.guid = guid
        self.tenant = tenant
        self.reason = reason
        self.retry_after = int(retry_after)


class TokenBucket:
    """Tick-based token bucket with lazy refill.

    ``refill_to(tick)`` advances the bucket to the given tick, adding
    ``rate`` tokens per elapsed tick up to ``burst``.  Callers refill
    before ``peek``/``take`` so idle buckets need no per-tick sweep.
    """

    __slots__ = ("rate", "burst", "tokens", "tick")

    def __init__(self, rate: float, burst: float, tick: int = 0) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.tick = int(tick)

    def refill_to(self, tick: int) -> None:
        if tick > self.tick:
            self.tokens = min(
                self.burst, self.tokens + self.rate * (tick - self.tick)
            )
            self.tick = tick

    def peek(self, cost: float = 1.0) -> bool:
        return self.tokens >= cost

    def take(self, cost: float = 1.0) -> bool:
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False

    def snapshot(self) -> dict:
        return {
            "rate": self.rate,
            "burst": self.burst,
            "tokens": self.tokens,
            "tick": self.tick,
        }


class WeightedFairQueue:
    """Virtual-finish-time weighted-fair queue over tenants.

    Each pushed item is stamped with a virtual finish time
    ``max(vtime, tenant_last_finish) + cost / weight``; pops return the
    smallest finish time, with the (finish, arrival-seq) pair as a total
    order so equal-weight tenants interleave round-robin
    deterministically.  A heavier weight drains proportionally faster; an
    abusive tenant flooding the queue only delays its own backlog.
    """

    __slots__ = ("_heap", "_seq", "_vtime", "_tenant_finish", "_depths")

    def __init__(self) -> None:
        self._heap: list[tuple[float, int, str, Any]] = []
        self._seq = 0
        self._vtime = 0.0
        self._tenant_finish: dict[str, float] = {}
        self._depths: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._heap)

    def push(
        self, tenant: str, item: Any, cost: float = 1.0, weight: float = 1.0
    ) -> None:
        start = max(self._vtime, self._tenant_finish.get(tenant, 0.0))
        finish = start + cost / max(1e-9, float(weight))
        self._tenant_finish[tenant] = finish
        self._seq += 1
        heapq.heappush(self._heap, (finish, self._seq, tenant, item))
        self._depths[tenant] = self._depths.get(tenant, 0) + 1

    def pop(self) -> tuple[str, Any]:
        finish, _seq, tenant, item = heapq.heappop(self._heap)
        self._vtime = max(self._vtime, finish)
        n = self._depths.get(tenant, 1) - 1
        if n <= 0:
            self._depths.pop(tenant, None)
            self._tenant_finish.pop(tenant, None)
        else:
            self._depths[tenant] = n
        return tenant, item

    def drain(self) -> list[tuple[str, Any]]:
        out = []
        while self._heap:
            out.append(self.pop())
        return out

    def depth_of(self, tenant: str) -> int:
        return self._depths.get(tenant, 0)

    def snapshot(self) -> dict:
        return {
            "depth": len(self._heap),
            "by_tenant": dict(sorted(self._depths.items())),
        }
