"""yjs_tpu.admission: fleet-wide admission control and brownout
degradation (ISSUE 10).

Public surface:

- :class:`AdmissionController` / :class:`AdmissionConfig` — the shared
  per-fleet (or per-provider) rate-limit + brownout state machine;
- :class:`AdmissionRejected` — typed veto raised at the admission seam;
- :class:`TokenBucket` / :class:`WeightedFairQueue` — the deterministic
  primitives underneath;
- :class:`BrownoutController` and the level constants
  ``NORMAL``/``SHED_BACKGROUND``/``COALESCE``/``REJECT_WRITES`` with
  ``LEVEL_NAMES``.
"""

from .brownout import (  # noqa: F401
    COALESCE,
    LEVEL_NAMES,
    NORMAL,
    REJECT_WRITES,
    SHED_BACKGROUND,
    BrownoutController,
)
from .controller import (  # noqa: F401
    AdmissionConfig,
    AdmissionController,
)
from .limiter import (  # noqa: F401
    AdmissionRejected,
    TokenBucket,
    WeightedFairQueue,
)

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "AdmissionRejected",
    "BrownoutController",
    "TokenBucket",
    "WeightedFairQueue",
    "NORMAL",
    "SHED_BACKGROUND",
    "COALESCE",
    "REJECT_WRITES",
    "LEVEL_NAMES",
]
