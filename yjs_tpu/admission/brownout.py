"""Adaptive brownout controller: deterministic, hysteresis-gated
degradation levels.

Levels (each one strictly widens the previous level's shedding):

====  ================  ====================================================
 0    normal            full service
 1    shed-background   anti-entropy digests and digest repair paused,
                        flush tick widened (advisory ``flush_interval_scale``)
 2    coalesce          lagging-style delta coalescing forced on all peers
 3    reject-writes     new writes refused with retry-after; reads and
                        sync-step1 still served
====  ================  ====================================================

Transitions move ONE level at a time and are gated by consecutive-streak
hysteresis: the overload signal must point above the current level for
``up_ticks`` consecutive ticks to escalate, and below it for
``down_ticks`` consecutive ticks to recover — so a borderline signal
cannot flap the fleet between levels.  Every transition is pushed through
``on_transition`` (the admission controller journals it to each attached
provider's WAL and bumps ``ytpu_adm_transitions_total``) and kept in a
bounded in-memory ring for snapshots.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Optional

__all__ = [
    "NORMAL",
    "SHED_BACKGROUND",
    "COALESCE",
    "REJECT_WRITES",
    "LEVEL_NAMES",
    "BrownoutController",
]

NORMAL = 0
SHED_BACKGROUND = 1
COALESCE = 2
REJECT_WRITES = 3

LEVEL_NAMES = ("normal", "shed-background", "coalesce", "reject-writes")

# advisory flush-cadence multiplier per level: hosts that own their flush
# cadence (loadgen, external drivers) widen the tick by this factor
FLUSH_SCALE = (1.0, 2.0, 4.0, 4.0)


class BrownoutController:
    """Hysteresis-gated level ladder driven by ``observe(target)``.

    ``observe`` is called once per controller tick with the *target*
    level the raw overload signals currently point at; the controller
    steps its actual level toward the target at most one rung per call,
    after the streak thresholds are met.
    """

    def __init__(
        self,
        up_ticks: int = 2,
        down_ticks: int = 8,
        on_transition: Optional[Callable[[int, int, str, int], None]] = None,
        history: int = 64,
    ) -> None:
        self.up_ticks = max(1, int(up_ticks))
        self.down_ticks = max(1, int(down_ticks))
        self.on_transition = on_transition
        self.level = NORMAL
        self.ticks_at_level = 0
        self.n_transitions = 0
        self.transitions: deque = deque(maxlen=max(1, int(history)))
        self._tick = 0
        self._up_streak = 0
        self._down_streak = 0

    @property
    def level_name(self) -> str:
        return LEVEL_NAMES[self.level]

    def observe(self, target: int, reason: str = "") -> int:
        """Advance one tick with the signal-derived target level."""
        target = max(NORMAL, min(REJECT_WRITES, int(target)))
        self._tick += 1
        self.ticks_at_level += 1
        if target > self.level:
            self._down_streak = 0
            self._up_streak += 1
            if self._up_streak >= self.up_ticks:
                self._step(self.level + 1, reason or "overload")
        elif target < self.level:
            self._up_streak = 0
            self._down_streak += 1
            if self._down_streak >= self.down_ticks:
                self._step(self.level - 1, reason or "recovered")
        else:
            self._up_streak = 0
            self._down_streak = 0
        return self.level

    def _step(self, new_level: int, reason: str) -> None:
        old = self.level
        self.level = new_level
        self.ticks_at_level = 0
        self._up_streak = 0
        self._down_streak = 0
        self.n_transitions += 1
        self.transitions.append(
            {
                "tick": self._tick,
                "from": LEVEL_NAMES[old],
                "to": LEVEL_NAMES[new_level],
                "reason": reason,
            }
        )
        if self.on_transition is not None:
            self.on_transition(old, new_level, reason, self._tick)

    def force(self, level: int, reason: str = "forced") -> None:
        """Jump directly to a level (recovery/testing); still journaled."""
        level = max(NORMAL, min(REJECT_WRITES, int(level)))
        if level != self.level:
            self._step(level, reason)

    def snapshot(self) -> dict:
        return {
            "level": self.level,
            "level_name": self.level_name,
            "ticks_at_level": self.ticks_at_level,
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
            "n_transitions": self.n_transitions,
            "transitions": list(self.transitions),
        }
