"""Fleet-wide admission controller (ISSUE 10 tentpole).

One ``AdmissionController`` instance is shared by every provider in a
fleet (the :class:`~yjs_tpu.fleet.FleetRouter` creates it and injects it
into each shard), so per-tenant token buckets and the brownout level are
*fleet-wide*: a hot tenant hammering shard 3 is throttled on shard 0 too.
A standalone :class:`~yjs_tpu.provider.TpuProvider` gets a private one.

Responsibilities:

- **Rate limiting** — per-tenant and per-doc token buckets at the
  provider seam (``receive_update`` / ``handle_sync_message`` / session
  DATA).  Over-rate traffic is *queued* (weighted-fair, per tenant)
  rather than dropped; queued entries are WAL-journaled at enqueue time,
  so a crash cannot lose an acked update (they enter the SLO window only
  when drained — intentionally-shed traffic must not page the
  interactive SLO the brownout reads as its own signal).  When the
  queue itself fills — or brownout reaches ``reject-writes`` — the caller
  gets a typed :class:`AdmissionRejected` (session paths turn it into a
  BUSY/retry-after envelope frame; it is never silently dropped).
- **Brownout** — a per-tick :class:`BrownoutController` driven by the
  worst attached provider's SLO burn-rate verdict, flush-queue depth,
  device-slot occupancy, admission-queue fill and provider/fleet-full
  events.  Level transitions are journaled (``KIND_ADM`` WAL records on
  every attached provider) and metered.
- **Memory pressure** — before ``ProviderFullError`` can surface on a
  tiered provider, the tick loop calls ``tiers.make_room()`` to keep a
  configured free-slot headroom, demoting the coldest docs first.

Everything is tick-deterministic: the controller owns a tick counter
advanced by exactly one driver (the fleet router when present, else the
first attached provider's ``tick_sessions``), and buckets refill lazily
from tick deltas.  Default off (``YTPU_ADM_ENABLED``): with admission
disabled every seam check is a single attribute read.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from ..obs import global_registry
from .brownout import (
    COALESCE,
    FLUSH_SCALE,
    LEVEL_NAMES,
    NORMAL,
    REJECT_WRITES,
    SHED_BACKGROUND,
    BrownoutController,
)
from .limiter import AdmissionRejected, TokenBucket, WeightedFairQueue

__all__ = ["AdmissionConfig", "AdmissionController", "AdmissionRejected"]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class AdmissionConfig:
    """Admission/brownout knobs (env-derived defaults, constructor wins).

    - ``YTPU_ADM_ENABLED`` — master switch (default off: every seam
      check degenerates to one attribute read);
    - ``YTPU_ADM_TENANT_RATE`` / ``YTPU_ADM_TENANT_BURST`` — per-tenant
      token bucket: sustained updates/tick and burst depth (64 / 256);
    - ``YTPU_ADM_DOC_RATE`` / ``YTPU_ADM_DOC_BURST`` — per-doc bucket
      (32 / 128) so one hot doc cannot spend its tenant's whole budget;
    - ``YTPU_ADM_QUEUE_MAX`` — fleet-wide cap on weighted-fair-queued
      updates before ``queue-full`` rejections start (1024);
    - ``YTPU_ADM_DRAIN_BATCH`` — queued updates integrated per provider
      flush, in weighted-fair order (256);
    - ``YTPU_ADM_UP_TICKS`` / ``YTPU_ADM_DOWN_TICKS`` — brownout
      hysteresis: consecutive overloaded ticks to escalate one level
      (2) / calm ticks to recover one level (8 — recovery is slow on
      purpose so it cannot flap);
    - ``YTPU_ADM_QUEUE_HIGH`` — queue-fill fraction that targets
      ``coalesce`` (0.5); ``YTPU_ADM_QUEUE_FULL`` — fraction that
      targets ``reject-writes`` (0.95);
    - ``YTPU_ADM_PENDING_HIGH`` — flush-queue pending-update depth that
      targets ``shed-background`` (4096);
    - ``YTPU_ADM_OCCUPANCY_HIGH`` — device-slot occupancy that targets
      ``shed-background`` and arms tiering demotion (0.9);
    - ``YTPU_ADM_HEADROOM`` — free device slots the tick loop maintains
      via ``tiers.make_room()`` under pressure (1);
    - ``YTPU_ADM_RETRY_AFTER`` — retry-after ticks carried by
      rejections and BUSY frames (8).
    """

    __slots__ = (
        "enabled", "tenant_rate", "tenant_burst", "doc_rate", "doc_burst",
        "queue_max", "drain_batch", "up_ticks", "down_ticks",
        "queue_high", "queue_full", "pending_high", "occupancy_high",
        "headroom", "retry_after",
    )

    def __init__(
        self,
        enabled: bool | None = None,
        tenant_rate: float | None = None,
        tenant_burst: float | None = None,
        doc_rate: float | None = None,
        doc_burst: float | None = None,
        queue_max: int | None = None,
        drain_batch: int | None = None,
        up_ticks: int | None = None,
        down_ticks: int | None = None,
        queue_high: float | None = None,
        queue_full: float | None = None,
        pending_high: int | None = None,
        occupancy_high: float | None = None,
        headroom: int | None = None,
        retry_after: int | None = None,
    ):
        if enabled is None:
            enabled = os.environ.get("YTPU_ADM_ENABLED", "0") in (
                "1", "true", "yes",
            )
        self.enabled = bool(enabled)
        if tenant_rate is None:
            tenant_rate = _env_float("YTPU_ADM_TENANT_RATE", 64.0)
        self.tenant_rate = max(0.0, float(tenant_rate))
        if tenant_burst is None:
            tenant_burst = _env_float("YTPU_ADM_TENANT_BURST", 256.0)
        self.tenant_burst = max(1.0, float(tenant_burst))
        if doc_rate is None:
            doc_rate = _env_float("YTPU_ADM_DOC_RATE", 32.0)
        self.doc_rate = max(0.0, float(doc_rate))
        if doc_burst is None:
            doc_burst = _env_float("YTPU_ADM_DOC_BURST", 128.0)
        self.doc_burst = max(1.0, float(doc_burst))
        if queue_max is None:
            queue_max = _env_int("YTPU_ADM_QUEUE_MAX", 1024)
        self.queue_max = max(0, int(queue_max))
        if drain_batch is None:
            drain_batch = _env_int("YTPU_ADM_DRAIN_BATCH", 256)
        self.drain_batch = max(1, int(drain_batch))
        if up_ticks is None:
            up_ticks = _env_int("YTPU_ADM_UP_TICKS", 2)
        self.up_ticks = max(1, int(up_ticks))
        if down_ticks is None:
            down_ticks = _env_int("YTPU_ADM_DOWN_TICKS", 8)
        self.down_ticks = max(1, int(down_ticks))
        if queue_high is None:
            queue_high = _env_float("YTPU_ADM_QUEUE_HIGH", 0.5)
        self.queue_high = min(1.0, max(0.0, float(queue_high)))
        if queue_full is None:
            queue_full = _env_float("YTPU_ADM_QUEUE_FULL", 0.95)
        self.queue_full = min(1.0, max(self.queue_high, float(queue_full)))
        if pending_high is None:
            pending_high = _env_int("YTPU_ADM_PENDING_HIGH", 4096)
        self.pending_high = max(1, int(pending_high))
        if occupancy_high is None:
            occupancy_high = _env_float("YTPU_ADM_OCCUPANCY_HIGH", 0.9)
        self.occupancy_high = min(1.0, max(0.0, float(occupancy_high)))
        if headroom is None:
            headroom = _env_int("YTPU_ADM_HEADROOM", 1)
        self.headroom = max(0, int(headroom))
        if retry_after is None:
            retry_after = _env_int("YTPU_ADM_RETRY_AFTER", 8)
        self.retry_after = max(1, int(retry_after))

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class AdmissionMetrics:
    """``ytpu_adm_*`` families; registration is idempotent, so a shared
    controller on the global registry is safe."""

    def __init__(self, registry=None) -> None:
        r = registry if registry is not None else global_registry()
        self.registry = r
        self.level = r.gauge(
            "ytpu_adm_brownout_level",
            "Current brownout degradation level "
            "(0=normal 1=shed-background 2=coalesce 3=reject-writes)",
        )
        self.transitions = r.counter(
            "ytpu_adm_transitions_total",
            "Brownout level transitions, labeled by entered level",
            labelnames=("level",),
        )
        self.admitted = r.counter(
            "ytpu_adm_admitted_total",
            "Updates accepted by admission control, by disposition "
            "(admit=straight through, queued=weighted-fair queue)",
            labelnames=("disposition",),
        )
        self.rejected = r.counter(
            "ytpu_adm_rejected_total",
            "Updates refused by admission control, by typed reason",
            labelnames=("reason",),
        )
        self.queue_depth = r.gauge(
            "ytpu_adm_queue_depth",
            "Updates currently held in the weighted-fair admission queue",
        )
        self.drained = r.counter(
            "ytpu_adm_drained_total",
            "Queued updates integrated by provider flush drains",
        )
        self.demotions = r.counter(
            "ytpu_adm_demotions_total",
            "Tiering demotions forced by admission memory-pressure "
            "headroom maintenance",
        )
        self.full_events = r.counter(
            "ytpu_adm_full_events_total",
            "ProviderFullError/FleetFullError events observed and "
            "absorbed by the admission layer",
            labelnames=("kind",),
        )


def _slo_state(provider) -> str:
    try:
        return provider.slo.state()
    except Exception:
        return "ok"


_STATE_RANK = {"ok": 0, "warning": 1, "page": 2}


class AdmissionController:
    """Shared admission/brownout state machine (see module docstring)."""

    def __init__(
        self,
        config: Optional[AdmissionConfig] = None,
        registry=None,
    ) -> None:
        self.config = config or AdmissionConfig()
        self.metrics = AdmissionMetrics(registry)
        self.brownout = BrownoutController(
            up_ticks=self.config.up_ticks,
            down_ticks=self.config.down_ticks,
            on_transition=self._on_transition,
        )
        self._tick = 0
        self._ticker: Any = None
        self._providers: list = []
        self._tenants: dict[str, TokenBucket] = {}
        self._docs: dict[str, TokenBucket] = {}
        self._weights: dict[str, float] = {}
        # per-provider WFQ sub-queues so each flush drains only its own
        # shard's backlog (keyed by id(); entries die with the provider)
        self._queues: dict[int, WeightedFairQueue] = {}
        self._queued_total = 0
        self._full_events = 0
        self._draining = False
        # plain-int counters kept alongside obs so snapshots work with
        # YTPU_OBS_DISABLED (same idiom as DeadLetterQueue)
        self.n_offered = 0
        self.n_admitted = 0
        self.n_queued = 0
        self.n_drained = 0
        self.n_rejected: dict[str, int] = {}
        self.n_demotions = 0
        self.n_full = {"provider": 0, "fleet": 0}

    # -- wiring ------------------------------------------------------------

    def attach(self, provider) -> None:
        """Register a provider; the first attached becomes the tick
        driver unless a fleet claims it via :meth:`claim_ticker`."""
        if provider not in self._providers:
            self._providers.append(provider)
        if self._ticker is None:
            self._ticker = provider

    def detach(self, provider) -> None:
        """Drop a (killed) provider; its in-memory queue entries are
        discarded — they were WAL-journaled and replicated at enqueue,
        so failover recovery replays them on the survivor."""
        if provider in self._providers:
            self._providers.remove(provider)
        q = self._queues.pop(id(provider), None)
        if q is not None:
            self._queued_total -= len(q)
            self.metrics.queue_depth.set(self._queued_total)
        if self._ticker is provider:
            self._ticker = self._providers[0] if self._providers else None

    def claim_ticker(self, owner) -> None:
        """A fleet router owns the tick (its ``tick()`` calls
        :meth:`tick` directly; shard ``tick_sessions`` become no-ops)."""
        self._ticker = owner

    def maybe_tick(self, caller) -> int:
        if caller is self._ticker:
            return self.tick()
        return self.brownout.level

    # -- level-effect properties (read by sessions and hosts) --------------

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    @property
    def level(self) -> int:
        return self.brownout.level

    @property
    def level_name(self) -> str:
        return self.brownout.level_name

    @property
    def antientropy_paused(self) -> bool:
        return self.config.enabled and self.brownout.level >= SHED_BACKGROUND

    @property
    def force_coalesce(self) -> bool:
        return self.config.enabled and self.brownout.level >= COALESCE

    @property
    def rejecting_writes(self) -> bool:
        return self.config.enabled and self.brownout.level >= REJECT_WRITES

    @property
    def flush_interval_scale(self) -> float:
        """Advisory flush-cadence multiplier for hosts that own their
        flush tick (loadgen, external drivers)."""
        if not self.config.enabled:
            return 1.0
        return FLUSH_SCALE[self.brownout.level]

    @property
    def retry_after(self) -> int:
        return self.config.retry_after

    # -- tenancy -----------------------------------------------------------

    @staticmethod
    def tenant_of(guid: str) -> str:
        """Tenant = guid prefix before the first ``/`` (whole guid when
        unscoped), matching the ``tenant/doc`` naming convention."""
        i = guid.find("/")
        return guid[:i] if i > 0 else guid

    def set_tenant_weight(self, tenant: str, weight: float) -> None:
        """Fair-share weight for queue drains (default 1.0; heavier
        drains proportionally faster)."""
        self._weights[tenant] = max(1e-6, float(weight))

    # -- admission seam ----------------------------------------------------

    def admit_update(self, provider, guid: str, nbytes: int) -> str:
        """Gate one inbound update.  Returns ``"admit"`` (integrate now)
        or ``"queue"`` (caller journals + enqueues via :meth:`enqueue`);
        raises :class:`AdmissionRejected` otherwise."""
        cfg = self.config
        if not cfg.enabled:
            return "admit"
        self.n_offered += 1
        tenant = self.tenant_of(guid)
        if self.brownout.level >= REJECT_WRITES:
            self._reject(guid, tenant, "reject-writes")
        tb = self._tenants.get(tenant)
        if tb is None:
            tb = self._tenants[tenant] = TokenBucket(
                cfg.tenant_rate, cfg.tenant_burst, self._tick
            )
        db = self._docs.get(guid)
        if db is None:
            db = self._docs[guid] = TokenBucket(
                cfg.doc_rate, cfg.doc_burst, self._tick
            )
        tb.refill_to(self._tick)
        db.refill_to(self._tick)
        if tb.peek() and db.peek():
            tb.take()
            db.take()
            self.n_admitted += 1
            self.metrics.admitted.labels(disposition="admit").inc()
            return "admit"
        # over rate: queue (weighted-fair) unless the queue is full
        if self._queued_total >= cfg.queue_max:
            self._reject(guid, tenant, "queue-full")
        self.n_queued += 1
        self.metrics.admitted.labels(disposition="queued").inc()
        return "queue"

    def _reject(self, guid: str, tenant: str, reason: str) -> None:
        self.n_rejected[reason] = self.n_rejected.get(reason, 0) + 1
        self.metrics.rejected.labels(reason=reason).inc()
        raise AdmissionRejected(guid, tenant, reason, self.config.retry_after)

    def enqueue(
        self,
        provider,
        guid: str,
        update: bytes,
        v2: bool,
        undoable: bool,
        slo_key,
        trace=None,
    ) -> None:
        """Park an already-journaled, SLO-received update for a later
        weighted-fair drain on ``provider``'s flush.  ``trace`` is the
        ingress :class:`~yjs_tpu.obs.dist.TraceContext` (ISSUE 11): it
        rides the queue entry with the enqueue tick so the drain can
        attribute the queue wait to the update's trace."""
        tenant = self.tenant_of(guid)
        q = self._queues.get(id(provider))
        if q is None:
            q = self._queues[id(provider)] = WeightedFairQueue()
        q.push(
            tenant,
            (guid, update, v2, undoable, slo_key, trace, self._tick),
            weight=self._weights.get(tenant, 1.0),
        )
        self._queued_total += 1
        self.metrics.queue_depth.set(self._queued_total)

    def drain_for(self, provider) -> int:
        """Integrate up to ``drain_batch`` queued updates for this
        provider, oldest virtual-finish first.  Called from
        ``provider.flush()``; re-entrant calls (flush inside a drain's
        tiering demotion) are no-ops."""
        if self._draining:
            return 0
        q = self._queues.get(id(provider))
        if not q:
            return 0
        from ..obs.dist import use_context

        n = 0
        self._draining = True
        try:
            while len(q) and n < self.config.drain_batch:
                _tenant, item = q.pop()
                self._queued_total -= 1
                n += 1
                guid, update, v2, undoable, slo_key, trace, enq_tick = item
                if trace is not None and trace.sampled:
                    # the queue-wait span of the sampled trace: ticks
                    # parked in the weighted-fair queue before this
                    # drain picked the update up
                    provider.engine.obs.tracer.instant(
                        "ytpu.adm.queue_wait",
                        guid=guid,
                        trace=trace.trace_hex,
                        wait_ticks=max(0, self._tick - enq_tick),
                    )
                with use_context(trace):
                    provider._integrate_admitted(
                        guid, update, v2, undoable, slo_key
                    )
        finally:
            self._draining = False
            if n:
                self.n_drained += n
                self.metrics.drained.inc(n)
                self.metrics.queue_depth.set(self._queued_total)
        return n

    def note_full(self, kind: str = "provider") -> None:
        """Feed a Provider/Fleet-full event into the brownout signal
        (counted even when admission is disabled)."""
        self._full_events += 1
        self.n_full[kind] = self.n_full.get(kind, 0) + 1
        self.metrics.full_events.labels(kind=kind).inc()

    # -- tick / brownout ---------------------------------------------------

    def _signals(self) -> dict:
        slo = "ok"
        pending = 0
        occupancy = 0.0
        for p in self._providers:
            st = _slo_state(p)
            if _STATE_RANK.get(st, 0) > _STATE_RANK.get(slo, 0):
                slo = st
            try:
                fm = p.engine.last_flush_metrics
                if fm:
                    pending = max(pending, int(fm.get("pending_depth", 0)))
                occupancy = max(occupancy, float(p.occupancy))
            except Exception:
                continue
        queue_frac = (
            self._queued_total / self.config.queue_max
            if self.config.queue_max
            else 0.0
        )
        return {
            "slo": slo,
            "pending_depth": pending,
            "occupancy": occupancy,
            "queue_frac": queue_frac,
            "full_events": self._full_events,
        }

    def _target_level(self, s: dict) -> tuple[int, str]:
        cfg = self.config
        target, reason = NORMAL, ""
        if s["slo"] == "warning":
            target, reason = SHED_BACKGROUND, "slo-warning"
        if s["pending_depth"] >= cfg.pending_high:
            target, reason = (
                max(target, SHED_BACKGROUND),
                reason or "flush-backlog",
            )
        if s["occupancy"] >= cfg.occupancy_high:
            target, reason = (
                max(target, SHED_BACKGROUND),
                reason or "memory-pressure",
            )
        if s["slo"] == "page":
            target, reason = COALESCE, "slo-page"
        if s["queue_frac"] >= cfg.queue_high:
            target, reason = max(target, COALESCE), "queue-high"
        if s["full_events"] > 0:
            target, reason = max(target, COALESCE), "full-events"
        if s["queue_frac"] >= cfg.queue_full:
            target, reason = REJECT_WRITES, "queue-full"
        return target, reason

    def tick(self) -> int:
        """Advance one tick: refill clocks, evaluate brownout signals,
        and relieve memory pressure via tiering demotion."""
        self._tick += 1
        cfg = self.config
        if not cfg.enabled:
            return NORMAL
        s = self._signals()
        target, reason = self._target_level(s)
        level = self.brownout.observe(target, reason)
        self.metrics.level.set(level)
        self._full_events = 0
        # memory pressure: demote coldest docs to keep free-slot headroom
        # so ProviderFullError never surfaces on a tiered provider
        if cfg.headroom and (
            level >= SHED_BACKGROUND or s["occupancy"] >= cfg.occupancy_high
        ):
            for p in self._providers:
                self._make_headroom(p)
        return level

    def _make_headroom(self, provider) -> None:
        try:
            tiers = provider.tiers
            if not tiers.enabled:
                return
            n_docs = provider.engine.n_docs
            free = len(provider._free) + max(0, n_docs - provider._next)
            while free < self.config.headroom:
                if not tiers.make_room():
                    return
                self.n_demotions += 1
                self.metrics.demotions.inc()
                free = len(provider._free) + max(0, n_docs - provider._next)
        except Exception:
            return

    # -- introspection -----------------------------------------------------

    def queue_depth(self) -> int:
        return self._queued_total

    def snapshot(self) -> dict:
        by_tenant: dict[str, int] = {}
        for q in self._queues.values():
            for t, n in q.snapshot()["by_tenant"].items():
                by_tenant[t] = by_tenant.get(t, 0) + n
        return {
            "enabled": self.config.enabled,
            "tick": self._tick,
            "level": self.brownout.level,
            "level_name": self.brownout.level_name,
            "queue_depth": self._queued_total,
            "queue_max": self.config.queue_max,
            "queued_by_tenant": dict(sorted(by_tenant.items())),
            "tenants": len(self._tenants),
            "offered": self.n_offered,
            "admitted": self.n_admitted,
            "queued": self.n_queued,
            "drained": self.n_drained,
            "rejected": dict(sorted(self.n_rejected.items())),
            "demotions": self.n_demotions,
            "full_events": dict(self.n_full),
            "brownout": self.brownout.snapshot(),
        }

    # -- journaling --------------------------------------------------------

    def _on_transition(
        self, old: int, new: int, reason: str, tick: int
    ) -> None:
        self.metrics.transitions.labels(level=LEVEL_NAMES[new]).inc()
        self.metrics.level.set(new)
        # brownout transitions are flight-recorder material (ISSUE 11):
        # a post-mortem must see the degradation ladder around a failure
        from ..obs.blackbox import flight_recorder

        flight_recorder().record(
            "admission", "brownout_transition",
            severity="warning" if new > old else "info",
            level=LEVEL_NAMES[new], previous=LEVEL_NAMES[old],
            reason=reason, tick=tick,
        )
        for p in self._providers:
            try:
                journal = getattr(p, "journal_admission", None)
                if journal is not None:
                    journal(LEVEL_NAMES[new], reason, tick)
            except Exception:
                continue
