"""Core CRDT runtime: identity structs, store, transactions, documents.

This is the CPU reference implementation of the YATA list CRDT with compound
(run-length) items, semantically equivalent to the reference JavaScript
implementation (yjs v13.4.9 @ /root/reference):

- Item / GC structs ............ reference src/structs/Item.js, GC.js
- Content classes .............. reference src/structs/Content*.js
- StructStore .................. reference src/utils/StructStore.js
- DeleteSet .................... reference src/utils/DeleteSet.js
- Transaction / transact ....... reference src/utils/Transaction.js
- Doc .......................... reference src/utils/Doc.js

It doubles as the conformance oracle for the TPU batch engine in
``yjs_tpu/ops`` (the same role the JS path plays for the reference's
north-star provider design, see BASELINE.json).
"""

from __future__ import annotations

import random as _random

from .ids import ID, compare_ids, create_id, find_root_type_key
from .lib0.binary import BIT1, BIT2, BIT3, BIT4, BIT6, BIT7, BIT8, BITS5
from .lib0.encoding import UNDEFINED
from .lib0.observable import Observable
from .lib0.u16 import from_u16

# ---------------------------------------------------------------------------
# Event handler (reference src/utils/EventHandler.js)
# ---------------------------------------------------------------------------


class EventHandler:
    __slots__ = ("l",)

    def __init__(self):
        self.l = []


def create_event_handler() -> EventHandler:
    return EventHandler()


def add_event_handler_listener(handler: EventHandler, f) -> None:
    handler.l.append(f)


def remove_event_handler_listener(handler: EventHandler, f) -> None:
    try:
        handler.l.remove(f)
    except ValueError:
        pass


def call_all(fs, args, i=0):
    """Call every function even if some throw (the last error propagates),
    processing entries appended during iteration (lib0/function.callAll)."""
    try:
        while i < len(fs):
            fs[i](*args)
            i += 1
    finally:
        if i < len(fs):
            call_all(fs, args, i + 1)


def call_event_handler_listeners(handler: EventHandler, arg0, arg1) -> None:
    call_all(list(handler.l), [arg0, arg1])


# ---------------------------------------------------------------------------
# Struct base + GC (reference src/structs/AbstractStruct.js, GC.js)
# ---------------------------------------------------------------------------

GC_STRUCT_REF = 0


class AbstractStruct:
    """Struct contract shared by :class:`GC` and :class:`Item` (reference
    src/structs/AbstractStruct.js:10-45).  The concrete structs implement
    the whole surface themselves (``id``/``length``/``deleted``,
    ``merge_with``, ``integrate``, ``write``, ``get_missing``) — this base
    is the exported contract (reference src/index.js:17), carrying no
    state (``__slots__ = ()``) so it costs nothing at runtime."""

    __slots__ = ()

    def merge_with(self, right) -> bool:  # pragma: no cover - contract
        raise NotImplementedError

    def integrate(self, transaction, offset: int) -> None:  # pragma: no cover
        raise NotImplementedError

    def write(self, encoder, offset: int) -> None:  # pragma: no cover
        raise NotImplementedError

    def get_missing(self, transaction, store):  # pragma: no cover
        raise NotImplementedError


class GC(AbstractStruct):
    """Length-only tombstone struct; always deleted, merges unconditionally."""

    __slots__ = ("id", "length")

    def __init__(self, id: ID, length: int):
        self.id = id
        self.length = length

    deleted = True

    def delete(self, transaction) -> None:
        pass

    def merge_with(self, right: "GC") -> bool:
        self.length += right.length
        return True

    def integrate(self, transaction: "Transaction", offset: int) -> None:
        if offset > 0:
            self.id = create_id(self.id.client, self.id.clock + offset)
            self.length -= offset
        add_struct(transaction.doc.store, self)

    def write(self, encoder, offset: int) -> None:
        encoder.write_info(GC_STRUCT_REF)
        encoder.write_len(self.length - offset)

    def get_missing(self, transaction, store) -> int | None:
        return None


# ---------------------------------------------------------------------------
# Content classes (reference src/structs/Content*.js)
# ---------------------------------------------------------------------------


class ContentDeleted:
    """Ref 1: length-only content of an already-deleted item."""

    __slots__ = ("len",)
    REF = 1
    countable = False

    def __init__(self, ln: int):
        self.len = ln

    def get_length(self) -> int:
        return self.len

    def get_content(self):
        return []

    def copy(self):
        return ContentDeleted(self.len)

    def splice(self, offset: int):
        right = ContentDeleted(self.len - offset)
        self.len = offset
        return right

    def merge_with(self, right) -> bool:
        self.len += right.len
        return True

    def integrate(self, transaction, item) -> None:
        add_to_delete_set(transaction.delete_set, item.id.client, item.id.clock, self.len)
        item.mark_deleted()

    def delete(self, transaction) -> None:
        pass

    def gc(self, store) -> None:
        pass

    def write(self, encoder, offset: int) -> None:
        encoder.write_len(self.len - offset)


def read_content_deleted(decoder):
    return ContentDeleted(decoder.read_len())


class ContentJSON:
    """Ref 2: legacy JSON-string-encoded array content."""

    __slots__ = ("arr",)
    REF = 2
    countable = True

    def __init__(self, arr: list):
        self.arr = arr

    def get_length(self) -> int:
        return len(self.arr)

    def get_content(self):
        return self.arr

    def copy(self):
        return ContentJSON(self.arr)

    def splice(self, offset: int):
        right = ContentJSON(self.arr[offset:])
        self.arr = self.arr[:offset]
        return right

    def merge_with(self, right) -> bool:
        self.arr = self.arr + right.arr
        return True

    def integrate(self, transaction, item) -> None:
        pass

    def delete(self, transaction) -> None:
        pass

    def gc(self, store) -> None:
        pass

    def write(self, encoder, offset: int) -> None:
        encoder.write_len(len(self.arr) - offset)
        for i in range(offset, len(self.arr)):
            c = self.arr[i]
            encoder.write_string("undefined" if c is UNDEFINED else _json_stringify(c))


def read_content_json(decoder):
    cs = []
    for _ in range(decoder.read_len()):
        c = decoder.read_string()
        cs.append(UNDEFINED if c == "undefined" else _json_parse(c))
    return ContentJSON(cs)


class ContentBinary:
    """Ref 3: a single Uint8Array payload (length always 1)."""

    __slots__ = ("content",)
    REF = 3
    countable = True

    def __init__(self, content: bytes):
        self.content = content

    def get_length(self) -> int:
        return 1

    def get_content(self):
        return [self.content]

    def copy(self):
        return ContentBinary(self.content)

    def splice(self, offset: int):
        raise NotImplementedError

    def merge_with(self, right) -> bool:
        return False

    def integrate(self, transaction, item) -> None:
        pass

    def delete(self, transaction) -> None:
        pass

    def gc(self, store) -> None:
        pass

    def write(self, encoder, offset: int) -> None:
        encoder.write_buf(self.content)


def read_content_binary(decoder):
    return ContentBinary(decoder.read_buf())


class ContentString:
    """Ref 4: a text run.  ``str`` is stored in u16 form (see lib0/u16.py);
    splitting guards surrogate pairs by substituting U+FFFD
    (reference src/structs/ContentString.js:51-66)."""

    __slots__ = ("str",)
    REF = 4
    countable = True

    def __init__(self, s: str):
        self.str = s

    def get_length(self) -> int:
        return len(self.str)

    def get_content(self):
        return list(self.str)

    def copy(self):
        return ContentString(self.str)

    def splice(self, offset: int):
        right = ContentString(self.str[offset:])
        self.str = self.str[:offset]
        last = self.str[offset - 1] if offset > 0 else ""
        if last and 0xD800 <= ord(last) <= 0xDBFF:
            # never split a surrogate pair: replace both halves with U+FFFD
            self.str = self.str[: offset - 1] + "�"
            right.str = "�" + right.str[1:]
        return right

    def merge_with(self, right) -> bool:
        self.str += right.str
        return True

    def integrate(self, transaction, item) -> None:
        pass

    def delete(self, transaction) -> None:
        pass

    def gc(self, store) -> None:
        pass

    def write(self, encoder, offset: int) -> None:
        encoder.write_string(self.str if offset == 0 else self.str[offset:])


def read_content_string(decoder):
    return ContentString(decoder.read_string())


class ContentEmbed:
    """Ref 5: one embedded JSON object inside rich text."""

    __slots__ = ("embed",)
    REF = 5
    countable = True

    def __init__(self, embed):
        self.embed = embed

    def get_length(self) -> int:
        return 1

    def get_content(self):
        return [self.embed]

    def copy(self):
        return ContentEmbed(self.embed)

    def splice(self, offset: int):
        raise NotImplementedError

    def merge_with(self, right) -> bool:
        return False

    def integrate(self, transaction, item) -> None:
        pass

    def delete(self, transaction) -> None:
        pass

    def gc(self, store) -> None:
        pass

    def write(self, encoder, offset: int) -> None:
        encoder.write_json(self.embed)


def read_content_embed(decoder):
    return ContentEmbed(decoder.read_json())


class ContentFormat:
    """Ref 6: rich-text formatting marker; not countable
    (reference src/structs/ContentFormat.js:38-40)."""

    __slots__ = ("key", "value")
    REF = 6
    countable = False

    def __init__(self, key: str, value):
        self.key = key
        self.value = value

    def get_length(self) -> int:
        return 1

    def get_content(self):
        return []

    def copy(self):
        return ContentFormat(self.key, self.value)

    def splice(self, offset: int):
        raise NotImplementedError

    def merge_with(self, right) -> bool:
        return False

    def integrate(self, transaction, item) -> None:
        # formats invalidate the parent's search-marker index
        item.parent._search_marker = None

    def delete(self, transaction) -> None:
        pass

    def gc(self, store) -> None:
        pass

    def write(self, encoder, offset: int) -> None:
        encoder.write_key(self.key)
        encoder.write_json(self.value)


def read_content_format(decoder):
    return ContentFormat(decoder.read_string(), decoder.read_json())


# type-ref dispatch registry, filled by yjs_tpu.types at import time
# (reference src/structs/ContentType.js:19-35)
type_refs: list = [None] * 7

YARRAY_REF_ID = 0
YMAP_REF_ID = 1
YTEXT_REF_ID = 2
YXML_ELEMENT_REF_ID = 3
YXML_FRAGMENT_REF_ID = 4
YXML_HOOK_REF_ID = 5
YXML_TEXT_REF_ID = 6


class ContentType:
    """Ref 7: nests a shared type inside an item."""

    __slots__ = ("type",)
    REF = 7
    countable = True

    def __init__(self, type_):
        self.type = type_

    def get_length(self) -> int:
        return 1

    def get_content(self):
        return [self.type]

    def copy(self):
        return ContentType(self.type._copy())

    def splice(self, offset: int):
        raise NotImplementedError

    def merge_with(self, right) -> bool:
        return False

    def integrate(self, transaction, item) -> None:
        self.type._integrate(transaction.doc, item)

    def delete(self, transaction) -> None:
        # recursively delete children; already-deleted ones become merge
        # candidates (reference src/structs/ContentType.js:106-129)
        item = self.type._start
        while item is not None:
            if not item.deleted:
                item.delete(transaction)
            else:
                transaction._merge_structs.append(item)
            item = item.right
        for item in self.type._map.values():
            if not item.deleted:
                item.delete(transaction)
            else:
                transaction._merge_structs.append(item)
        transaction.changed.pop(self.type, None)

    def gc(self, store) -> None:
        item = self.type._start
        while item is not None:
            item.gc(store, True)
            item = item.right
        self.type._start = None
        for item in self.type._map.values():
            while item is not None:
                item.gc(store, True)
                item = item.left
        self.type._map = {}

    def write(self, encoder, offset: int) -> None:
        self.type._write(encoder)


def read_content_type(decoder):
    return ContentType(type_refs[decoder.read_type_ref()](decoder))


class ContentAny:
    """Ref 8: default content — an array of arbitrary JSON-ish values."""

    __slots__ = ("arr",)
    REF = 8
    countable = True

    def __init__(self, arr: list):
        self.arr = arr

    def get_length(self) -> int:
        return len(self.arr)

    def get_content(self):
        return self.arr

    def copy(self):
        return ContentAny(self.arr)

    def splice(self, offset: int):
        right = ContentAny(self.arr[offset:])
        self.arr = self.arr[:offset]
        return right

    def merge_with(self, right) -> bool:
        self.arr = self.arr + right.arr
        return True

    def integrate(self, transaction, item) -> None:
        pass

    def delete(self, transaction) -> None:
        pass

    def gc(self, store) -> None:
        pass

    def write(self, encoder, offset: int) -> None:
        encoder.write_len(len(self.arr) - offset)
        for i in range(offset, len(self.arr)):
            encoder.write_any(self.arr[i])


def read_content_any(decoder):
    return ContentAny([decoder.read_any() for _ in range(decoder.read_len())])


class ContentDoc:
    """Ref 9: subdocument embedding (reference src/structs/ContentDoc.js)."""

    __slots__ = ("doc", "opts")
    REF = 9
    countable = True

    def __init__(self, doc: "Doc"):
        if doc._item is not None:
            raise RuntimeError(
                "This document was already integrated as a sub-document. "
                "Create a second instance with the same guid instead."
            )
        self.doc = doc
        opts = {}
        if not doc.gc:
            opts["gc"] = False
        if doc.auto_load:
            opts["autoLoad"] = True
        if doc.meta is not None:
            opts["meta"] = doc.meta
        self.opts = opts

    def get_length(self) -> int:
        return 1

    def get_content(self):
        return [self.doc]

    def copy(self):
        return ContentDoc(self.doc)

    def splice(self, offset: int):
        raise NotImplementedError

    def merge_with(self, right) -> bool:
        return False

    def integrate(self, transaction, item) -> None:
        self.doc._item = item
        transaction.subdocs_added.add(self.doc)
        if self.doc.should_load:
            transaction.subdocs_loaded.add(self.doc)

    def delete(self, transaction) -> None:
        if self.doc in transaction.subdocs_added:
            transaction.subdocs_added.discard(self.doc)
        else:
            transaction.subdocs_removed.add(self.doc)

    def gc(self, store) -> None:
        pass

    def write(self, encoder, offset: int) -> None:
        encoder.write_string(self.doc.guid)
        encoder.write_any(self.opts)


def read_content_doc(decoder):
    guid = decoder.read_string()
    opts = decoder.read_any() or {}
    kwargs = {"guid": guid}
    kwargs.update(_opts_to_kwargs(opts))
    return ContentDoc(Doc(**kwargs))


# content-ref dispatch table (reference src/structs/Item.js:672-683)
content_refs = [
    None,  # 0 is the GC struct ref, not an item content
    read_content_deleted,
    read_content_json,
    read_content_binary,
    read_content_string,
    read_content_embed,
    read_content_format,
    read_content_type,
    read_content_any,
    read_content_doc,
]


def read_item_content(decoder, info: int):
    return content_refs[info & BITS5](decoder)


# ---------------------------------------------------------------------------
# Item (reference src/structs/Item.js:232-659)
# ---------------------------------------------------------------------------


class Item(AbstractStruct):
    """THE core struct: a run of content with YATA integration pointers.

    ``info`` bitfield: BIT1 keep, BIT2 countable, BIT3 deleted, BIT4 marker.
    """

    __slots__ = (
        "id",
        "length",
        "origin",
        "left",
        "right",
        "right_origin",
        "parent",
        "parent_sub",
        "redone",
        "content",
        "info",
    )

    def __init__(self, id, left, origin, right, right_origin, parent, parent_sub, content):
        self.id = id
        self.length = content.get_length()
        self.origin = origin
        self.left = left
        self.right = right
        self.right_origin = right_origin
        self.parent = parent
        self.parent_sub = parent_sub
        self.redone = None
        self.content = content
        self.info = BIT2 if content.countable else 0

    # -- info bits ----------------------------------------------------------

    @property
    def marker(self) -> bool:
        return (self.info & BIT4) > 0

    @marker.setter
    def marker(self, is_marked: bool) -> None:
        if ((self.info & BIT4) > 0) != is_marked:
            self.info ^= BIT4

    @property
    def keep(self) -> bool:
        return (self.info & BIT1) > 0

    @keep.setter
    def keep(self, do_keep: bool) -> None:
        if self.keep != do_keep:
            self.info ^= BIT1

    @property
    def countable(self) -> bool:
        return (self.info & BIT2) > 0

    @property
    def deleted(self) -> bool:
        return (self.info & BIT3) > 0

    @deleted.setter
    def deleted(self, do_delete: bool) -> None:
        if self.deleted != do_delete:
            self.info ^= BIT3

    def mark_deleted(self) -> None:
        self.info |= BIT3

    # -- causal dependencies ------------------------------------------------

    def get_missing(self, transaction, store) -> int | None:
        """Return the client of a missing causal dependency, or None after
        resolving origins into live left/right pointers
        (reference src/structs/Item.js:354-397)."""
        origin = self.origin
        if (
            origin is not None
            and origin.client != self.id.client
            and origin.clock >= get_state(store, origin.client)
        ):
            return origin.client
        right_origin = self.right_origin
        if (
            right_origin is not None
            and right_origin.client != self.id.client
            and right_origin.clock >= get_state(store, right_origin.client)
        ):
            return right_origin.client
        parent = self.parent
        if (
            parent is not None
            and type(parent) is ID
            and self.id.client != parent.client
            and parent.clock >= get_state(store, parent.client)
        ):
            return parent.client

        # all dependencies known; resolve them into pointers
        if origin is not None:
            self.left = get_item_clean_end(transaction, store, origin)
            # the origin may resolve into a GC run (tombstoned before this
            # item arrived); JS reads `.lastId` as undefined and the GC
            # check below degrades the item (reference Item.js:369-377)
            self.origin = (
                self.left.last_id if type(self.left) is Item else None
            )
        if right_origin is not None:
            self.right = get_item_clean_start(transaction, right_origin)
            self.right_origin = self.right.id
        if (self.left is not None and type(self.left) is GC) or (
            self.right is not None and type(self.right) is GC
        ):
            self.parent = None
        if self.parent is None:
            if self.left is not None and type(self.left) is Item:
                self.parent = self.left.parent
                self.parent_sub = self.left.parent_sub
            if self.right is not None and type(self.right) is Item:
                self.parent = self.right.parent
                self.parent_sub = self.right.parent_sub
        elif type(self.parent) is ID:
            parent_item = get_item(store, self.parent)
            if type(parent_item) is GC:
                self.parent = None
            else:
                # the parent item's content may have been replaced by
                # ContentDeleted; JS reads `.type` as undefined and the item
                # then integrates as a GC struct (reference Item.js:388-395)
                self.parent = getattr(parent_item.content, "type", None)
        return None

    # -- YATA integration ---------------------------------------------------

    def integrate(self, transaction, offset: int) -> None:
        """Insert this item into its parent's list, resolving concurrent
        inserts by the YATA rules (reference src/structs/Item.js:403-517)."""
        if offset > 0:
            self.id = create_id(self.id.client, self.id.clock + offset)
            self.left = get_item_clean_end(
                transaction, transaction.doc.store, create_id(self.id.client, self.id.clock - 1)
            )
            # the known prefix may have been replaced by a GC run; JS reads
            # `.lastId` as undefined and proceeds (reference Item.js:404-409)
            self.origin = (
                self.left.last_id if type(self.left) is Item else None
            )
            self.content = self.content.splice(offset)
            self.length -= offset

        parent = self.parent
        if parent is not None:
            if (self.left is None and (self.right is None or self.right.left is not None)) or (
                self.left is not None and self.left.right is not self.right
            ):
                left = self.left
                # find the first potentially conflicting item
                if left is not None:
                    o = left.right
                elif self.parent_sub is not None:
                    o = parent._map.get(self.parent_sub)
                    while o is not None and o.left is not None:
                        o = o.left
                else:
                    o = parent._start
                conflicting_items = set()
                items_before_origin = set()
                # Let c in conflicting_items, b in items_before_origin:
                # ***{origin}bbbb{this}{c,b}{c,b}{o}***
                this_origin = self.origin
                this_client = self.id.client
                store = transaction.doc.store
                while o is not None and o is not self.right:
                    items_before_origin.add(o)
                    conflicting_items.add(o)
                    if compare_ids(this_origin, o.origin):
                        # case 1: same origin — lower client id goes left
                        if o.id.client < this_client:
                            left = o
                            conflicting_items.clear()
                        elif compare_ids(self.right_origin, o.right_origin):
                            # same integration points: id decides; this goes
                            # to the left of o, so we are done
                            break
                    elif o.origin is not None and get_item(store, o.origin) in items_before_origin:
                        # case 2: o's origin is between origin and this
                        if get_item(store, o.origin) not in conflicting_items:
                            left = o
                            conflicting_items.clear()
                    else:
                        break
                    o = o.right
                self.left = left
            # reconnect left/right + update parent map/start
            if self.left is not None:
                right = self.left.right
                self.right = right
                self.left.right = self
            else:
                if self.parent_sub is not None:
                    r = parent._map.get(self.parent_sub)
                    while r is not None and r.left is not None:
                        r = r.left
                else:
                    r = parent._start
                    parent._start = self
                self.right = r
            if self.right is not None:
                self.right.left = self
            elif self.parent_sub is not None:
                # this is the new current attribute value of parent
                parent._map[self.parent_sub] = self
                if self.left is not None:
                    self.left.delete(transaction)
            if self.parent_sub is None and self.countable and not self.deleted:
                parent._length += self.length
            add_struct(transaction.doc.store, self)
            self.content.integrate(transaction, self)
            add_changed_type_to_transaction(transaction, parent, self.parent_sub)
            if (parent._item is not None and parent._item.deleted) or (
                self.parent_sub is not None and self.right is not None
            ):
                # delete if parent is deleted, or if this is not the current
                # attribute value of parent
                self.delete(transaction)
        else:
            # parent is not defined: integrate a GC struct instead
            GC(self.id, self.length).integrate(transaction, 0)

    # -- navigation ---------------------------------------------------------

    @property
    def next(self):
        n = self.right
        while n is not None and n.deleted:
            n = n.right
        return n

    @property
    def prev(self):
        n = self.left
        while n is not None and n.deleted:
            n = n.left
        return n

    @property
    def last_id(self) -> ID:
        return self.id if self.length == 1 else create_id(self.id.client, self.id.clock + self.length - 1)

    # -- run compaction -----------------------------------------------------

    def merge_with(self, right: "Item") -> bool:
        """Merge a directly adjacent right neighbour into this run
        (reference src/structs/Item.js:555-579)."""
        if (
            compare_ids(right.origin, self.last_id)
            and self.right is right
            and compare_ids(self.right_origin, right.right_origin)
            and self.id.client == right.id.client
            and self.id.clock + self.length == right.id.clock
            and self.deleted == right.deleted
            and self.redone is None
            and right.redone is None
            and type(self.content) is type(right.content)
            and self.content.merge_with(right.content)
        ):
            if right.keep:
                self.keep = True
            self.right = right.right
            if self.right is not None:
                self.right.left = self
            self.length += right.length
            return True
        return False

    def delete(self, transaction) -> None:
        if not self.deleted:
            parent = self.parent
            if self.countable and self.parent_sub is None:
                parent._length -= self.length
            self.mark_deleted()
            add_to_delete_set(transaction.delete_set, self.id.client, self.id.clock, self.length)
            add_changed_type_to_transaction(transaction, parent, self.parent_sub)
            self.content.delete(transaction)

    def gc(self, store, parent_gcd: bool) -> None:
        if not self.deleted:
            raise RuntimeError("cannot gc an undeleted item")
        self.content.gc(store)
        if parent_gcd:
            replace_struct(store, self, GC(self.id, self.length))
        else:
            self.content = ContentDeleted(self.length)

    # -- wire ---------------------------------------------------------------

    def write(self, encoder, offset: int) -> None:
        """Wire-encode (reference src/structs/Item.js:625-658)."""
        origin = create_id(self.id.client, self.id.clock + offset - 1) if offset > 0 else self.origin
        right_origin = self.right_origin
        parent_sub = self.parent_sub
        info = (
            (self.content.REF & BITS5)
            | (0 if origin is None else BIT8)
            | (0 if right_origin is None else BIT7)
            | (0 if parent_sub is None else BIT6)
        )
        encoder.write_info(info)
        if origin is not None:
            encoder.write_left_id(origin)
        if right_origin is not None:
            encoder.write_right_id(right_origin)
        if origin is None and right_origin is None:
            parent = self.parent
            parent_item = parent._item
            if parent_item is None:
                ykey = find_root_type_key(parent)
                encoder.write_parent_info(True)
                encoder.write_string(ykey)
            else:
                encoder.write_parent_info(False)
                encoder.write_left_id(parent_item.id)
            if parent_sub is not None:
                encoder.write_string(parent_sub)
        self.content.write(encoder, offset)


# -- item helpers (reference src/structs/Item.js:38-227) --------------------


def follow_redone(store, id: ID):
    """Follow a chain of ``redone`` pointers; returns (item, diff)."""
    next_id = id
    diff = 0
    while True:
        if diff > 0:
            next_id = create_id(next_id.client, next_id.clock + diff)
        item = get_item(store, next_id)
        diff = next_id.clock - item.id.clock
        next_id = item.redone if type(item) is Item else None
        if next_id is None or type(item) is not Item:
            break
    return item, diff


def keep_item(item, keep: bool) -> None:
    """Pin item + all ancestors against GC (reference Item.js:67-72)."""
    while item is not None and item.keep != keep:
        item.keep = keep
        item = item.parent._item


def split_item(transaction, left_item: Item, diff: int) -> Item:
    """Split a run at ``diff`` content units (reference Item.js:84-120)."""
    client = left_item.id.client
    clock = left_item.id.clock
    right_item = Item(
        create_id(client, clock + diff),
        left_item,
        create_id(client, clock + diff - 1),
        left_item.right,
        left_item.right_origin,
        left_item.parent,
        left_item.parent_sub,
        left_item.content.splice(diff),
    )
    if left_item.deleted:
        right_item.mark_deleted()
    if left_item.keep:
        right_item.keep = True
    if left_item.redone is not None:
        right_item.redone = create_id(left_item.redone.client, left_item.redone.clock + diff)
    # do not set left_item.right_origin — that would break sync
    left_item.right = right_item
    if right_item.right is not None:
        right_item.right.left = right_item
    transaction._merge_structs.append(right_item)
    if right_item.parent_sub is not None and right_item.right is None:
        right_item.parent._map[right_item.parent_sub] = right_item
    left_item.length = diff
    return right_item


def redo_item(transaction, item: Item, redoitems: set) -> Item | None:
    """Redo the effect of an (undone) operation (reference Item.js:133-227)."""
    doc = transaction.doc
    store = doc.store
    own_client_id = doc.client_id
    redone = item.redone
    if redone is not None:
        return get_item_clean_start(transaction, redone)
    parent_item = item.parent._item
    if item.parent_sub is None:
        # list item: re-insert at the old position
        left = item.left
        right = item
    else:
        # map item: insert as the current value
        left = item
        while left.right is not None:
            left = left.right
            if left.id.client != own_client_id:
                # conflicts with a change from another client; cannot redo
                return None
        right = None
    # make sure the parent is redone
    if parent_item is not None and parent_item.deleted and parent_item.redone is None:
        if parent_item not in redoitems or redo_item(transaction, parent_item, redoitems) is None:
            return None
    if parent_item is not None and parent_item.redone is not None:
        while parent_item.redone is not None:
            parent_item = get_item_clean_start(transaction, parent_item.redone)
        # find next cloned_redo items
        while left is not None:
            left_trace = left
            while left_trace is not None and left_trace.parent._item is not parent_item:
                left_trace = (
                    None
                    if left_trace.redone is None
                    else get_item_clean_start(transaction, left_trace.redone)
                )
            if left_trace is not None and left_trace.parent._item is parent_item:
                left = left_trace
                break
            left = left.left
        while right is not None:
            right_trace = right
            while right_trace is not None and right_trace.parent._item is not parent_item:
                right_trace = (
                    None
                    if right_trace.redone is None
                    else get_item_clean_start(transaction, right_trace.redone)
                )
            if right_trace is not None and right_trace.parent._item is parent_item:
                right = right_trace
                break
            right = right.right
    next_clock = get_state(store, own_client_id)
    next_id = create_id(own_client_id, next_clock)
    redone_item = Item(
        next_id,
        left,
        left.last_id if left is not None else None,
        right,
        right.id if right is not None else None,
        item.parent if parent_item is None else parent_item.content.type,
        item.parent_sub,
        item.content.copy(),
    )
    item.redone = next_id
    keep_item(redone_item, True)
    redone_item.integrate(transaction, 0)
    return redone_item


# ---------------------------------------------------------------------------
# StructStore (reference src/utils/StructStore.js)
# ---------------------------------------------------------------------------


class StructStore:
    """Per-client insertion-order arrays of structs, sorted by clock, plus
    pending buffers for causally-early updates."""

    __slots__ = ("clients", "pending_clients_struct_refs", "pending_stack", "pending_delete_readers")

    def __init__(self):
        self.clients: dict[int, list] = {}
        # client -> {"i": next index, "refs": [structs]}
        self.pending_clients_struct_refs: dict[int, dict] = {}
        self.pending_stack: list = []
        self.pending_delete_readers: list = []


def get_state_vector(store: StructStore) -> dict[int, int]:
    sm = {}
    for client, structs in store.clients.items():
        struct = structs[-1]
        sm[client] = struct.id.clock + struct.length
    return sm


def get_state(store: StructStore, client: int) -> int:
    structs = store.clients.get(client)
    if structs is None:
        return 0
    last = structs[-1]
    return last.id.clock + last.length


def integrity_check(store: StructStore) -> None:
    for structs in store.clients.values():
        for i in range(1, len(structs)):
            left = structs[i - 1]
            right = structs[i]
            if left.id.clock + left.length != right.id.clock:
                raise RuntimeError("StructStore failed integrity check")


def add_struct(store: StructStore, struct) -> None:
    structs = store.clients.get(struct.id.client)
    if structs is None:
        store.clients[struct.id.client] = [struct]
        return
    last = structs[-1]
    if last.id.clock + last.length != struct.id.clock:
        raise RuntimeError("struct store clocks must be contiguous")
    structs.append(struct)


def find_index_ss(structs: list, clock: int) -> int:
    """Binary search with pivot guess (reference StructStore.js:123-151)."""
    left = 0
    right = len(structs) - 1
    mid = structs[right]
    midclock = mid.id.clock
    if midclock == clock:
        return right
    midindex = int((clock / (midclock + mid.length - 1)) * right)
    while left <= right:
        mid = structs[midindex]
        midclock = mid.id.clock
        if midclock <= clock:
            if clock < midclock + mid.length:
                return midindex
            left = midindex + 1
        else:
            right = midindex - 1
        midindex = (left + right) // 2
    raise RuntimeError(f"struct with clock {clock} not found")


def find(store: StructStore, id: ID):
    structs = store.clients[id.client]
    return structs[find_index_ss(structs, id.clock)]


get_item = find


def find_index_clean_start(transaction, structs: list, clock: int) -> int:
    index = find_index_ss(structs, clock)
    struct = structs[index]
    if struct.id.clock < clock and type(struct) is Item:
        structs.insert(index + 1, split_item(transaction, struct, clock - struct.id.clock))
        return index + 1
    return index


def get_item_clean_start(transaction, id: ID) -> Item:
    structs = transaction.doc.store.clients[id.client]
    return structs[find_index_clean_start(transaction, structs, id.clock)]


def get_item_clean_end(transaction, store: StructStore, id: ID):
    structs = store.clients[id.client]
    index = find_index_ss(structs, id.clock)
    struct = structs[index]
    if id.clock != struct.id.clock + struct.length - 1 and type(struct) is not GC:
        structs.insert(index + 1, split_item(transaction, struct, id.clock - struct.id.clock + 1))
    return struct


def replace_struct(store: StructStore, struct, new_struct) -> None:
    structs = store.clients[struct.id.client]
    structs[find_index_ss(structs, struct.id.clock)] = new_struct


def iterate_structs(transaction, structs: list, clock_start: int, length: int, f) -> None:
    if length == 0:
        return
    clock_end = clock_start + length
    index = find_index_clean_start(transaction, structs, clock_start)
    while True:
        struct = structs[index]
        index += 1
        if clock_end < struct.id.clock + struct.length:
            find_index_clean_start(transaction, structs, clock_end)
        f(struct)
        if index >= len(structs) or structs[index].id.clock >= clock_end:
            break


# ---------------------------------------------------------------------------
# DeleteSet (reference src/utils/DeleteSet.js)
# ---------------------------------------------------------------------------


class DeleteItem:
    __slots__ = ("clock", "len")

    def __init__(self, clock: int, ln: int):
        self.clock = clock
        self.len = ln

    def __repr__(self):
        return f"DeleteItem({self.clock},{self.len})"


class DeleteSet:
    """State-based delete CRDT: client -> sorted array of (clock, len)."""

    __slots__ = ("clients",)

    def __init__(self):
        self.clients: dict[int, list[DeleteItem]] = {}


def iterate_deleted_structs(transaction, ds: DeleteSet, f) -> None:
    for client, deletes in ds.clients.items():
        structs = transaction.doc.store.clients[client]
        for del_item in deletes:
            iterate_structs(transaction, structs, del_item.clock, del_item.len, f)


def find_index_ds(dis: list[DeleteItem], clock: int) -> int | None:
    left = 0
    right = len(dis) - 1
    while left <= right:
        midindex = (left + right) // 2
        mid = dis[midindex]
        midclock = mid.clock
        if midclock <= clock:
            if clock < midclock + mid.len:
                return midindex
            left = midindex + 1
        else:
            right = midindex - 1
    return None


def is_deleted(ds: DeleteSet, id: ID) -> bool:
    dis = ds.clients.get(id.client)
    return dis is not None and find_index_ds(dis, id.clock) is not None


def sort_and_merge_delete_set(ds: DeleteSet) -> None:
    for dels in ds.clients.values():
        dels.sort(key=lambda d: d.clock)
        # merge in place: i scans, j is the insert position
        j = 1
        for i in range(1, len(dels)):
            left = dels[j - 1]
            right = dels[i]
            if left.clock + left.len == right.clock:
                left.len += right.len
            else:
                if j < i:
                    dels[j] = right
                j += 1
        del dels[j:]


def merge_delete_sets(dss: list[DeleteSet]) -> DeleteSet:
    merged = DeleteSet()
    for dss_i, ds in enumerate(dss):
        for client, dels_left in ds.clients.items():
            if client not in merged.clients:
                dels = [DeleteItem(d.clock, d.len) for d in dels_left]
                for i in range(dss_i + 1, len(dss)):
                    dels.extend(
                        DeleteItem(d.clock, d.len) for d in dss[i].clients.get(client, ())
                    )
                merged.clients[client] = dels
    sort_and_merge_delete_set(merged)
    return merged


def add_to_delete_set(ds: DeleteSet, client: int, clock: int, length: int) -> None:
    ds.clients.setdefault(client, []).append(DeleteItem(clock, length))


def create_delete_set() -> DeleteSet:
    """Fresh empty DeleteSet (reference src/utils/DeleteSet.js
    createDeleteSet, exported from src/index.js:42)."""
    return DeleteSet()


def create_delete_set_from_struct_store(ss: StructStore) -> DeleteSet:
    ds = DeleteSet()
    for client, structs in ss.clients.items():
        ds_items = []
        i = 0
        n = len(structs)
        while i < n:
            struct = structs[i]
            if struct.deleted:
                clock = struct.id.clock
                ln = struct.length
                while i + 1 < n:
                    nxt = structs[i + 1]
                    if nxt.id.clock == clock + ln and nxt.deleted:
                        ln += nxt.length
                        i += 1
                    else:
                        break
                ds_items.append(DeleteItem(clock, ln))
            i += 1
        if ds_items:
            ds.clients[client] = ds_items
    return ds


def write_delete_set(encoder, ds: DeleteSet) -> None:
    from .lib0 import encoding as lib0enc

    lib0enc.write_var_uint(encoder.rest_encoder, len(ds.clients))
    for client, ds_items in ds.clients.items():
        encoder.reset_ds_cur_val()
        lib0enc.write_var_uint(encoder.rest_encoder, client)
        lib0enc.write_var_uint(encoder.rest_encoder, len(ds_items))
        for item in ds_items:
            encoder.write_ds_clock(item.clock)
            encoder.write_ds_len(item.len)


def read_delete_set(decoder) -> DeleteSet:
    from .lib0 import decoding as lib0dec

    ds = DeleteSet()
    num_clients = lib0dec.read_var_uint(decoder.rest_decoder)
    for _ in range(num_clients):
        decoder.reset_ds_cur_val()
        client = lib0dec.read_var_uint(decoder.rest_decoder)
        num_deletes = lib0dec.read_var_uint(decoder.rest_decoder)
        if num_deletes > 0:
            ds_field = ds.clients.setdefault(client, [])
            for _ in range(num_deletes):
                ds_field.append(DeleteItem(decoder.read_ds_clock(), decoder.read_ds_len()))
    return ds


def read_and_apply_delete_set(decoder, transaction, store) -> None:
    """Split & delete live ranges; buffer not-yet-known ranges
    (reference src/utils/DeleteSet.js:270-323)."""
    from .lib0 import decoding as lib0dec

    unapplied = DeleteSet()
    num_clients = lib0dec.read_var_uint(decoder.rest_decoder)
    for _ in range(num_clients):
        decoder.reset_ds_cur_val()
        client = lib0dec.read_var_uint(decoder.rest_decoder)
        num_deletes = lib0dec.read_var_uint(decoder.rest_decoder)
        structs = store.clients.get(client, [])
        state = get_state(store, client)
        for _ in range(num_deletes):
            clock = decoder.read_ds_clock()
            clock_end = clock + decoder.read_ds_len()
            if clock < state:
                if state < clock_end:
                    add_to_delete_set(unapplied, client, state, clock_end - state)
                index = find_index_ss(structs, clock)
                struct = structs[index]
                # split the first item if necessary
                if not struct.deleted and struct.id.clock < clock:
                    structs.insert(
                        index + 1, split_item(transaction, struct, clock - struct.id.clock)
                    )
                    index += 1
                while index < len(structs):
                    struct = structs[index]
                    index += 1
                    if struct.id.clock < clock_end:
                        if not struct.deleted:
                            if clock_end < struct.id.clock + struct.length:
                                structs.insert(
                                    index,
                                    split_item(
                                        transaction, struct, clock_end - struct.id.clock
                                    ),
                                )
                            struct.delete(transaction)
                    else:
                        break
            else:
                add_to_delete_set(unapplied, client, clock, clock_end - clock)
    if unapplied.clients:
        # re-encode the unapplied ranges and park them for later
        from .coding import DSDecoderV2, DSEncoderV2
        from .lib0.decoding import Decoder

        ds_encoder = DSEncoderV2()
        write_delete_set(ds_encoder, unapplied)
        store.pending_delete_readers.append(DSDecoderV2(Decoder(ds_encoder.to_bytes())))


# ---------------------------------------------------------------------------
# Transaction (reference src/utils/Transaction.js)
# ---------------------------------------------------------------------------


class Transaction:
    __slots__ = (
        "doc",
        "delete_set",
        "before_state",
        "after_state",
        "changed",
        "changed_parent_types",
        "_merge_structs",
        "origin",
        "meta",
        "local",
        "subdocs_added",
        "subdocs_removed",
        "subdocs_loaded",
    )

    def __init__(self, doc: "Doc", origin, local: bool):
        self.doc = doc
        self.delete_set = DeleteSet()
        self.before_state = get_state_vector(doc.store)
        self.after_state: dict[int, int] = {}
        self.changed: dict = {}
        self.changed_parent_types: dict = {}
        self._merge_structs: list = []
        self.origin = origin
        self.meta: dict = {}
        self.local = local
        self.subdocs_added: set = set()
        self.subdocs_removed: set = set()
        self.subdocs_loaded: set = set()


def write_update_message_from_transaction(encoder, transaction: Transaction) -> bool:
    if not transaction.delete_set.clients and not any(
        transaction.before_state.get(client) != clock
        for client, clock in transaction.after_state.items()
    ):
        return False
    from .updates import write_clients_structs

    sort_and_merge_delete_set(transaction.delete_set)
    write_clients_structs(encoder, transaction.doc.store, transaction.before_state)
    write_delete_set(encoder, transaction.delete_set)
    return True


def next_id(transaction: Transaction) -> ID:
    y = transaction.doc
    return create_id(y.client_id, get_state(y.store, y.client_id))


def add_changed_type_to_transaction(transaction: Transaction, type_, parent_sub) -> None:
    item = type_._item
    if item is None or (
        item.id.clock < transaction.before_state.get(item.id.client, 0) and not item.deleted
    ):
        transaction.changed.setdefault(type_, set()).add(parent_sub)


def _try_to_merge_with_left(structs: list, pos: int) -> None:
    left = structs[pos - 1]
    right = structs[pos]
    if left.deleted == right.deleted and type(left) is type(right):
        if left.merge_with(right):
            del structs[pos]
            if (
                type(right) is Item
                and right.parent_sub is not None
                and right.parent._map.get(right.parent_sub) is right
            ):
                right.parent._map[right.parent_sub] = left


def _try_gc_delete_set(ds: DeleteSet, store: StructStore, gc_filter) -> None:
    for client, delete_items in ds.clients.items():
        structs = store.clients[client]
        for di in range(len(delete_items) - 1, -1, -1):
            delete_item = delete_items[di]
            end_clock = delete_item.clock + delete_item.len
            si = find_index_ss(structs, delete_item.clock)
            while si < len(structs):
                struct = structs[si]
                if struct.id.clock >= end_clock:
                    break
                if type(struct) is Item and struct.deleted and not struct.keep and gc_filter(struct):
                    struct.gc(store, False)
                si += 1


def _try_merge_delete_set(ds: DeleteSet, store: StructStore) -> None:
    # merge right-to-left for efficiency and completeness
    for client, delete_items in ds.clients.items():
        structs = store.clients[client]
        for di in range(len(delete_items) - 1, -1, -1):
            delete_item = delete_items[di]
            most_right = min(
                len(structs) - 1,
                1 + find_index_ss(structs, delete_item.clock + delete_item.len - 1),
            )
            si = most_right
            while si > 0 and structs[si].id.clock >= delete_item.clock:
                _try_to_merge_with_left(structs, si)
                si -= 1


def try_gc(ds: DeleteSet, store: StructStore, gc_filter) -> None:
    _try_gc_delete_set(ds, store, gc_filter)
    _try_merge_delete_set(ds, store)


def _cleanup_transactions(transaction_cleanups: list, i: int) -> None:
    if i >= len(transaction_cleanups):
        return
    transaction = transaction_cleanups[i]
    doc = transaction.doc
    store = doc.store
    ds = transaction.delete_set
    merge_structs = transaction._merge_structs
    try:
        sort_and_merge_delete_set(ds)
        transaction.after_state = get_state_vector(store)
        doc._transaction = None
        doc.emit("beforeObserverCalls", [transaction, doc])
        fs: list = []
        for itemtype, subs in transaction.changed.items():
            def _call_observer(itemtype=itemtype, subs=subs):
                if itemtype._item is None or not itemtype._item.deleted:
                    itemtype._call_observer(transaction, subs)

            fs.append(_call_observer)

        def _deep_events():
            for type_, events in transaction.changed_parent_types.items():
                def _call_deep(type_=type_, events=events):
                    if type_._item is None or not type_._item.deleted:
                        evts = [
                            event
                            for event in events
                            if event.target._item is None or not event.target._item.deleted
                        ]
                        for event in evts:
                            event.current_target = type_
                        evts.sort(key=lambda event: len(event.path))
                        if evts:
                            call_event_handler_listeners(type_._deh, evts, transaction)

                fs.append(_call_deep)
            fs.append(lambda: doc.emit("afterTransaction", [transaction, doc]))

        fs.append(_deep_events)
        call_all(fs, [])
    finally:
        # GC + compaction passes; this is where content is actually removed
        if doc.gc:
            _try_gc_delete_set(ds, store, doc.gc_filter)
        _try_merge_delete_set(ds, store)

        for client, clock in transaction.after_state.items():
            before_clock = transaction.before_state.get(client, 0)
            if before_clock != clock:
                structs = store.clients[client]
                first_change_pos = max(find_index_ss(structs, before_clock), 1)
                for idx in range(len(structs) - 1, first_change_pos - 1, -1):
                    _try_to_merge_with_left(structs, idx)
        for struct in merge_structs:
            client = struct.id.client
            clock = struct.id.clock
            structs = store.clients[client]
            replaced_pos = find_index_ss(structs, clock)
            if replaced_pos + 1 < len(structs):
                _try_to_merge_with_left(structs, replaced_pos + 1)
            if replaced_pos > 0:
                _try_to_merge_with_left(structs, replaced_pos)
        if not transaction.local and transaction.after_state.get(
            doc.client_id
        ) != transaction.before_state.get(doc.client_id):
            # another client is using our client id: regenerate
            doc.client_id = generate_new_client_id()
        doc.emit("afterTransactionCleanup", [transaction, doc])
        if "update" in doc._observers:
            from .coding import default_update_encoder

            encoder = default_update_encoder()
            if write_update_message_from_transaction(encoder, transaction):
                doc.emit("update", [encoder.to_bytes(), transaction.origin, doc])
        if "updateV2" in doc._observers:
            from .coding import UpdateEncoderV2

            encoder = UpdateEncoderV2()
            if write_update_message_from_transaction(encoder, transaction):
                doc.emit("updateV2", [encoder.to_bytes(), transaction.origin, doc])
        for subdoc in transaction.subdocs_added:
            doc.subdocs.add(subdoc)
        for subdoc in transaction.subdocs_removed:
            doc.subdocs.discard(subdoc)
        doc.emit(
            "subdocs",
            [
                {
                    "loaded": transaction.subdocs_loaded,
                    "added": transaction.subdocs_added,
                    "removed": transaction.subdocs_removed,
                }
            ],
        )
        for subdoc in transaction.subdocs_removed:
            subdoc.destroy()
        if len(transaction_cleanups) <= i + 1:
            doc._transaction_cleanups = []
            doc.emit("afterAllTransactions", [doc, transaction_cleanups])
        else:
            _cleanup_transactions(transaction_cleanups, i + 1)


def transact(doc: "Doc", f, origin=None, local: bool = True):
    """Run `f(transaction)`, reusing the current transaction when nested
    (reference src/utils/Transaction.js:378-405)."""
    transaction_cleanups = doc._transaction_cleanups
    initial_call = False
    result = None
    if doc._transaction is None:
        initial_call = True
        doc._transaction = Transaction(doc, origin, local)
        transaction_cleanups.append(doc._transaction)
        if len(transaction_cleanups) == 1:
            doc.emit("beforeAllTransactions", [doc])
        doc.emit("beforeTransaction", [doc._transaction, doc])
    try:
        result = f(doc._transaction)
    finally:
        if initial_call and transaction_cleanups[0] is doc._transaction:
            _cleanup_transactions(transaction_cleanups, 0)
    return result


# ---------------------------------------------------------------------------
# Doc (reference src/utils/Doc.js)
# ---------------------------------------------------------------------------


def generate_new_client_id() -> int:
    return _random.getrandbits(32)


def _uuidv4() -> str:
    import uuid

    return str(uuid.uuid4())


class Doc(Observable):
    """A shared document: root-type registry + struct store + transactions."""

    def __init__(self, guid=None, gc=True, gc_filter=None, meta=None, auto_load=False):
        super().__init__()
        self.gc = gc
        self.gc_filter = gc_filter if gc_filter is not None else (lambda item: True)
        self.client_id = generate_new_client_id()
        self.guid = guid if guid is not None else _uuidv4()
        self.share: dict[str, object] = {}
        self.store = StructStore()
        self._transaction: Transaction | None = None
        self._transaction_cleanups: list[Transaction] = []
        self.subdocs: set[Doc] = set()
        self._item: Item | None = None
        self.should_load = auto_load
        self.auto_load = auto_load
        self.meta = meta

    # camelCase alias kept for API parity with the reference
    @property
    def clientID(self) -> int:  # noqa: N802
        return self.client_id

    @clientID.setter
    def clientID(self, v: int) -> None:  # noqa: N802
        self.client_id = v

    def load(self) -> None:
        item = self._item
        if item is not None and not self.should_load:
            def _mark(transaction):
                transaction.subdocs_loaded.add(self)

            transact(item.parent.doc, _mark, None, True)
        self.should_load = True

    def get_subdocs(self) -> set:
        return self.subdocs

    def get_subdoc_guids(self) -> set:
        return {doc.guid for doc in self.subdocs}

    def transact(self, f, origin=None):
        return transact(self, f, origin)

    def get(self, name: str, type_constructor=None):
        """Lazy root-type definition with retyping of placeholder types
        (reference src/utils/Doc.js:139-171)."""
        from .types.abstract import AbstractType

        if type_constructor is None:
            type_constructor = AbstractType
        type_ = self.share.get(name)
        if type_ is None:
            type_ = type_constructor()
            type_._integrate(self, None)
            self.share[name] = type_
        constr = type(type_)
        if type_constructor is not AbstractType and constr is not type_constructor:
            if constr is AbstractType:
                t = type_constructor()
                t._map = type_._map
                for n in type_._map.values():
                    while n is not None:
                        n.parent = t
                        n = n.left
                t._start = type_._start
                n = t._start
                while n is not None:
                    n.parent = t
                    n = n.right
                t._length = type_._length
                self.share[name] = t
                t._integrate(self, None)
                return t
            raise TypeError(
                f"Type with the name {name} has already been defined with a different constructor"
            )
        return type_

    def get_array(self, name: str = ""):
        from .types.yarray import YArray

        return self.get(name, YArray)

    def get_text(self, name: str = ""):
        from .types.ytext import YText

        return self.get(name, YText)

    def get_map(self, name: str = ""):
        from .types.ymap import YMap

        return self.get(name, YMap)

    def get_xml_fragment(self, name: str = ""):
        from .types.yxml import YXmlFragment

        return self.get(name, YXmlFragment)

    def to_json(self) -> dict:
        return {key: value.to_json() for key, value in self.share.items()}

    def destroy(self) -> None:
        for subdoc in list(self.subdocs):
            subdoc.destroy()
        item = self._item
        if item is not None:
            self._item = None
            content = item.content
            if item.deleted:
                # content may already be gc'd to ContentDeleted; JS sets a
                # dangling .doc property there, which is a no-op for us
                if type(content) is ContentDoc:
                    content.doc = None
            else:
                new_doc = Doc(guid=self.guid, **_opts_to_kwargs(content.opts))
                content.doc = new_doc
                new_doc._item = item

            def _propagate(transaction):
                if not item.deleted:
                    transaction.subdocs_added.add(content.doc)
                transaction.subdocs_removed.add(self)

            transact(item.parent.doc, _propagate, None, True)
        self.emit("destroyed", [True])
        self.emit("destroy", [self])
        super().destroy()


def _opts_to_kwargs(opts: dict) -> dict:
    kwargs = {}
    if "gc" in opts:
        kwargs["gc"] = opts["gc"]
    if "autoLoad" in opts:
        kwargs["auto_load"] = opts["autoLoad"]
    if "meta" in opts:
        kwargs["meta"] = opts["meta"]
    return kwargs


# -- misc helpers -----------------------------------------------------------


def is_parent_of(parent, child: Item | None) -> bool:
    """Ancestor test (reference src/utils/isParentOf.js:14-22)."""
    while child is not None:
        if child.parent is parent:
            return True
        child = child.parent._item
    return False


def log_type(type_) -> None:
    """Debug dump of a type's item list (reference src/utils/logging.js)."""
    s = type_._start
    arr = []
    while s is not None:
        arr.append(s)
        s = s.right
    print("Children:", arr)
    print(
        "Children content:",
        [from_u16("".join(map(str, c.content.get_content()))) for c in arr if not c.deleted],
    )


def _json_stringify(value) -> str:
    from .coding import _json_stringify as impl

    return impl(value)


def _json_parse(s: str):
    from .coding import _json_parse as impl

    return impl(s)
