"""Distributed causal tracing: compact trace contexts propagated across
providers (ISSUE 11 tentpole, part 1).

A :class:`TraceContext` is a Dapper-style triple — 16-byte trace id,
8-byte span id, 1-byte flags — minted at ingress (``receive_update`` /
session DATA / the fleet router seam) and carried to every downstream
seam two ways:

- **in-process** via a :mod:`contextvars` slot (:func:`use_context` /
  :func:`current_context`), so admission queues, replication fan-out,
  and flush visibility all see the ingress context without any
  signature churn; and
- **across peers** as an optional trailing key on the type-121 session
  DATA envelope (see ``sync/session.py``).  Readers older than this PR
  read only ``seq`` + ``inner`` and never touch trailing decoder bytes,
  and stock y-protocols v13.4.9 readers skip the whole unknown type-121
  message — zero wire change.

Trace identity is **deterministic**: the trace id is a keyed blake2b of
the raw update bytes, so two providers that each see the same update
independently compute the SAME trace id even before the envelope carry
reaches them — cross-provider stitching degrades gracefully instead of
breaking.  Sampling is equally deterministic (a residue test on the
trace-id integer, ``YTPU_TRACE_SAMPLE``, default 1-in-64), so every
peer makes the same keep/drop decision for a given update with no
coordination.  DLQ / rollback / failover paths force-sample
(:meth:`TraceContext.force`) so every failure has a trace.

Flow-arrow ids are derived from the same hash space
(:func:`flow_id_for`), replacing the PR 4 process-global
``itertools.count`` that could collide after ``YTPU_TRACE_EVENTS`` cap
truncation: a hash-derived id is stable under truncation and across
processes.
"""

from __future__ import annotations

import contextvars
import os
from contextlib import contextmanager
from hashlib import blake2b
from typing import Optional

from .registry import MetricsRegistry

__all__ = [
    "TraceContext",
    "current_context",
    "use_context",
    "mint_for_update",
    "flow_id_for",
    "sample_rate",
    "trace_metrics",
]

# wire layout: 16-byte trace id (BE) + 8-byte span id (BE) + 1 flag byte
TRACE_CTX_LEN = 25
_FLAG_SAMPLED = 0x01
_PERSON = b"ytpu-trace"


def sample_rate() -> int:
    """Head-sampling rate from ``YTPU_TRACE_SAMPLE``: ``N`` keeps one
    trace in N (default 64), ``1`` samples everything, ``0`` disables
    head sampling entirely (forced samples still trace)."""
    try:
        return max(0, int(os.environ.get("YTPU_TRACE_SAMPLE", "64")))
    except (TypeError, ValueError):
        return 64


def _head_sampled(trace_id: int) -> bool:
    rate = sample_rate()
    if rate == 0:
        return False
    if rate <= 1:
        return True
    return trace_id % rate == 0


class TraceContext:
    """One update's causal identity: ``(trace_id, span_id, sampled)``."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: int, span_id: int, sampled: bool):
        self.trace_id = trace_id & ((1 << 128) - 1)
        self.span_id = span_id & ((1 << 64) - 1)
        self.sampled = bool(sampled)

    # -- wire --------------------------------------------------------------

    def to_bytes(self) -> bytes:
        flags = _FLAG_SAMPLED if self.sampled else 0
        return (
            self.trace_id.to_bytes(16, "big")
            + self.span_id.to_bytes(8, "big")
            + bytes((flags,))
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> Optional["TraceContext"]:
        """Parse a wire blob; returns ``None`` on any shape mismatch
        (future flag bytes may extend the blob — only the 25-byte
        prefix is interpreted)."""
        if not isinstance(raw, (bytes, bytearray)) or len(raw) < TRACE_CTX_LEN:
            return None
        return cls(
            int.from_bytes(raw[:16], "big"),
            int.from_bytes(raw[16:24], "big"),
            bool(raw[24] & _FLAG_SAMPLED),
        )

    # -- identity ----------------------------------------------------------

    @property
    def trace_hex(self) -> str:
        return f"{self.trace_id:032x}"

    @property
    def span_hex(self) -> str:
        return f"{self.span_id:016x}"

    @property
    def flow_id(self) -> int:
        """A Perfetto flow id for this trace (low 48 bits of the trace
        id — JSON-safe, stable across peers and cap truncation)."""
        return (self.trace_id & ((1 << 48) - 1)) or 1

    def child(self, seed: str) -> "TraceContext":
        """A deterministic child span of this trace (same trace id and
        sampled bit; the span id is re-derived from ``seed``)."""
        h = blake2b(digest_size=8, person=_PERSON)
        h.update(self.span_id.to_bytes(8, "big"))
        h.update(seed.encode("utf-8", "replace"))
        return TraceContext(
            self.trace_id, int.from_bytes(h.digest(), "big"), self.sampled
        )

    def force(self, reason: str = "") -> "TraceContext":
        """Force-sample this trace (DLQ / rollback / failover paths —
        every failure gets a trace regardless of the head-sample
        draw)."""
        if self.sampled:
            return self
        if reason:
            trace_metrics().forced.labels(reason=reason).inc()
        return TraceContext(self.trace_id, self.span_id, True)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"TraceContext({self.trace_hex[:8]}…/{self.span_hex[:8]}…"
            f"{' sampled' if self.sampled else ''})"
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, TraceContext)
            and self.trace_id == other.trace_id
            and self.span_id == other.span_id
            and self.sampled == other.sampled
        )

    def __hash__(self) -> int:
        return hash((self.trace_id, self.span_id, self.sampled))


def mint_for_update(update: bytes, salt: bytes = b"") -> TraceContext:
    """Deterministically mint the :class:`TraceContext` for one raw
    update: every provider that hashes the same bytes computes the same
    trace id and the same sampling verdict."""
    h = blake2b(digest_size=24, person=_PERSON)
    h.update(bytes(update))
    if salt:
        h.update(salt)
    d = h.digest()
    trace_id = int.from_bytes(d[:16], "big")
    return TraceContext(
        trace_id, int.from_bytes(d[16:24], "big"), _head_sampled(trace_id)
    )


def flow_id_for(key) -> int:
    """A collision-resistant Perfetto flow id for an arbitrary hashable
    key (e.g. the SLO ``(client, clock)`` update key).  Hash-derived, so
    it stays stable after tracer-ring truncation and matches across
    providers — unlike a process-global counter."""
    h = blake2b(repr(key).encode("utf-8", "replace"), digest_size=6,
                person=_PERSON)
    return int.from_bytes(h.digest(), "big") or 1


# -- in-process propagation ---------------------------------------------------

_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "ytpu_trace_context", default=None
)


def current_context() -> Optional[TraceContext]:
    """The trace context of the in-flight ingress call, if any."""
    return _CURRENT.get()


@contextmanager
def use_context(ctx: Optional[TraceContext]):
    """Install ``ctx`` as the current trace context for the body (a
    ``None`` ctx clears it, isolating nested ingress paths)."""
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


# -- metrics ------------------------------------------------------------------


class _TraceMetrics:
    """``ytpu_trace_*`` families on the process-global registry."""

    def __init__(self, registry: MetricsRegistry) -> None:
        self.contexts = registry.counter(
            "ytpu_trace_contexts_total",
            "Trace contexts established at ingress, by origin "
            "(minted = hashed locally, adopted = carried in on the "
            "session envelope / in-process propagation)",
            labelnames=("origin",),
        )
        self.sampled = registry.counter(
            "ytpu_trace_sampled_total",
            "Ingress trace contexts whose head-sample draw kept them",
        )
        self.forced = registry.counter(
            "ytpu_trace_forced_total",
            "Trace contexts force-sampled by a failure path "
            "(dlq / rollback / failover / quarantine)",
            labelnames=("reason",),
        )
        self.carried = registry.counter(
            "ytpu_trace_carried_total",
            "Trace contexts carried on session DATA envelopes, by "
            "direction",
            labelnames=("dir",),
        )


_METRICS: Optional[_TraceMetrics] = None


def trace_metrics() -> _TraceMetrics:
    """Lazily register the ``ytpu_trace_*`` families (idempotent; the
    global registry dedupes by name)."""
    global _METRICS
    if _METRICS is None:
        from . import global_registry

        _METRICS = _TraceMetrics(global_registry())
    return _METRICS
