"""Bounded flush-history ring: the last N per-flush metric dicts.

Supersedes the overwrite-only ``last_flush_metrics`` — the engine now
appends every flush's metrics dict here, and ``last_flush_metrics``
remains as a compatibility view of the newest entry (the SAME dict
object, not a copy; ``snapshot()`` returns copies for export).
"""

from __future__ import annotations

import os
import threading
from collections import deque

DEFAULT_HISTORY = 128


def history_len_from_env() -> int:
    """Ring capacity: ``YTPU_OBS_HISTORY`` (default 128, min 1)."""
    try:
        return max(1, int(os.environ.get("YTPU_OBS_HISTORY", DEFAULT_HISTORY)))
    except ValueError:
        return DEFAULT_HISTORY


class FlushHistory:
    """FIFO ring of per-flush metric dicts (oldest evicted first).

    ``append`` and ``snapshot`` are lock-guarded: exposition scrapes run
    from other threads while a flush appends, and deque iteration raises
    on concurrent mutation (a torn scrape, not just a stale one)."""

    __slots__ = ("_ring", "total", "_lock")

    def __init__(self, maxlen: int | None = None):
        if maxlen is None:
            maxlen = history_len_from_env()
        self._ring: deque = deque(maxlen=maxlen)
        # flushes ever recorded (monotonic; ring length caps at maxlen)
        self.total = 0
        self._lock = threading.Lock()

    @property
    def maxlen(self) -> int:
        # the deque binding is final and .maxlen is immutable
        return self._ring.maxlen  # ytpu-lint: disable=lock-discipline -- reads an immutable attribute of a never-rebound deque

    @property
    def latest(self) -> dict | None:
        """The newest entry itself — the ``last_flush_metrics`` alias."""
        with self._lock:
            return self._ring[-1] if self._ring else None

    def append(self, metrics: dict) -> None:
        with self._lock:
            self._ring.append(metrics)
            self.total += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def __iter__(self):
        # iterate a point-in-time copy: deque iteration raises if a
        # concurrent flush appends mid-walk (a torn scrape)
        with self._lock:
            return iter(tuple(self._ring))

    def __getitem__(self, i):
        with self._lock:
            return self._ring[i]

    def snapshot(self) -> list[dict]:
        """Oldest-to-newest copies, safe to serialize or mutate."""
        with self._lock:
            return [dict(m) for m in self._ring]
