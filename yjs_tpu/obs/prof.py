"""yjs_tpu.obs.prof: compile-aware device/compile cost attribution.

Every jitted entry point (kernels.py, engine's statics scatter, the
sharded mesh factories) is wrapped with :func:`profiled`, which keeps a
per-kernel set of abstract call signatures — (shape, dtype) per array
leaf, value per static scalar — mirroring jax's trace cache:

- first signature ever seen  -> a **compile**: the call's wall time
  (trace + lower + compile + first run) lands in
  ``ytpu_prof_compile_seconds{kernel,shape}``;
- a NEW signature on a kernel that already compiled -> additionally a
  **retrace**: counted in ``ytpu_prof_retraces_total`` and recorded as a
  bounded event list (``kernel_profiler().retrace_events``) carrying the
  offending abstract shapes, plus a tracer instant for Perfetto;
- a known signature -> a **cache hit**: dispatch wall time lands in
  ``ytpu_prof_device_seconds{kernel,shape}``.

The signature set is a host-side mirror of jax's cache, not the cache
itself: weak-type promotions jax distinguishes may be recorded here as
hits (the dispatch histogram absorbs the extra trace time).  Shape
labels are power-of-two buckets of the largest array leaf's element
count, so label cardinality stays bounded while growth-driven retraces
remain attributable.

``YTPU_PROF_DEVICE=1`` additionally: blocks until the result is ready
(``jax.block_until_ready``) so device-time deltas are exact instead of
dispatch-only, and opens a ``jax.profiler.TraceAnnotation`` around every
profiled call so kernels are attributable inside a device profiler
trace.  Leave it unset on the hot path — forcing a sync per call defeats
async dispatch (bench.py's ``detail.obs_prof`` measures the unset-mode
overhead).

Host-side batch ops (``ops/batch.py`` columnar ops, the native planner's
``prepare_many``) record into ``ytpu_prof_batch_op_seconds{op}`` via
:func:`host_timed` / ``record_host_op``.

All families live on the process-global registry (kernels are
module-level, shared by every engine in the process), pre-registered at
import so exposition and ``scripts/check_metrics_schema.py`` see them
before the first kernel call.
"""

from __future__ import annotations

import functools
import os
import time
from collections import deque

from . import global_registry, obs_enabled
from .trace import Tracer

# retrace events kept for inspection (ytpu_top / tests); counters keep
# the full total
RETRACE_EVENTS_MAX = 256


def _leaf_sig(leaf):
    shape = getattr(leaf, "shape", None)
    if shape is not None:
        return (tuple(shape), str(getattr(leaf, "dtype", "")))
    if isinstance(leaf, (int, float, bool, str, bytes, type(None))):
        return leaf
    return type(leaf).__name__


def call_signature(args, kwargs) -> tuple:
    """Abstract signature of one call: (shape, dtype) per array leaf,
    value per hashable static — the host mirror of jax's cache key."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs))
    return tuple(_leaf_sig(leaf) for leaf in leaves)


def shape_bucket(sig: tuple) -> str:
    """Power-of-two bucket of the largest array leaf's element count —
    the bounded-cardinality ``shape`` label."""
    biggest = 0
    for s in sig:
        if isinstance(s, tuple) and len(s) == 2 and isinstance(s[0], tuple):
            n = 1
            for d in s[0]:
                n *= int(d)
            biggest = max(biggest, n)
    if biggest <= 0:
        return "scalar"
    p = 1
    while p < biggest:
        p <<= 1
    return f"le_{p}"


def _sig_str(sig: tuple, limit: int = 12) -> str:
    parts = []
    for s in sig[:limit]:
        if isinstance(s, tuple) and len(s) == 2 and isinstance(s[0], tuple):
            parts.append(f"{s[1]}[{','.join(str(d) for d in s[0])}]")
        else:
            parts.append(repr(s))
    if len(sig) > limit:
        parts.append(f"...+{len(sig) - limit}")
    return " ".join(parts)


class KernelProfiler:
    """Process-wide compile/dispatch cost attribution for jitted kernels.

    One instance per process (see :func:`kernel_profiler`); instruments
    live on the process-global registry so every engine's exposition
    includes them."""

    def __init__(self, registry=None, tracer: Tracer | None = None):
        self.enabled = obs_enabled()
        self.registry = registry if registry is not None else global_registry()
        # its own tracer: retrace instants ride YTPU_TRACE_PATH dumps
        # even with no engine in scope
        self.tracer = tracer if tracer is not None else Tracer(
            enabled=self.enabled
        )
        r = self.registry
        self._compiles = r.counter(
            "ytpu_prof_compiles_total",
            "Profiled kernel calls that traced+compiled (first sighting "
            "of a call signature)",
            labelnames=("kernel",),
        )
        self._hits = r.counter(
            "ytpu_prof_cache_hits_total",
            "Profiled kernel calls served by an already-compiled "
            "signature",
            labelnames=("kernel",),
        )
        self._retraces = r.counter(
            "ytpu_prof_retraces_total",
            "New call signatures on already-compiled kernels (each one "
            "paid a fresh trace+compile)",
            labelnames=("kernel",),
        )
        self._compile_seconds = r.histogram(
            "ytpu_prof_compile_seconds",
            "Wall time of compiling calls (trace+lower+compile+run), by "
            "kernel and shape bucket",
            unit="s",
            labelnames=("kernel", "shape"),
        )
        self._device_seconds = r.histogram(
            "ytpu_prof_device_seconds",
            "Wall time of cache-hit kernel calls (dispatch; exact device "
            "time under YTPU_PROF_DEVICE=1), by kernel and shape bucket",
            unit="s",
            labelnames=("kernel", "shape"),
        )
        self._batch_op_seconds = r.histogram(
            "ytpu_prof_batch_op_seconds",
            "Host-side batch/columnar op wall time, by op",
            unit="s",
            labelnames=("op",),
        )
        self._signatures: dict[str, set] = {}
        self.retrace_events: deque = deque(maxlen=RETRACE_EVENTS_MAX)
        # (kernel, sig) -> (hit child, device-seconds child): the steady
        # state is two dict hits + arithmetic per call
        self._children: dict = {}
        self._host_children: dict = {}

    # -- recording -----------------------------------------------------

    def call(self, kernel: str, fn, args, kwargs):
        device_mode = os.environ.get("YTPU_PROF_DEVICE") == "1"
        sig = call_signature(args, kwargs)
        cached = self._children.get((kernel, sig))
        if cached is not None and not device_mode:
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            cached[0].inc()
            cached[1].observe(dt)
            return out
        return self._call_slow(kernel, fn, args, kwargs, sig, device_mode)

    def _call_slow(self, kernel, fn, args, kwargs, sig, device_mode):
        import jax

        ann = (
            jax.profiler.TraceAnnotation(f"ytpu.prof.{kernel}")
            if device_mode
            else None
        )
        compiling = (kernel, sig) not in self._children
        t0 = time.perf_counter()
        if ann is not None:
            with ann:
                out = fn(*args, **kwargs)
        else:
            out = fn(*args, **kwargs)
        if device_mode or compiling:
            # block so the recorded delta covers the device work (and,
            # when compiling, the compile itself) — not just dispatch
            out = jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        bucket = shape_bucket(sig)
        if not compiling:
            children = self._children[(kernel, sig)]
            children[0].inc()
            children[1].observe(dt)
            return out
        seen = self._signatures.setdefault(kernel, set())
        is_retrace = bool(seen)
        seen.add(sig)
        self._compiles.labels(kernel=kernel).inc()
        self._compile_seconds.labels(kernel=kernel, shape=bucket).observe(dt)
        if is_retrace:
            self._retraces.labels(kernel=kernel).inc()
            event = {
                "kernel": kernel,
                "shape": bucket,
                "signature": _sig_str(sig),
                "n_signatures": len(seen),
                "compile_s": dt,
            }
            self.retrace_events.append(event)
            self.tracer.instant("ytpu.prof.retrace", **event)
        self._children[(kernel, sig)] = (
            self._hits.labels(kernel=kernel),
            self._device_seconds.labels(kernel=kernel, shape=bucket),
        )
        return out

    def record_host_op(self, op: str, dt_s: float) -> None:
        child = self._host_children.get(op)
        if child is None:
            child = self._batch_op_seconds.labels(op=op)
            self._host_children[op] = child
        child.observe(dt_s)

    def host_op_stats(self) -> dict:
        """op -> ``{"count", "total_s"}`` from the host batch-op
        timers — the bench-phase view regression tests pin against
        (e.g. the ISSUE 15 snapshot-reuse fix asserts ``plan_snapshot``
        stays cold on monotone prepend runs)."""
        out: dict = {}
        for labels, series in self._batch_op_seconds.samples():
            op = labels.get("op", "")
            out[op] = {"count": series.count, "total_s": series.sum}
        return out

    # -- inspection ----------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able per-kernel compile/hit/retrace totals + the bounded
        retrace event list (newest last)."""
        kernels: dict[str, dict] = {}
        for fam, key in (
            (self._compiles, "compiles"),
            (self._hits, "hits"),
            (self._retraces, "retraces"),
        ):
            for labels, series in fam.samples():
                k = labels.get("kernel", "")
                kernels.setdefault(
                    k, {"compiles": 0, "hits": 0, "retraces": 0}
                )[key] = series.value
        for k, rec in kernels.items():
            total = rec["compiles"] + rec["hits"]
            rec["hit_rate"] = rec["hits"] / total if total else 0.0
        return {
            "kernels": kernels,
            "retrace_events": list(self.retrace_events),
        }


_PROFILER: KernelProfiler | None = None


def kernel_profiler() -> KernelProfiler:
    global _PROFILER
    if _PROFILER is None:
        _PROFILER = KernelProfiler()
    return _PROFILER


def profiled(kernel: str):
    """Wrap a jitted callable with compile/retrace/dispatch attribution.

    The wrapper is transparent under ``YTPU_OBS_DISABLED=1`` (checked
    per call: bench.py toggles it in-process to measure overhead)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            p = kernel_profiler()
            if not p.enabled or os.environ.get("YTPU_OBS_DISABLED") == "1":
                return fn(*args, **kwargs)
            return p.call(kernel, fn, args, kwargs)

        wrapped.__wrapped__ = fn
        return wrapped

    return deco


def host_timed(op: str):
    """Wall-time a host-side batch op into
    ``ytpu_prof_batch_op_seconds{op}`` (no signature tracking — these
    are plain Python, nothing compiles)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            p = kernel_profiler()
            if not p.enabled or os.environ.get("YTPU_OBS_DISABLED") == "1":
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            p.record_host_op(op, time.perf_counter() - t0)
            return out

        wrapped.__wrapped__ = fn
        return wrapped

    return deco


# pre-register the families: check_metrics_schema and exposition must
# see them before any kernel runs
kernel_profiler()
