"""Per-process HTTP introspection plane (ISSUE 16 tentpole).

Every y-tpu process — shard, gateway, supervisor, or a plain
:class:`~yjs_tpu.provider.TpuProvider` / ``FleetRouter`` host — embeds
one :class:`AdminServer`: a zero-dependency ``http.server`` daemon
thread answering GETs on a loopback port.  This is the pull-based
Borgmon/Prometheus model the ISSUE 1 exposition format anticipated:
remote hosts cannot share a snapshot directory, but they can all answer
``GET /metrics``, so the admin plane is the seam the multi-host cluster
scales through (``obs/federate.py`` grew the matching
``scrape_endpoints`` HTTP mode).

Endpoints::

    /metrics         Prometheus exposition (text)
    /metrics.json    registry_snapshot JSON — byte-identical to the
                     shard-K.json file-drop payload, so HTTP-scrape
                     federation merges the exact same input
    /healthz         liveness: 200 the moment the server thread runs;
                     touches NO application state (a wedged provider
                     still answers; a SIGSTOPped process times out)
    /readyz          readiness: 200 only when recovery is complete,
                     the brownout ladder is below reject-writes, and
                     the fencing epoch is current (a fenced corpse or
                     mid-recovery shard answers 503 + JSON detail)
    /statusz         one JSON page: role, epoch, slot/tier occupancy,
                     session table, SLO verdict, brownout level,
                     plan-cache hit rate, segment-residue fraction
    /debug/blackbox  flight-recorder ring + stats
    /debug/prof      kernel profile, host-op stats, device-memory gauges
    /debug/trace     bounded recent-span dump (``?n=`` caps the tail)
    /query           embedded-TSDB range query (ISSUE 19):
                     ``?name=…&labels=…&start=…&end=…&agg=…&tier=…``;
                     malformed params answer 400
    /debug/tsdb      TSDB store stats (series/points/bytes per tier)

Knobs (constructor-overridable, env-derived defaults like
``ClusterConfig``): ``YTPU_ADMIN_PORT`` (default 0 = ephemeral),
``YTPU_ADMIN_BIND`` (default 127.0.0.1), ``YTPU_ADMIN_DISABLED=1``
(never serve), ``YTPU_ADMIN_MAX_INFLIGHT`` (concurrent request bound —
excess requests get 503, so a scrape storm cannot pile threads onto the
GIL the flush hot path is using).

The server is duck-typed over a *target*: any object optionally
providing ``metrics_text()`` / ``metrics_snapshot()`` / ``statusz()`` /
``readiness()`` / ``trace_events()``.  Missing pieces fall back to the
process-global registry, so a bare ``AdminServer(None)`` is already a
useful metrics endpoint.  Handlers never let a target exception escape:
they render as a 500 with the error name, keeping the plane up while
the application misbehaves — that is exactly when it is needed.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = [
    "AdminConfig",
    "AdminServer",
    "admin_metrics",
    "maybe_start_admin",
]


def _env_int(name: str, default: int, lo: int = 0) -> int:
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        return default
    return max(lo, v)


class AdminConfig:
    """Admin-plane knobs (env-derived defaults, constructor wins)."""

    __slots__ = ("port", "bind", "disabled", "max_inflight")

    def __init__(
        self,
        port: int | None = None,
        bind: str | None = None,
        disabled: bool | None = None,
        max_inflight: int | None = None,
    ):
        self.port = (
            port if port is not None else _env_int("YTPU_ADMIN_PORT", 0)
        )
        self.bind = (
            bind
            if bind is not None
            else os.environ.get("YTPU_ADMIN_BIND", "127.0.0.1")
        )
        self.disabled = (
            disabled
            if disabled is not None
            else os.environ.get("YTPU_ADMIN_DISABLED", "") == "1"
        )
        self.max_inflight = (
            max_inflight
            if max_inflight is not None
            else _env_int("YTPU_ADMIN_MAX_INFLIGHT", 8, lo=1)
        )


class _AdminMetrics:
    """``ytpu_admin_*`` families on the process-global registry."""

    def __init__(self):
        from . import global_registry

        reg = global_registry()
        self.requests = reg.counter(
            "ytpu_admin_requests_total",
            "Admin-plane HTTP requests served, by endpoint and status "
            "code (shed = bounced by the inflight bound)",
            labelnames=("endpoint", "code"),
        )
        self.inflight = reg.gauge(
            "ytpu_admin_inflight",
            "Admin-plane HTTP requests currently being served",
        )


_ADMIN_METRICS: _AdminMetrics | None = None
_ADMIN_METRICS_LOCK = threading.Lock()


def admin_metrics() -> _AdminMetrics:
    # cold path (a few calls per scrape): plain lock, like rpc_metrics
    global _ADMIN_METRICS
    with _ADMIN_METRICS_LOCK:
        if _ADMIN_METRICS is None:
            _ADMIN_METRICS = _AdminMetrics()
        return _ADMIN_METRICS


# endpoint label values are a closed set so the requests counter cannot
# grow a series per probed path
_KNOWN_ENDPOINTS = frozenset({
    "/metrics", "/metrics.json", "/healthz", "/readyz", "/statusz",
    "/debug/blackbox", "/debug/prof", "/debug/trace",
    "/query", "/debug/tsdb",
})


class _AdminHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    admin: "AdminServer"

    def handle_error(self, request, client_address):
        pass  # a torn client connection is the client's problem


class _Handler(BaseHTTPRequestHandler):
    server_version = "ytpu-admin"

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, code: int, obj) -> None:
        body = json.dumps(obj, indent=1, sort_keys=True).encode("utf-8")
        self._reply(code, body + b"\n", "application/json")

    def do_GET(self):  # noqa: N802 - stdlib handler name
        admin = self.server.admin
        path, _, query = self.path.partition("?")
        endpoint = path if path in _KNOWN_ENDPOINTS else "other"
        m = admin_metrics()
        if not admin._gate.acquire(blocking=False):
            # over the inflight bound: shed instead of stacking reader
            # threads against the flush hot path's GIL time
            m.requests.labels(endpoint=endpoint, code=503).inc()
            try:
                self._reply_json(503, {"error": "admin busy"})
            except OSError:
                pass
            return
        m.inflight.inc()
        try:
            code = self._route(admin, path, query)
        except OSError:
            code = 0  # client went away mid-body; nothing to answer
        except Exception as e:  # target bug: keep the plane serving
            code = 500
            try:
                self._reply_json(
                    500, {"error": type(e).__name__, "detail": str(e)}
                )
            except OSError:
                pass
        finally:
            admin._gate.release()
            m.inflight.dec()
            if code:
                m.requests.labels(endpoint=endpoint, code=code).inc()

    def _route(self, admin: "AdminServer", path: str, query: str) -> int:
        if path == "/healthz":
            # liveness only: no target call, no lock — answering at all
            # IS the signal (a SIGSTOPped process times the probe out)
            self._reply(200, b"ok\n", "text/plain; charset=utf-8")
            return 200
        if path == "/metrics":
            body = admin.metrics_text().encode("utf-8")
            self._reply(
                200, body, "text/plain; version=0.0.4; charset=utf-8"
            )
            return 200
        if path == "/metrics.json":
            self._reply_json(200, admin.metrics_snapshot())
            return 200
        if path == "/readyz":
            verdict = admin.readiness()
            code = 200 if verdict.get("ready") else 503
            self._reply_json(code, verdict)
            return code
        if path == "/statusz":
            self._reply_json(200, admin.statusz())
            return 200
        if path == "/debug/blackbox":
            from .blackbox import flight_recorder

            bb = flight_recorder()
            self._reply_json(
                200, {"stats": bb.stats(), "events": bb.snapshot()}
            )
            return 200
        if path == "/debug/prof":
            self._reply_json(200, admin.prof())
            return 200
        if path == "/query":
            from urllib.parse import parse_qs

            params = {
                k: v[-1] for k, v in parse_qs(query).items() if v
            }
            try:
                result = admin.tsdb_query(params)
            except ValueError as e:
                self._reply_json(400, {"error": str(e)})
                return 400
            self._reply_json(200, result)
            return 200
        if path == "/debug/tsdb":
            self._reply_json(200, admin.tsdb_stats())
            return 200
        if path == "/debug/trace":
            n = 256
            for part in query.split("&"):
                if part.startswith("n="):
                    try:
                        n = max(1, int(part[2:]))
                    except ValueError:
                        pass
            events = admin.trace_events()
            self._reply_json(200, {
                "total": len(events),
                "events": events[-n:],
            })
            return 200
        self._reply_json(404, {"error": f"no endpoint {path}"})
        return 404


class AdminServer:
    """One process-embedded introspection endpoint (module docstring).

    ``target`` is duck-typed; ``role`` names the process in
    ``/statusz`` and readiness output.  ``start()`` binds and serves
    from a daemon thread; a disabled config makes ``start()`` a no-op
    (``port`` stays 0), so callers embed unconditionally and the knob
    decides."""

    def __init__(
        self,
        target=None,
        role: str = "process",
        config: AdminConfig | None = None,
    ):
        self.target = target
        self.role = role
        self.config = config if config is not None else AdminConfig()
        self._gate = threading.Semaphore(self.config.max_inflight)
        self._httpd: _AdminHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "AdminServer":
        if self.config.disabled or self._httpd is not None:
            return self
        httpd = _AdminHTTPServer(
            (self.config.bind, self.config.port), _Handler
        )
        httpd.admin = self
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            name=f"ytpu-admin-{self.role}",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else 0

    @property
    def url(self) -> str:
        if not self._httpd:
            return ""
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- target facade (each falls back to the process-global view) ---------

    def metrics_text(self) -> str:
        fn = getattr(self.target, "metrics_text", None)
        if fn is not None:
            return fn()
        from . import global_registry, prometheus_text

        return prometheus_text(global_registry())

    def metrics_snapshot(self) -> dict:
        fn = getattr(self.target, "metrics_snapshot", None)
        if fn is not None:
            return fn()
        from . import global_registry, registry_snapshot

        return registry_snapshot(global_registry())

    def readiness(self) -> dict:
        fn = getattr(self.target, "readiness", None)
        if fn is not None:
            verdict = fn()
        else:
            verdict = {"ready": True, "checks": {}}
        verdict.setdefault("role", self.role)
        return verdict

    def statusz(self) -> dict:
        fn = getattr(self.target, "statusz", None)
        status = fn() if fn is not None else {}
        status.setdefault("role", self.role)
        status.setdefault("pid", os.getpid())
        status.setdefault("ready", bool(self.readiness().get("ready")))
        return status

    def prof(self) -> dict:
        out: dict = {}
        try:
            from .prof import kernel_profiler

            p = kernel_profiler()
            out["kernel"] = p.snapshot()
            out["host_ops"] = p.host_op_stats()
        except Exception as e:
            out["kernel_error"] = type(e).__name__
        # device-memory gauges live on the engine registry when the
        # target is provider-backed; surface them when reachable
        snap = {}
        try:
            snap = self.metrics_snapshot()
        except Exception:
            pass
        gauges = (snap.get("gauges") or {}) if isinstance(snap, dict) else {}
        out["device_memory"] = {
            name: series
            for name, series in gauges.items()
            if name.startswith("ytpu_prof_device_")
        }
        return out

    def trace_events(self) -> list:
        fn = getattr(self.target, "trace_events", None)
        if fn is not None:
            return fn()
        return []

    def tsdb_query(self, params: dict) -> dict:
        """``/query``: target override (the supervisor federates shard
        stores here) falling back to the process-global TSDB."""
        fn = getattr(self.target, "tsdb_query", None)
        if fn is not None:
            return fn(params)
        from .tsdb import tsdb

        return tsdb().query_params(params)

    def tsdb_stats(self) -> dict:
        fn = getattr(self.target, "tsdb_stats", None)
        if fn is not None:
            return fn()
        from .tsdb import tsdb, tsdb_enabled

        out = tsdb().stats()
        out["enabled"] = tsdb_enabled()
        return out


def maybe_start_admin(
    target, role: str, config: AdminConfig | None = None
) -> AdminServer | None:
    """Embed-and-start for library-constructed objects (TpuProvider,
    FleetRouter): serves only when the operator opted in by setting
    ``YTPU_ADMIN_PORT`` — a test constructing 200 providers must not
    open 200 sockets.  Cluster processes (shard/gateway/supervisor)
    construct :class:`AdminServer` directly and default to ON instead,
    since one process embeds exactly one plane."""
    if config is None:
        if "YTPU_ADMIN_PORT" not in os.environ:
            return None
        config = AdminConfig()
    if config.disabled:
        return None
    try:
        return AdminServer(target, role=role, config=config).start()
    except OSError:
        # a fixed YTPU_ADMIN_PORT already taken (second provider in
        # one process): the app must come up anyway — no admin plane
        # beats no process
        return None
