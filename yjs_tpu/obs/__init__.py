"""yjs_tpu.obs: observability for the engine/provider stack.

Four pieces (ISSUE 1 tentpole):

- :mod:`.registry` — zero-dependency counters/gauges/log-bucketed
  histograms, cheap enough to stay on in the flush hot path;
- :mod:`.history` — the bounded flush-history ring superseding the
  overwrite-only ``last_flush_metrics`` (which remains as a
  compatibility view of the newest entry);
- :mod:`.trace` — host-side phase spans exported as Chrome-trace JSON,
  layered on the existing ``jax.profiler.TraceAnnotation`` wrappers;
- :mod:`.expo` — Prometheus text dump + JSON snapshot.

Fleet-wide observability (ISSUE 11):

- :mod:`.dist` — deterministic cross-provider trace contexts
  (``YTPU_TRACE_SAMPLE`` head sampling, envelope carry, hash-derived
  flow ids);
- :mod:`.blackbox` — the always-on black-box flight recorder
  (``YTPU_BLACKBOX{,_CAP,_DIR}``), auto-dumped on quarantine /
  failover / ``ProviderFullError`` / flush exceptions;
- :mod:`.federate` — N-shard metric federation (counters sum, gauges
  keep per-shard series, histograms merge) shared by
  ``FleetRouter.metrics_snapshot``, ``ytpu_top`` and ``ytpu_stats``.

Env knobs: ``YTPU_OBS_DISABLED=1`` (no-op registry + tracer; the flush
history stays on so ``last_flush_metrics`` keeps its contract),
``YTPU_OBS_HISTORY`` (ring size, default 128), ``YTPU_TRACE_PATH``
(write a merged Chrome trace at interpreter exit), ``YTPU_TRACE_EVENTS``
(per-tracer event cap, default 200k).
"""

from __future__ import annotations

import os

from .expo import prometheus_text, registry_snapshot  # noqa: F401
from .history import FlushHistory  # noqa: F401
from .registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NOOP_METRIC,
)
from .trace import Tracer  # noqa: F401
from .blackbox import (  # noqa: F401
    FlightRecorder,
    flight_recorder,
    reset_flight_recorder,
)
from .dist import (  # noqa: F401
    TraceContext,
    current_context,
    flow_id_for,
    mint_for_update,
    trace_metrics,
    use_context,
)
from .federate import (  # noqa: F401
    FederationMetrics,
    fed_metrics,
    federate_snapshots,
    merge_summaries,
    read_snapshot_dir,
    scrape_endpoints,
)
from .admin import (  # noqa: F401
    AdminConfig,
    AdminServer,
    maybe_start_admin,
)
from .tsdb import (  # noqa: F401
    Tsdb,
    TsdbConfig,
    maybe_attach_tsdb,
    tsdb,
    tsdb_enabled,
    tsdb_metrics,
    tsdb_window,
)
from .cost import CostLedger, cost_enabled  # noqa: F401
from .capacity import (  # noqa: F401
    CapacityConfig,
    ramp_capacity,
    read_knee,
    sessions_per_device,
)

SNAPSHOT_SCHEMA_VERSION = 1

# -- the per-flush metrics schema -------------------------------------------
# ONE constructor for every flush exit (apply / levels / seq / batched /
# empty-early-return): the paths previously shared these keys by
# convention only, and a drift was silent until a consumer KeyError'd.
# tests/test_obs.py pins identical key sets across all modes.
FLUSH_METRICS_SCHEMA: dict = {
    "n_docs_flushed": 0,
    "n_demoted": 0,
    # docs transactionally rolled back (and demoted) by failure
    # isolation during this flush — always <= n_demoted
    "n_rolled_back": 0,
    "n_fallback_docs": 0,
    "n_rows_max": 0,
    "n_sched_entries": 0,
    "n_levels": 0,
    "level_width": 0,
    "schedule_occupancy": 0.0,
    "n_pending_docs": 0,
    "pending_depth": 0,
    # planner fan-out this flush actually used: the native planner's
    # worker-pool width (min(pool width, docs in the batch);
    # YTPU_PLAN_THREADS overrides the pool), or — on the Python path
    # under YTPU_PLAN_SEGMENT=device — the number of cold docs
    # co-planned by one whole-chunk segment-planner call (ISSUE 15).
    # 1 = fully serial per-doc planning.
    "plan_threads": 1,
    # frontier-keyed plan cache (ISSUE 9): probes served from cache /
    # planned cold this flush, and structs placed by the segment-sorted
    # fast path instead of the sequential YATA walk
    "plan_cache_hits": 0,
    "plan_cache_misses": 0,
    "plan_fastpath_structs": 0,
    # device-authoritative segment planner (ISSUE 15): structs
    # integrated straight from device-computed ranks (fast set) vs
    # handed to the sequential YATA conflict fallback (residue)
    "plan_segment_fast": 0,
    "plan_segment_residue": 0,
    "t_compact_s": 0.0,
    "t_plan_s": 0.0,
    # t_plan_s split: snapshot-adoption time for cache hits vs cold
    # prepare time (t_plan_cached_s + t_plan_cold_s <= t_plan_s)
    "t_plan_cached_s": 0.0,
    "t_plan_cold_s": 0.0,
    "t_pack_s": 0.0,
    "t_dispatch_s": 0.0,
    "t_emit_s": 0.0,
    "t_total_s": 0.0,
    # pipelined flush (ISSUE 12): host pack time that overlapped an
    # in-flight device dispatch, and host time spent blocked on the
    # device (staging-buffer reuse guards + YTPU_FLUSH_PIPELINE=0's
    # per-dispatch barrier).  Pipeline-off, overlap is 0 and the wait
    # is the full device time; pipeline-on, pack overlap is the payoff
    # and wait shrinks to the true dependency stalls.
    "t_pack_overlap_s": 0.0,
    "t_device_wait_s": 0.0,
    # 1 when every device dispatch of this flush updated donated
    # resident tables in place (no table growth/reallocation since the
    # previous flush); realloc_bytes is the growth cost when it is 0
    "flush_donated": 0,
    "realloc_bytes": 0,
    # max device dispatches in flight at once (0 = no dispatch or
    # synchronous mode; the double-buffered staging pair bounds it)
    "pipeline_depth": 0,
}

FLUSH_PHASES = ("compact", "plan", "pack", "dispatch", "emit")


def new_flush_metrics(**overrides) -> dict:
    """A fresh flush-metrics dict with every schema key present.

    Unknown keys raise: a new metric must be added to the schema (and
    the README table) first, so the exposed key set cannot drift."""
    unknown = set(overrides) - set(FLUSH_METRICS_SCHEMA)
    if unknown:
        raise KeyError(
            f"not in FLUSH_METRICS_SCHEMA: {sorted(unknown)}"
        )
    m = dict(FLUSH_METRICS_SCHEMA)
    m.update(overrides)
    return m


def obs_enabled() -> bool:
    return os.environ.get("YTPU_OBS_DISABLED") != "1"


# -- process-global registry -------------------------------------------------
# Serves module-level consumers with no engine handle (the y-protocols
# sync framing).  Engine/provider exposition merges it in.

_GLOBAL: MetricsRegistry | None = None


def global_registry() -> MetricsRegistry:
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = MetricsRegistry(enabled=obs_enabled())
        # pre-register the protocol family so exposition (and the schema
        # checker) sees it before the first frame is read/written
        _GLOBAL.counter(
            "ytpu_sync_messages_total",
            "y-protocols sync frames processed by yjs_tpu.sync.protocol",
            labelnames=("dir", "type"),
        )
        _GLOBAL.counter(
            "ytpu_chaos_faults_total",
            "Faults injected by the chaos harness, by fault kind",
            labelnames=("fault",),
        )
    return _GLOBAL


class EngineObs:
    """Per-engine observability bundle: registry + flush ring + tracer.

    Every instrument the flush hot path touches is pre-created here so
    recording is attribute access + arithmetic — no name resolution, no
    label resolution (phase children are pre-resolved)."""

    def __init__(self, history_len: int | None = None):
        self.enabled = obs_enabled()
        self.registry = MetricsRegistry(enabled=self.enabled)
        self.history = FlushHistory(maxlen=history_len)
        self.tracer = Tracer(enabled=self.enabled)
        # the process-global black box records even with metrics
        # disabled (it is forensics, not telemetry); trace metrics are
        # registered here so the schema checker sees the families after
        # one provider construction
        self.blackbox = flight_recorder()
        self.blackbox._obs()
        trace_metrics()
        r = self.registry
        self._flushes = r.counter(
            "ytpu_engine_flushes_total", "Engine flushes run"
        )
        self._docs_flushed = r.counter(
            "ytpu_engine_docs_flushed_total",
            "Docs integrated with visible work, summed over flushes",
        )
        self._updates_emitted = r.counter(
            "ytpu_engine_updates_emitted_total",
            "Incremental updates emitted via doc.on('update')",
        )
        self._egress_bytes = r.counter(
            "ytpu_engine_update_egress_bytes_total",
            "Bytes of emitted incremental updates",
            unit="bytes",
        )
        self._demotions = r.counter(
            "ytpu_engine_demotions_total",
            "Device->CPU demotions by reason",
            labelnames=("reason",),
        )
        self._fallback_docs = r.gauge(
            "ytpu_engine_fallback_docs", "Docs currently on the CPU core"
        )
        self._pending_docs = r.gauge(
            "ytpu_engine_pending_docs",
            "Docs with parked (causally unready) traffic after last flush",
        )
        self._pending_depth = r.gauge(
            "ytpu_engine_pending_depth",
            "Total parked struct depth after last flush",
        )
        self._occupancy = r.gauge(
            "ytpu_engine_schedule_occupancy",
            "Real fraction of dispatched schedule/lane slots, last flush",
            unit="ratio",
        )
        self._plan_threads = r.gauge(
            "ytpu_engine_plan_threads", "Native planner worker-pool width"
        )
        self._row_capacity = r.gauge(
            "ytpu_engine_row_capacity",
            "Device row capacity (per doc) after last flush",
            unit="rows",
        )
        # segment-planner residue (ISSUE 16 satellite): the live number
        # the residue-elimination work drives against — fraction of
        # planned structs the device fast path could NOT place and
        # handed to the sequential YATA conflict fallback
        self._segment_residue_fraction = r.gauge(
            "ytpu_plan_segment_residue_fraction",
            "Fraction of planned structs handed to the sequential YATA "
            "conflict fallback, last flush with planner work "
            "(residue / (fast + residue))",
            unit="ratio",
        )
        self._flush_seconds = r.histogram(
            "ytpu_engine_flush_seconds", "End-to-end flush wall time",
            unit="s",
        )
        self._phase_seconds = r.histogram(
            "ytpu_engine_phase_seconds",
            "Per-phase flush wall time",
            unit="s",
            labelnames=("phase",),
        )
        self._phase_children = {
            ph: self._phase_seconds.labels(phase=ph) for ph in FLUSH_PHASES
        }
        self._native_prepare_seconds = r.histogram(
            "ytpu_native_prepare_many_seconds",
            "One ymx_prepare_many batch (stage + plan), per call",
            unit="s",
        )
        self._native_prepare_docs = r.histogram(
            "ytpu_native_prepare_many_docs",
            "Docs planned per ymx_prepare_many call",
            unit="docs",
        )
        self._rollbacks = r.counter(
            "ytpu_resilience_rollbacks_total",
            "Per-doc transactional flush rollbacks by reason",
            labelnames=("reason",),
        )
        self._dead_letters = r.counter(
            "ytpu_resilience_dead_letters_total",
            "Updates diverted to the dead-letter queue by reason",
            labelnames=("reason",),
        )
        self._dlq_depth = r.gauge(
            "ytpu_resilience_dead_letter_depth",
            "Dead letters currently held in the bounded queue",
        )
        self._dlq_dropped = r.counter(
            "ytpu_resilience_dead_letters_dropped_total",
            "Dead letters evicted (oldest-first) by the capacity bound",
        )
        self._docs_degraded = r.gauge(
            "ytpu_resilience_docs_degraded",
            "Docs currently in the degraded health state",
        )
        self._docs_quarantined = r.gauge(
            "ytpu_resilience_docs_quarantined",
            "Docs currently quarantined (traffic diverted to dead letters)",
        )
        self._readmissions = r.counter(
            "ytpu_resilience_readmissions_total",
            "Quarantined docs re-admitted after backoff expiry",
        )
        self._replayed = r.counter(
            "ytpu_resilience_replayed_total",
            "Dead letters successfully re-integrated by replay()",
        )
        self._replay_truncated = r.counter(
            "ytpu_resilience_dlq_replay_truncated_total",
            "Matching dead letters left queued by the per-invocation "
            "replay batch cap (YTPU_DLQ_REPLAY_BATCH)",
        )
        # device-memory cost attribution (ISSUE 4): refreshed once per
        # flush from the engine's persistent device buffers
        self._device_table_bytes = r.gauge(
            "ytpu_prof_device_table_bytes",
            "Live device bytes per persistent doc-table column group",
            unit="bytes",
            labelnames=("table",),
        )
        self._device_bytes_total = r.gauge(
            "ytpu_prof_device_bytes_total",
            "Total live persistent device bytes, by backend platform",
            unit="bytes",
            labelnames=("backend",),
        )
        self._slot_occupancy = r.gauge(
            "ytpu_prof_slot_occupancy",
            "Fraction of engine doc slots holding live rows",
            unit="ratio",
        )
        # pipelined flush (ISSUE 12): overlap/donation accounting
        self._flush_pipeline_depth = r.gauge(
            "ytpu_flush_pipeline_depth",
            "Max device dispatches in flight during the last flush "
            "(0 = synchronous / no dispatch)",
        )
        self._flush_pack_overlap = r.histogram(
            "ytpu_flush_pack_overlap_seconds",
            "Host pack time spent while a device dispatch was "
            "outstanding (not yet blocked on), per flush",
            unit="s",
        )
        self._flush_device_wait = r.histogram(
            "ytpu_flush_device_wait_seconds",
            "Host time blocked waiting on device dispatches, per flush",
            unit="s",
        )
        self._flush_donated = r.counter(
            "ytpu_flush_donated_total",
            "Flushes whose dispatches all updated donated device tables "
            "in place (zero table reallocation)",
        )
        self._flush_realloc_bytes = r.counter(
            "ytpu_flush_realloc_bytes_total",
            "Device bytes allocated by resident-table growth (the cost "
            "a donated steady-state flush avoids)",
            unit="bytes",
        )

    # -- hot-path recording hooks -------------------------------------

    def record_flush(self, metrics: dict, row_capacity: int = 0) -> None:
        """One flush finished: ring append + registry update."""
        self.history.append(metrics)
        if not self.enabled:
            return
        self._flushes.inc()
        self._docs_flushed.inc(metrics["n_docs_flushed"])
        self._fallback_docs.set(metrics["n_fallback_docs"])
        self._pending_docs.set(metrics["n_pending_docs"])
        self._pending_depth.set(metrics["pending_depth"])
        self._occupancy.set(metrics["schedule_occupancy"])
        self._plan_threads.set(metrics["plan_threads"])
        self._row_capacity.set(row_capacity)
        self._flush_seconds.observe(metrics["t_total_s"])
        for ph, child in self._phase_children.items():
            child.observe(metrics[f"t_{ph}_s"])
        planned = (
            metrics["plan_segment_fast"] + metrics["plan_segment_residue"]
        )
        if planned:
            # idle flushes keep the last real verdict on the gauge
            self._segment_residue_fraction.set(
                metrics["plan_segment_residue"] / planned
            )
        self._flush_pipeline_depth.set(metrics["pipeline_depth"])
        self._flush_pack_overlap.observe(metrics["t_pack_overlap_s"])
        self._flush_device_wait.observe(metrics["t_device_wait_s"])
        if metrics["flush_donated"]:
            self._flush_donated.inc()
        if metrics["realloc_bytes"]:
            self._flush_realloc_bytes.inc(metrics["realloc_bytes"])

    def demoted(self, doc: int, reason: str) -> None:
        ctx = current_context()
        self.blackbox.record(
            "engine", "demote", guid=None, doc=doc, reason=reason,
            trace=ctx.trace_hex if ctx else None,
        )
        if not self.enabled:
            return
        self._demotions.labels(reason=reason).inc()
        self.tracer.instant("ytpu.demote", doc=doc, reason=reason)

    def update_emitted(self, n_bytes: int) -> None:
        if not self.enabled:
            return
        self._updates_emitted.inc()
        self._egress_bytes.inc(n_bytes)

    def native_prepare(self, n_docs: int, dt_s: float) -> None:
        if not self.enabled:
            return
        self._native_prepare_seconds.observe(dt_s)
        self._native_prepare_docs.observe(n_docs)

    def device_memory(
        self, tables: dict, backend: str, occupancy: float
    ) -> None:
        """Per-table live device bytes + slot occupancy (post-flush)."""
        if not self.enabled:
            return
        total = 0
        for table, nbytes in tables.items():
            self._device_table_bytes.labels(table=table).set(nbytes)
            total += nbytes
        self._device_bytes_total.labels(backend=backend).set(total)
        self._slot_occupancy.set(occupancy)

    # -- resilience hooks ----------------------------------------------

    def rollback(self, doc: int, reason: str) -> None:
        ctx = current_context()
        if ctx is not None:
            ctx.force("rollback")
        self.blackbox.record(
            "engine", "rollback", severity="warning", doc=doc,
            reason=reason, trace=ctx.trace_hex if ctx else None,
        )
        if not self.enabled:
            return
        self._rollbacks.labels(reason=reason).inc()
        self.tracer.instant(
            "ytpu.rollback", doc=doc, reason=reason,
            **({"trace": ctx.trace_hex} if ctx else {}),
        )

    def dead_lettered(self, reason: str, depth: int, dropped: int) -> None:
        ctx = current_context()
        if ctx is not None:
            ctx.force("dlq")
        self.blackbox.record(
            "resilience", "dead_letter", severity="warning",
            reason=reason, depth=depth,
            trace=ctx.trace_hex if ctx else None,
        )
        if not self.enabled:
            return
        # group by the reason's stable prefix so a poison storm with
        # per-byte exception detail cannot explode label cardinality
        self._dead_letters.labels(reason=reason.split(":", 1)[0]).inc()
        self._dlq_depth.set(depth)
        # `dropped` is the queue's monotonic total; counters only inc,
        # so mirror the delta since the last call
        seen = getattr(self, "_dlq_dropped_seen", 0)
        if dropped > seen:
            self._dlq_dropped.inc(dropped - seen)
            self._dlq_dropped_seen = dropped

    def health_gauges(self, degraded: int, quarantined: int) -> None:
        if not self.enabled:
            return
        self._docs_degraded.set(degraded)
        self._docs_quarantined.set(quarantined)

    def readmitted(self) -> None:
        if not self.enabled:
            return
        self._readmissions.inc()

    def replayed(self, n: int) -> None:
        if not self.enabled or n <= 0:
            return
        self._replayed.inc(n)

    def replay_truncated(self, n: int) -> None:
        if not self.enabled or n <= 0:
            return
        self._replay_truncated.inc(n)

    # -- exposition ----------------------------------------------------

    def metrics_text(self) -> str:
        return prometheus_text(self.registry, global_registry())

    def snapshot(self) -> dict:
        snap = registry_snapshot(self.registry, global_registry())
        snap["schema"] = SNAPSHOT_SCHEMA_VERSION
        latest = self.history.latest
        snap["flush"] = dict(latest) if latest is not None else None
        snap["flush_history"] = self.history.snapshot()
        snap["n_flushes_recorded"] = self.history.total
        return snap


class TierMetrics:
    """The ``ytpu_tier_*`` families (ISSUE 7): doc-lifecycle tiering.

    Registered unconditionally at provider construction (the schema
    checker instantiates ``TpuProvider(1)`` and expects every family
    live) on the provider's engine registry, so per-shard fleets get
    per-shard tier series like every other engine family."""

    TIERS = ("hot", "warm", "cold")

    def __init__(self, registry: MetricsRegistry):
        r = registry
        self._docs = r.gauge(
            "ytpu_tier_docs",
            "Docs resident per lifecycle tier (hot=device slot, "
            "warm=detached host columns, cold=WAL tier record)",
            labelnames=("tier",),
        )
        self._bytes = r.gauge(
            "ytpu_tier_bytes",
            "Approximate bytes held by demoted docs, per tier "
            "(warm: host mirrors; cold: encoded state blobs/records)",
            unit="bytes",
            labelnames=("tier",),
        )
        self._transitions = r.counter(
            "ytpu_tier_transitions_total",
            "Tier transitions, by source and destination tier",
            labelnames=("src", "dst"),
        )
        self._promote_seconds = r.histogram(
            "ytpu_tier_promote_seconds",
            "Wall time to promote one doc back into a device slot, "
            "by source tier",
            unit="s",
            labelnames=("src",),
        )
        self._demote_seconds = r.histogram(
            "ytpu_tier_demote_seconds",
            "Wall time to demote one doc, by destination tier",
            unit="s",
            labelnames=("dst",),
        )
        self._evictions = r.counter(
            "ytpu_tier_evictions_total",
            "Hot docs auto-demoted to admit another doc (the path that "
            "previously raised ProviderFullError)",
        )
        self._gc_passes = r.counter(
            "ytpu_tier_gc_passes_total",
            "Forced tombstone/GC compaction passes over hot docs",
        )
        self._gc_rows = r.counter(
            "ytpu_tier_gc_reclaimed_rows_total",
            "Packed-column rows dropped by tier GC compaction",
        )
        self._gc_bytes = r.counter(
            "ytpu_tier_gc_reclaimed_bytes_total",
            "Approximate host-mirror bytes reclaimed by tier GC "
            "compaction",
            unit="bytes",
        )
        # pre-resolve label children: transitions/demotes run inside the
        # admission path
        self._docs_by_tier = {
            t: self._docs.labels(tier=t) for t in self.TIERS
        }
        self._bytes_by_tier = {
            t: self._bytes.labels(tier=t) for t in self.TIERS
        }

    def occupancy(self, counts: dict, nbytes: dict) -> None:
        for t in self.TIERS:
            self._docs_by_tier[t].set(counts.get(t, 0))
            self._bytes_by_tier[t].set(nbytes.get(t, 0))

    def transition(self, src: str, dst: str) -> None:
        self._transitions.labels(src=src, dst=dst).inc()

    def promoted(self, src: str, dt_s: float) -> None:
        self._promote_seconds.labels(src=src).observe(dt_s)

    def demoted(self, dst: str, dt_s: float) -> None:
        self._demote_seconds.labels(dst=dst).observe(dt_s)

    def evicted(self) -> None:
        self._evictions.inc()

    def gc(self, rows: int, nbytes: int) -> None:
        self._gc_passes.inc()
        if rows > 0:
            self._gc_rows.inc(rows)
        if nbytes > 0:
            self._gc_bytes.inc(nbytes)
