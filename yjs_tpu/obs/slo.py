"""yjs_tpu.obs.slo: end-to-end convergence latency + burn-rate SLOs.

What a collaborator actually feels is not flush wall time — it is the
latency from an edit leaving its origin until every replica can read it.
This module measures that WITHOUT touching the wire format: an update is
keyed by the natural identity it already carries, the (client, clock) of
its first struct block (v1 layout: numClients, then numStructs, client,
clock — four varints in).  Delete-only payloads, v2 updates, and
unparseable bytes fall back to a CRC of the exact transported bytes;
both sides of a link compute the key from the same bytes, so the
fallback converges too.

Pipeline per update (Dapper-style causal stages, one flow id):

    origin ──> receive ──> integrate ──> visible
    (first    (provider    (queue_update  (provider.flush
     sighting  ingests)     accepts)       returns: readable)

``origin`` is stamped in a process-global :class:`OriginClock` the first
time any provider in the process sees the key — the emitting provider
stamps it at broadcast, so in-process relay chains measure true
end-to-end latency; cross-process receivers (no shared clock) floor the
origin at their own receive time, making every stage after transport
still attributable.

Burn-rate monitoring follows the Monarch/Prometheus multi-window rule:
breach fraction over a long window (``YTPU_SLO_WINDOW``, default 300 s)
and a short window (long/12), each divided by the error budget
(1 - ``YTPU_SLO_OBJECTIVE``).  Both windows >= 14.4 -> ``page``; both
>= 6 -> ``warning``; else ``ok``.  The convergence target is
``YTPU_SLO_CONVERGENCE_MS`` (default 250 ms).
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from collections import OrderedDict, deque

from ..lib0 import decoding
from ..lib0.decoding import Decoder
from .dist import flow_id_for

# classic multiwindow burn thresholds: 14.4x burns a 30-day budget in
# ~2 days (page); 6x in ~5 days (ticket/warning)
PAGE_BURN = 14.4
WARN_BURN = 6.0

DEFAULT_TARGET_MS = 250.0
DEFAULT_WINDOW_S = 300.0
DEFAULT_OBJECTIVE = 0.99

STAGES = ("receive", "integrate", "visible")
_STATE_CODES = {"ok": 0, "warning": 1, "page": 2}

# flow ids are hash-derived from the update key (ISSUE 11 satellite):
# the previous process-global counter restarted numbering relative to
# surviving events after a YTPU_TRACE_EVENTS cap truncation, so a
# truncated trace could pair a new flow-start with a stale flow-end of
# the same id.  A keyed hash is stable under truncation AND matches
# across providers/processes, which is what lets one update's
# convergence arrows stitch into a single cross-peer trace.


def update_key(update: bytes, v2: bool = False) -> tuple[int, int]:
    """The natural identity of an update: (client, clock) of its first
    struct block; ``(-1, crc32)`` for delete-only/v2/unparseable bytes.

    Pure read of the leading varints — never decodes structs, never
    copies, zero wire-format impact."""
    if not v2:
        try:
            dec = Decoder(bytes(update))
            if decoding.read_var_uint(dec):  # numClients >= 1
                decoding.read_var_uint(dec)  # numStructs (skipped)
                client = decoding.read_var_uint(dec)
                clock = decoding.read_var_uint(dec)
                return (client, clock)
        except Exception:
            pass
    return (-1, zlib.crc32(bytes(update)))


class OriginClock:
    """Bounded first-sighting timestamps, shared by every provider in
    the process (the emitting provider stamps; receivers look up)."""

    def __init__(self, maxlen: int = 8192):
        self._t: OrderedDict = OrderedDict()
        self.maxlen = maxlen
        # process-global instance: emitters stamp from provider threads
        # while receivers look up — OrderedDict reorders on eviction
        self._lock = threading.Lock()

    def record_once(self, key, t: float) -> None:
        with self._lock:
            if key in self._t:
                return
            self._t[key] = t
            while len(self._t) > self.maxlen:
                self._t.popitem(last=False)

    def lookup(self, key):
        with self._lock:
            return self._t.get(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._t)


_ORIGINS = OriginClock()


def origin_clock() -> OriginClock:
    """The process-global origin clock (tests may build private ones)."""
    return _ORIGINS


class ConvergenceTracker:
    """Per-provider convergence pipeline timestamps + SLO burn state.

    ``now`` is injectable for deterministic tests; instruments register
    on the provider's engine registry so one exposition call covers
    them.  All hooks are no-ops under a disabled registry."""

    def __init__(
        self,
        registry,
        tracer=None,
        now=time.perf_counter,
        origins: OriginClock | None = None,
        target_ms: float | None = None,
        window_s: float | None = None,
        objective: float | None = None,
        max_pending: int = 4096,
        max_events: int = 65536,
    ):
        self.enabled = getattr(registry, "enabled", True)
        self.tracer = tracer
        self._now = now
        self._origins = origins if origins is not None else _ORIGINS
        self.target_ms = (
            target_ms
            if target_ms is not None
            else _env_float("YTPU_SLO_CONVERGENCE_MS", DEFAULT_TARGET_MS)
        )
        self.window_s = (
            window_s
            if window_s is not None
            else _env_float("YTPU_SLO_WINDOW", DEFAULT_WINDOW_S)
        )
        self.short_window_s = max(1.0, self.window_s / 12.0)
        self.objective = (
            objective
            if objective is not None
            else _env_float("YTPU_SLO_OBJECTIVE", DEFAULT_OBJECTIVE)
        )
        self.max_pending = max_pending
        # guards _pending and _events: exposition scrapes re-evaluate
        # the burn windows from other threads while a flush completes
        # pipelines (deque/dict iteration tears under mutation)
        self._lock = threading.Lock()
        # key -> [t_origin, t_receive, t_integrate, flow_id, trace_hex]
        self._pending: OrderedDict = OrderedDict()
        # (t_visible, breached) completions feeding the burn windows
        self._events: deque = deque(maxlen=max_events)
        self._completed = 0
        self._state = "ok"
        self._burns = {"short": 0.0, "long": 0.0}
        self._windows = {
            w: {"total": 0, "breached": 0, "breach_fraction": 0.0}
            for w in ("short", "long")
        }
        r = registry
        self._latency = r.histogram(
            "ytpu_convergence_latency_seconds",
            "End-to-end origin->visible latency per converged update",
            unit="s",
        )
        stage = r.histogram(
            "ytpu_convergence_stage_seconds",
            "Per-stage convergence latency (receive: origin->ingest; "
            "integrate: ingest->queued; visible: queued->flushed)",
            unit="s",
            labelnames=("stage",),
        )
        self._stage = {s: stage.labels(stage=s) for s in STAGES}
        self._m_completed = r.counter(
            "ytpu_slo_convergence_total",
            "Updates that completed the convergence pipeline",
        )
        self._m_breaches = r.counter(
            "ytpu_slo_breaches_total",
            "Converged updates whose end-to-end latency exceeded "
            "YTPU_SLO_CONVERGENCE_MS",
        )
        burn = r.gauge(
            "ytpu_slo_burn_rate",
            "Error-budget burn rate per SLO window (>=14.4 on both "
            "windows pages)",
            labelnames=("window",),
        )
        self._burn = {w: burn.labels(window=w) for w in ("short", "long")}
        self._m_state = r.gauge(
            "ytpu_slo_state",
            "Burn-rate alert state: 0 ok, 1 warning, 2 page",
        )

    # -- pipeline stages ----------------------------------------------

    def origin(self, update: bytes, v2: bool = False):
        """Stamp first-sighting time for an emitted update (no-op when
        the key was already stamped — e.g. a relay of foreign bytes)."""
        if not self.enabled:
            return None
        key = update_key(update, v2)
        self._origins.record_once(key, self._now())
        return key

    def receive(self, update: bytes, v2: bool = False, guid=None,
                trace=None):
        """An update entered this provider; returns its tracking key.
        ``trace`` is the ingress :class:`~yjs_tpu.obs.dist.TraceContext`
        when one is in flight — sampled contexts stamp their trace id
        onto the convergence flow arrows so the per-update flow joins
        the cross-provider trace."""
        if not self.enabled:
            return None
        key = update_key(update, v2)
        t = self._now()
        # cross-process senders share no clock: floor origin at receive
        self._origins.record_once(key, t)
        with self._lock:
            if key in self._pending:  # duplicate delivery: first one wins
                return key
            flow_id = flow_id_for(key)
            self._pending[key] = [
                self._origins.lookup(key), t, None, flow_id,
                trace.trace_hex if trace is not None and trace.sampled
                else None,
            ]
            while len(self._pending) > self.max_pending:
                self._pending.popitem(last=False)
        if self.tracer is not None:
            args = {"client": key[0], "clock": key[1], "guid": guid}
            if trace is not None and trace.sampled:
                args["trace"] = trace.trace_hex
            self.tracer.flow_start("ytpu.convergence", flow_id, **args)
        return key

    def integrated(self, key) -> None:
        """The update was accepted into the engine queue."""
        with self._lock:
            rec = self._pending.get(key) if key is not None else None
            if rec is not None and rec[2] is None:
                rec[2] = self._now()

    def rejected(self, key) -> None:
        """The update was diverted (dead-lettered): stop tracking it."""
        if key is not None:
            with self._lock:
                self._pending.pop(key, None)

    def visible(self, tracer=None) -> int:
        """A flush completed: every integrated pending update is now
        readable on this replica — close its pipeline.  Call INSIDE the
        flush span so the flow-end events bind to it in Perfetto."""
        if not self.enabled or not self._pending:  # ytpu-lint: disable=lock-discipline -- benign racy precheck: dict truthiness is atomic; a just-added pending closes on the next flush tick
            return 0
        if tracer is None:
            tracer = self.tracer
        t = self._now()
        with self._lock:
            done = [
                (k, self._pending.pop(k))
                for k in [
                    k for k, rec in self._pending.items()
                    if rec[2] is not None
                ]
            ]
        for k, rec in done:
            t_origin, t_recv, t_int, flow_id, trace_hex = rec
            total = max(0.0, t - t_origin)
            self._latency.observe(total)
            self._stage["receive"].observe(max(0.0, t_recv - t_origin))
            self._stage["integrate"].observe(max(0.0, t_int - t_recv))
            self._stage["visible"].observe(max(0.0, t - t_int))
            breached = total * 1000.0 > self.target_ms
            self._m_completed.inc()
            if breached:
                self._m_breaches.inc()
            with self._lock:
                self._events.append((t, breached))
            self._completed += 1
            if tracer is not None:
                args = {
                    "latency_ms": round(total * 1000.0, 3),
                    "breached": breached,
                }
                if trace_hex is not None:
                    args["trace"] = trace_hex
                tracer.flow_end("ytpu.convergence", flow_id, **args)
        if done:
            self._update_state()
        return len(done)

    # -- burn-rate state ----------------------------------------------

    def _update_state(self) -> None:
        now = self._now()
        budget = max(1e-9, 1.0 - self.objective)
        burns = {}
        windows = {}
        with self._lock:
            events = tuple(self._events)
        for wname, wlen in (
            ("short", self.short_window_s), ("long", self.window_s)
        ):
            total = breached = 0
            for t, b in reversed(events):
                if now - t > wlen:
                    break
                total += 1
                if b:
                    breached += 1
            frac = breached / total if total else 0.0
            burns[wname] = frac / budget
            windows[wname] = {
                "total": total,
                "breached": breached,
                "breach_fraction": frac,
            }
        worst_common = min(burns.values())
        if worst_common >= PAGE_BURN:
            state = "page"
        elif worst_common >= WARN_BURN:
            state = "warning"
        else:
            state = "ok"
        self._burns = burns
        self._windows = windows
        self._state = state
        self._burn["short"].set(burns["short"])
        self._burn["long"].set(burns["long"])
        self._m_state.set(_STATE_CODES[state])

    def state(self) -> str:
        """Current burn-rate verdict (``ok``/``warning``/``page``),
        re-evaluated so aged-out windows decay — cheap enough for the
        admission controller to poll every tick."""
        if self.enabled and self._events:  # ytpu-lint: disable=lock-discipline -- benign racy precheck: deque truthiness is atomic; _update_state snapshots under the lock
            self._update_state()
        return self._state

    def snapshot(self) -> dict:
        """JSON-able SLO state (served as ``provider.slo_snapshot()``)."""
        if self.enabled and self._events:  # ytpu-lint: disable=lock-discipline -- benign racy precheck: deque truthiness is atomic; _update_state snapshots under the lock
            self._update_state()  # re-evaluate: windows age out over time
        return {
            "target_ms": self.target_ms,
            "window_s": self.window_s,
            "short_window_s": self.short_window_s,
            "objective": self.objective,
            "state": self._state,
            "burn_rates": dict(self._burns),
            "windows": {w: dict(s) for w, s in self._windows.items()},
            "completed": self._completed,
            "pending": len(self._pending),  # ytpu-lint: disable=lock-discipline -- point-in-time gauge: len() of a dict is atomic under the GIL
        }


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default
