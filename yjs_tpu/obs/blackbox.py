"""Black-box flight recorder (ISSUE 11 tentpole, part 2).

A lock-guarded bounded ring of structured events — monotonic tick,
subsystem, severity, doc/tenant/shard, trace id, kv payload — fed from
every seam that already emits tracer instants: demotions, rollbacks,
dead letters, brownout transitions, failover convictions, migration
windows, plan-cache poisons.  Always on (the steady-state cost is one
lock + one deque append per *rare* event), capped by
``YTPU_BLACKBOX_CAP`` so it can idle forever.

``dump(reason)`` snapshots the ring into a JSON-able dict; the stack
calls it automatically on quarantine convictions, failovers,
``ProviderFullError``, and unhandled flush exceptions, so a chaos
failure ships forensics instead of a seed alone.  With
``YTPU_BLACKBOX_DIR`` set each dump is also written to
``<dir>/blackbox-<reason>-<n>.json``; without it dumps stay in-memory
(``recorder.dumps``, newest last).  ``YTPU_BLACKBOX=0`` disables
recording entirely.

The scrape path (:meth:`FlightRecorder.snapshot`) copies under the same
lock as the writers — the torn-scrape race family PR 4 fixed in
``FlushHistory`` cannot recur here.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Optional

__all__ = ["FlightRecorder", "flight_recorder", "reset_flight_recorder"]

DEFAULT_CAP = 4096
# in-memory dump ring: enough for a chaos run's worth of forensics
# without growing unboundedly when no dump dir is configured
_DUMPS_KEPT = 16

SEVERITIES = ("debug", "info", "warning", "error")


def _env_cap() -> int:
    try:
        return max(16, int(os.environ.get("YTPU_BLACKBOX_CAP", DEFAULT_CAP)))
    except (TypeError, ValueError):
        return DEFAULT_CAP


def _env_enabled() -> bool:
    return os.environ.get("YTPU_BLACKBOX", "1") not in ("0", "false", "no")


class FlightRecorder:
    """Bounded, thread-safe ring of structured forensic events."""

    def __init__(self, cap: int | None = None) -> None:
        self._cap = cap if cap is not None else _env_cap()
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self._cap)
        self._tick = 0          # monotonic event counter (never resets)
        self._dropped = 0       # events evicted by the cap
        self._n_dumps = 0
        self._last_dump_tick = 0
        self.dumps: deque = deque(maxlen=_DUMPS_KEPT)
        self._metrics = None

    # -- metrics (lazy: the recorder must work before obs wiring) ---------

    def _obs(self):
        if self._metrics is None:
            from . import global_registry

            r = global_registry()
            self._metrics = {
                "events": r.counter(
                    "ytpu_blackbox_events_total",
                    "Structured events recorded by the black-box flight "
                    "recorder, by subsystem",
                    labelnames=("subsystem",),
                ),
                "dropped": r.counter(
                    "ytpu_blackbox_dropped_total",
                    "Flight-recorder events evicted by the "
                    "YTPU_BLACKBOX_CAP ring bound",
                ),
                "dumps": r.counter(
                    "ytpu_blackbox_dumps_total",
                    "Automatic black-box dumps, by trigger reason",
                    labelnames=("reason",),
                ),
            }
        return self._metrics

    # -- recording ---------------------------------------------------------

    def record(
        self,
        subsystem: str,
        event: str,
        severity: str = "info",
        guid: Optional[str] = None,
        tenant: Optional[str] = None,
        shard: Optional[int] = None,
        trace: Optional[str] = None,
        **kv,
    ) -> None:
        """Append one structured event.  ``trace`` is the trace-id hex
        of the causal context, when one is in flight (callers pass
        ``ctx.trace_hex`` or use :func:`record_current`)."""
        if not _env_enabled():
            return
        entry = {
            "subsystem": subsystem,
            "event": event,
            "severity": severity if severity in SEVERITIES else "info",
        }
        if guid is not None:
            entry["guid"] = str(guid)
        if tenant is not None:
            entry["tenant"] = str(tenant)
        if shard is not None:
            entry["shard"] = int(shard)
        if trace is not None:
            entry["trace"] = str(trace)
        if kv:
            entry["kv"] = {k: _jsonable(v) for k, v in kv.items()}
        with self._lock:
            self._tick += 1
            entry["tick"] = self._tick
            if len(self._ring) == self._cap:
                self._dropped += 1
                self._obs()["dropped"].inc()
            self._ring.append(entry)
        self._obs()["events"].labels(subsystem=subsystem).inc()

    # -- scrape ------------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """A consistent copy of the ring (oldest first), taken under the
        writers' lock so a concurrent scrape can never observe a torn
        entry."""
        with self._lock:
            return [dict(e) for e in self._ring]

    def stats(self) -> dict:
        with self._lock:
            return {
                "cap": self._cap,
                "events": self._tick,
                "in_ring": len(self._ring),
                "dropped": self._dropped,
                "dumps": self._n_dumps,
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- dumps -------------------------------------------------------------

    def dump(self, reason: str, **context) -> Optional[dict]:
        """Snapshot the ring into a dump dict (and a JSON file when
        ``YTPU_BLACKBOX_DIR`` is set).  Returns ``None`` — and records
        nothing — when no new event arrived since the previous dump, so
        a hot failure seam (e.g. a full provider rejecting a burst)
        cannot amplify one incident into thousands of identical
        files."""
        if not _env_enabled():
            return None
        with self._lock:
            if self._tick == self._last_dump_tick:
                return None
            self._last_dump_tick = self._tick
            self._n_dumps += 1
            seq = self._n_dumps
            events = [dict(e) for e in self._ring]
        out = {
            "reason": reason,
            "seq": seq,
            "tick": events[-1]["tick"] if events else 0,
            "events": events,
        }
        if context:
            out["context"] = {k: _jsonable(v) for k, v in context.items()}
        try:
            # the metric context leading up to the failure (ISSUE 19):
            # the last YTPU_BLACKBOX_TSDB_WINDOW_S of key TSDB series
            from .tsdb import tsdb_window

            win = tsdb_window()
            if win:
                out["tsdb"] = win
        except Exception:
            pass  # forensics must never take the failing path down
        self._obs()["dumps"].labels(reason=reason).inc()
        self.dumps.append(out)
        directory = os.environ.get("YTPU_BLACKBOX_DIR")
        if directory:
            try:
                os.makedirs(directory, exist_ok=True)
                path = os.path.join(
                    directory, f"blackbox-{_slug(reason)}-{seq:04d}.json"
                )
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(out, f, indent=1)
                os.replace(tmp, path)
                out["path"] = path
            except OSError:
                pass  # forensics must never take the failing path down
        return out

    @property
    def last_dump(self) -> Optional[dict]:
        return self.dumps[-1] if self.dumps else None


def _slug(s: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "-" for c in s)[:48]


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (bytes, bytearray)):
        return f"<{len(v)} bytes>"
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return repr(v)


# -- process-global default instance ------------------------------------------

_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def flight_recorder() -> FlightRecorder:
    """The process-global recorder every subsystem feeds (one black box
    per process, like a real aircraft)."""
    global _RECORDER
    rec = _RECORDER  # ytpu-lint: disable=lock-discipline -- double-checked fast path: publication of a fully-constructed recorder is atomic under the GIL
    if rec is None:
        with _RECORDER_LOCK:
            rec = _RECORDER
            if rec is None:
                rec = FlightRecorder()
                _RECORDER = rec
    return rec


def reset_flight_recorder() -> FlightRecorder:
    """Swap in a fresh recorder (tests that assert on ring contents)."""
    global _RECORDER
    with _RECORDER_LOCK:
        rec = FlightRecorder()
        _RECORDER = rec
    return rec
