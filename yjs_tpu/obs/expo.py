"""Exposition: Prometheus-style text dump and JSON snapshot.

Both accept any number of registries (the engine's own plus the
process-global one serving module-level consumers like the sync
protocol) and merge them into one view.  Histograms are rendered as
Prometheus summaries (quantile series + ``_count``/``_sum``) because the
log-bucketed storage maps to quantiles, not to fixed ``le`` rails.
"""

from __future__ import annotations


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        '{}="{}"'.format(k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in labels.items()
    )
    return "{" + inner + "}"


def _fmt_value(v) -> str:
    if isinstance(v, float):
        return repr(v)
    return str(v)


def prometheus_text(*registries) -> str:
    """Prometheus exposition-format text for every metric family."""
    lines: list[str] = []
    seen: set[str] = set()
    for reg in registries:
        for m in reg.collect():
            if m.name in seen:
                continue  # first registry wins on a name collision
            seen.add(m.name)
            help_text = m.help
            if m.unit:
                help_text = f"{help_text} [{m.unit}]" if help_text else f"[{m.unit}]"
            lines.append(f"# HELP {m.name} {help_text}")
            if m.kind == "histogram":
                lines.append(f"# TYPE {m.name} summary")
                for labels, series in m.samples():
                    s = series.summary()
                    for q, key in (("0.5", "p50"), ("0.95", "p95"),
                                   ("0.99", "p99")):
                        ql = dict(labels)
                        ql["quantile"] = q
                        lines.append(
                            f"{m.name}{_fmt_labels(ql)} {_fmt_value(s[key])}"
                        )
                    ls = _fmt_labels(labels)
                    lines.append(f"{m.name}_count{ls} {s['count']}")
                    lines.append(f"{m.name}_sum{ls} {_fmt_value(s['sum'])}")
                    lines.append(f"{m.name}_min{ls} {_fmt_value(s['min'])}")
                    lines.append(f"{m.name}_max{ls} {_fmt_value(s['max'])}")
            else:
                lines.append(f"# TYPE {m.name} {m.kind}")
                for labels, series in m.samples():
                    lines.append(
                        f"{m.name}{_fmt_labels(labels)} "
                        f"{_fmt_value(series.value)}"
                    )
    return "\n".join(lines) + ("\n" if lines else "")


def _labels_key(labels: dict) -> str:
    if not labels:
        return ""
    return ",".join(f"{k}={v}" for k, v in labels.items())


def registry_snapshot(*registries) -> dict:
    """JSON-able ``{counters, gauges, histograms}`` merged view.

    Each section maps ``name`` -> ``{labels_key: value_or_summary}``
    where ``labels_key`` is ``""`` for unlabeled series and
    ``"k=v,k2=v2"`` otherwise."""
    counters: dict = {}
    gauges: dict = {}
    histograms: dict = {}
    for reg in registries:
        for m in reg.collect():
            if m.kind == "counter":
                dst = counters
            elif m.kind == "gauge":
                dst = gauges
            else:
                dst = histograms
            if m.name in dst:
                continue
            series_map = {}
            for labels, series in m.samples():
                key = _labels_key(labels)
                if m.kind == "histogram":
                    series_map[key] = series.summary()
                else:
                    series_map[key] = series.value
            dst[m.name] = series_map
    return {"counters": counters, "gauges": gauges, "histograms": histograms}
