"""Embedded per-process time-series store (ISSUE 19 tentpole).

Every observability surface before this PR — registry scrapes,
``/metrics.json``, file-drop/HTTP federation, ``ytpu_top`` — is a
*point-in-time* snapshot: the moment a scrape ends, the fleet's past is
gone.  This module gives each process a memory.  A background sampler
(injectable clock; ``YTPU_TSDB_INTERVAL_S``, default 5s) walks the
metrics registries into per-series rings:

- **raw tier** — every sample, sealed into Gorilla-style compressed
  chunks (delta-of-delta timestamps + XOR float values, the Facebook
  in-memory TSDB encoding), retained ``YTPU_TSDB_RETENTION_RAW_S``;
- **1m / 10m downsample tiers** — per-bucket ``(count, sum, min, max,
  last)`` aggregates retained ``YTPU_TSDB_RETENTION_1M_S`` /
  ``YTPU_TSDB_RETENTION_10M_S``, so a day of history costs hundreds of
  points per series, not tens of thousands.

Sampled series: one per counter/gauge label-set, plus ``name:p50`` /
``name:p99`` / ``name:count`` derived series per histogram.  Total
series are capped (``YTPU_TSDB_MAX_SERIES``); overflow is counted, not
silently absorbed.

Lock discipline is torn-scrape-safe: the registry walk happens OUTSIDE
the store lock (registry reads are lock-free snapshots by design), and
every ring mutation and every range query runs under one store lock —
a ``/query`` racing the sampler sees either the pre- or post-sample
ring, never a half-appended chunk.

Persistence (``YTPU_TSDB_DIR``): length+CRC framed binary records,
written to a temp file and atomically renamed every
``YTPU_TSDB_PERSIST_S``.  Reload tolerates a crash-truncated file by
keeping exactly the frames whose checksum verifies — no sample is ever
invented, the torn tail is dropped and counted
(``ytpu_tsdb_reload_truncated_total``).

The range-query API (:meth:`Tsdb.query`) is served over the ISSUE 16
admin plane as ``/query`` (``?name=…&labels=…&start=…&end=…&agg=…``)
and ``/debug/tsdb``; the cluster supervisor federates it across shard
children via the same admin scrape path (:func:`query_endpoints` +
:func:`merge_points`).

``YTPU_TSDB_DISABLED=1`` turns the whole subsystem off; it is
observational only, so engine output is byte-identical either way
(pinned by tests/test_cost.py).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import urllib.parse
import urllib.request
import weakref
import zlib
from collections import deque

__all__ = [
    "Tsdb",
    "TsdbConfig",
    "tsdb",
    "tsdb_enabled",
    "tsdb_metrics",
    "maybe_attach_tsdb",
    "tsdb_window",
    "encode_chunk",
    "decode_chunk",
    "query_endpoints",
    "merge_points",
]

_MAGIC = b"YTPUTSDB1\0"
_CHUNK_POINTS = 128  # raw points per sealed Gorilla chunk
_TIER_BUCKETS_MS = {"1m": 60_000, "10m": 600_000}
_AGGS = ("avg", "min", "max", "last", "sum", "count")
# key-series prefixes the flight recorder embeds in post-mortem dumps
KEY_SERIES_PREFIXES = (
    "ytpu_convergence_latency_seconds",
    "ytpu_engine_flushes_total",
    "ytpu_engine_flush_seconds",
    "ytpu_engine_pending_docs",
    "ytpu_admission_",
    "ytpu_cost_",
)


def _env_float(name: str, default: float, lo: float = 0.0) -> float:
    try:
        v = float(os.environ.get(name, default))
    except ValueError:
        return default
    return max(lo, v)


def _env_int(name: str, default: int, lo: int = 0) -> int:
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        return default
    return max(lo, v)


def tsdb_enabled() -> bool:
    return os.environ.get("YTPU_TSDB_DISABLED", "") != "1"


class TsdbConfig:
    """TSDB knobs (env-derived defaults, constructor wins)."""

    __slots__ = (
        "interval_s", "retention_raw_s", "retention_1m_s",
        "retention_10m_s", "max_series", "directory", "persist_s",
    )

    def __init__(
        self,
        interval_s: float | None = None,
        retention_raw_s: float | None = None,
        retention_1m_s: float | None = None,
        retention_10m_s: float | None = None,
        max_series: int | None = None,
        directory: str | None = None,
        persist_s: float | None = None,
    ):
        def pick(v, n, d, lo):
            return v if v is not None else _env_float(n, d, lo)

        self.interval_s = pick(interval_s, "YTPU_TSDB_INTERVAL_S", 5.0, 0.05)
        self.retention_raw_s = pick(
            retention_raw_s, "YTPU_TSDB_RETENTION_RAW_S", 3600.0, 60.0
        )
        self.retention_1m_s = pick(
            retention_1m_s, "YTPU_TSDB_RETENTION_1M_S", 6 * 3600.0, 60.0
        )
        self.retention_10m_s = pick(
            retention_10m_s, "YTPU_TSDB_RETENTION_10M_S", 24 * 3600.0, 600.0
        )
        self.max_series = (
            max_series
            if max_series is not None
            else _env_int("YTPU_TSDB_MAX_SERIES", 4096, lo=16)
        )
        self.directory = (
            directory
            if directory is not None
            else (os.environ.get("YTPU_TSDB_DIR") or None)
        )
        self.persist_s = pick(persist_s, "YTPU_TSDB_PERSIST_S", 60.0, 1.0)

    def retention_ms(self, tier: str) -> int:
        if tier == "raw":
            return int(self.retention_raw_s * 1000)
        if tier == "1m":
            return int(self.retention_1m_s * 1000)
        return int(self.retention_10m_s * 1000)


class _TsdbMetrics:
    """``ytpu_tsdb_*`` families on the process-global registry."""

    def __init__(self):
        from . import global_registry

        reg = global_registry()
        self.samples = reg.counter(
            "ytpu_tsdb_samples_total",
            "Sampler passes completed (one walk of every attached "
            "registry)",
        )
        self.points = reg.counter(
            "ytpu_tsdb_points_total",
            "Raw points appended across all series",
        )
        self.series = reg.gauge(
            "ytpu_tsdb_series",
            "Distinct (name, labels) series currently retained",
        )
        self.dropped = reg.counter(
            "ytpu_tsdb_dropped_series_total",
            "Series rejected by the YTPU_TSDB_MAX_SERIES cap",
        )
        self.queries = reg.counter(
            "ytpu_tsdb_queries_total",
            "Range queries served (local + admin /query)",
        )
        self.persists = reg.counter(
            "ytpu_tsdb_persists_total",
            "Atomic-rename persistence attempts, by outcome",
            labelnames=("status",),
        )
        self.reload_truncated = reg.counter(
            "ytpu_tsdb_reload_truncated_total",
            "Reloads that hit a torn frame and kept only the intact "
            "prefix (crash-mid-persist tolerance)",
        )


_TSDB_METRICS: _TsdbMetrics | None = None
_TSDB_METRICS_LOCK = threading.Lock()


def tsdb_metrics() -> _TsdbMetrics:
    global _TSDB_METRICS
    with _TSDB_METRICS_LOCK:
        if _TSDB_METRICS is None:
            _TSDB_METRICS = _TsdbMetrics()
        return _TSDB_METRICS


# -- Gorilla bit codec --------------------------------------------------------


class _BitWriter:
    __slots__ = ("buf", "_acc", "_nbits")

    def __init__(self):
        self.buf = bytearray()
        self._acc = 0
        self._nbits = 0

    def write(self, value: int, nbits: int) -> None:
        self._acc = (self._acc << nbits) | (value & ((1 << nbits) - 1))
        self._nbits += nbits
        while self._nbits >= 8:
            self._nbits -= 8
            self.buf.append((self._acc >> self._nbits) & 0xFF)
        self._acc &= (1 << self._nbits) - 1

    def to_bytes(self) -> bytes:
        out = bytes(self.buf)
        if self._nbits:
            out += bytes([(self._acc << (8 - self._nbits)) & 0xFF])
        return out


class _BitReader:
    __slots__ = ("data", "_pos")

    def __init__(self, data: bytes):
        self.data = data
        self._pos = 0

    def read(self, nbits: int) -> int:
        out = 0
        pos = self._pos
        data = self.data
        for _ in range(nbits):
            out = (out << 1) | ((data[pos >> 3] >> (7 - (pos & 7))) & 1)
            pos += 1
        self._pos = pos
        return out


def _f2b(v: float) -> int:
    return struct.unpack(">Q", struct.pack(">d", float(v)))[0]


def _b2f(b: int) -> float:
    return struct.unpack(">d", struct.pack(">Q", b))[0]


def _signed(v: int, nbits: int) -> int:
    return v - (1 << nbits) if v >= 1 << (nbits - 1) else v


# delta-of-delta payload widths for the '10' / '110' / '1110' prefixes
_DOD_WIDTHS = (7, 13, 20)


def encode_chunk(points) -> bytes:
    """Gorilla-encode ``[(ts_ms, value), …]``: first point raw 64+64,
    then delta-of-delta timestamps ('0' = repeat cadence) and XOR
    values with leading/trailing zero-window reuse."""
    w = _BitWriter()
    prev_ts = prev_delta = 0
    prev_bits = 0
    lead = trail = -1
    for i, (ts, v) in enumerate(points):
        ts = int(ts)
        bits = _f2b(v)
        if i == 0:
            w.write(ts, 64)
            w.write(bits, 64)
        else:
            delta = ts - prev_ts
            dod = delta - prev_delta
            prev_delta = delta
            if dod == 0:
                w.write(0, 1)
            else:
                for k, width in enumerate(_DOD_WIDTHS):
                    half = 1 << (width - 1)
                    if -half + 1 <= dod <= half:
                        # prefix: k+1 ones then a zero (10 / 110 / 1110)
                        w.write(((1 << (k + 1)) - 1) << 1, k + 2)
                        w.write(dod + half - 1, width)
                        break
                else:
                    w.write(0b1111, 4)
                    w.write(dod, 64)
            x = bits ^ prev_bits
            if x == 0:
                w.write(0, 1)
            else:
                xl = 64 - x.bit_length()
                xt = (x & -x).bit_length() - 1
                if lead >= 0 and xl >= lead and xt >= trail:
                    w.write(0b10, 2)
                    w.write(x >> trail, 64 - lead - trail)
                else:
                    lead = min(xl, 31)
                    trail = xt
                    mbits = 64 - lead - trail
                    w.write(0b11, 2)
                    w.write(lead, 5)
                    w.write(mbits - 1, 6)
                    w.write(x >> trail, mbits)
        prev_ts = ts
        prev_bits = bits
    return w.to_bytes()


def decode_chunk(data: bytes, count: int) -> list:
    """Inverse of :func:`encode_chunk` (``count`` points)."""
    if count <= 0:
        return []
    r = _BitReader(data)
    ts = _signed(r.read(64), 64)
    bits = r.read(64)
    out = [(ts, _b2f(bits))]
    delta = 0
    lead = trail = 0
    for _ in range(count - 1):
        if r.read(1) == 0:
            dod = 0
        else:
            ones = 1
            while ones < 4 and r.read(1) == 1:
                ones += 1
            if ones < 4:
                width = _DOD_WIDTHS[ones - 1]
                dod = r.read(width) - (1 << (width - 1)) + 1
            else:
                dod = _signed(r.read(64), 64)
        delta += dod
        ts += delta
        if r.read(1) == 1:
            if r.read(1) == 0:
                x = r.read(64 - lead - trail) << trail
            else:
                lead = r.read(5)
                mbits = r.read(6) + 1
                trail = 64 - lead - mbits
                x = r.read(mbits) << trail
            bits ^= x
        out.append((ts, _b2f(bits)))
    return out


# -- per-series storage -------------------------------------------------------


class _SealedChunk:
    __slots__ = ("start_ts", "end_ts", "count", "data")

    def __init__(self, start_ts: int, end_ts: int, count: int, data: bytes):
        self.start_ts = start_ts
        self.end_ts = end_ts
        self.count = count
        self.data = data


class _Series:
    """One (name, labels) ring: sealed Gorilla chunks + an open plain
    tail at raw resolution, plus the 1m/10m downsample tiers.  All
    mutation happens under the owning store's lock."""

    __slots__ = (
        "name", "labels", "chunks", "open", "tiers", "last_ts",
        "_next_ret_ms", "_tier_rings",
    )

    def __init__(self, name: str, labels: str):
        self.name = name
        self.labels = labels
        self.chunks: deque = deque()
        self.open: list = []
        # tier -> deque of [bucket_ts, count, sum, mn, mx, last]
        self.tiers = {t: deque() for t in _TIER_BUCKETS_MS}
        # (ring, bucket_ms) pairs hoisted for the per-append loop; the
        # deques are only ever mutated in place, so the refs stay live
        self._tier_rings = tuple(
            (self.tiers[t], ms) for t, ms in _TIER_BUCKETS_MS.items()
        )
        self.last_ts = 0
        # retention is enforced at most once per minute of series time:
        # the tightest retention window is measured in hours, so a
        # per-append sweep is pure sampler-tick overhead
        self._next_ret_ms = 0

    def append(self, ts_ms: int, value: float, config: TsdbConfig) -> None:
        if ts_ms <= self.last_ts:
            ts_ms = self.last_ts + 1  # clock went backwards: keep order
        self.last_ts = ts_ms
        self.open.append((ts_ms, float(value)))
        if len(self.open) >= _CHUNK_POINTS:
            pts = self.open
            self.chunks.append(_SealedChunk(
                pts[0][0], pts[-1][0], len(pts), encode_chunk(pts)
            ))
            self.open = []
        for ring, bucket_ms in self._tier_rings:
            bucket = ts_ms - ts_ms % bucket_ms
            if ring:
                row = ring[-1]
                if row[0] == bucket:
                    row[1] += 1
                    row[2] += value
                    if value < row[3]:
                        row[3] = value
                    if value > row[4]:
                        row[4] = value
                    row[5] = value
                    continue
                if bucket <= row[0]:
                    continue
            ring.append([bucket, 1, value, value, value, value])
        if ts_ms >= self._next_ret_ms:
            self.enforce_retention(ts_ms, config)
            self._next_ret_ms = ts_ms + 60_000

    def enforce_retention(self, now_ms: int, config: TsdbConfig) -> None:
        floor = now_ms - config.retention_ms("raw")
        while self.chunks and self.chunks[0].end_ts < floor:
            self.chunks.popleft()
        for tier, bucket_ms in _TIER_BUCKETS_MS.items():
            ring = self.tiers[tier]
            tfloor = now_ms - config.retention_ms(tier) - bucket_ms
            while ring and ring[0][0] < tfloor:
                ring.popleft()

    def raw_points(self, start_ms: int, end_ms: int) -> list:
        out = []
        for c in self.chunks:
            if c.end_ts < start_ms or c.start_ts > end_ms:
                continue
            out.extend(
                p for p in decode_chunk(c.data, c.count)
                if start_ms <= p[0] <= end_ms
            )
        out.extend(
            p for p in self.open if start_ms <= p[0] <= end_ms
        )
        return out

    def tier_points(
        self, tier: str, start_ms: int, end_ms: int, agg: str
    ) -> list:
        out = []
        for bucket, count, total, mn, mx, last in self.tiers[tier]:
            if bucket < start_ms or bucket > end_ms:
                continue
            if agg == "min":
                v = mn
            elif agg == "max":
                v = mx
            elif agg == "last":
                v = last
            elif agg == "sum":
                v = total
            elif agg == "count":
                v = float(count)
            else:
                v = total / count if count else 0.0
            out.append((bucket, v))
        return out

    def point_count(self) -> int:
        return sum(c.count for c in self.chunks) + len(self.open)

    def byte_size(self) -> int:
        return sum(len(c.data) for c in self.chunks) + 16 * len(self.open)


# -- the store ---------------------------------------------------------------


class Tsdb:
    """Per-process embedded TSDB (module docstring).  ``clock`` is
    injectable for deterministic tests; the background thread (when
    :meth:`start`-ed) paces itself on wall time but stamps samples with
    ``clock()``."""

    def __init__(self, config: TsdbConfig | None = None, clock=None):
        import time as _time

        self.config = config if config is not None else TsdbConfig()
        self.clock = clock if clock is not None else _time.time
        self._lock = threading.Lock()
        self._series: dict = {}
        self._sources: list = []  # weakrefs to attached registries
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        self._n_samples = 0
        self._n_dropped = 0
        self._n_truncated = 0
        self._last_persist = 0.0
        if self.config.directory:
            self._load()

    # -- sources -------------------------------------------------------------

    def add_registry(self, registry) -> None:
        """Attach one metrics registry (weakly referenced; a dead
        registry is pruned on the next sample)."""
        ref = weakref.ref(registry)
        with self._lock:
            live = [r for r in self._sources if r() is not None]
            if not any(r() is registry for r in live):
                live.append(ref)
            self._sources = live

    # -- sampling ------------------------------------------------------------

    def _collect(self) -> dict:
        """Merged flat sample map ``(name, labels) -> value`` over the
        global registry + every attached registry.  Runs OUTSIDE the
        store lock: registry reads are lock-free snapshots, and holding
        the store lock across them would serialize /query behind a
        potentially large walk."""
        from . import global_registry
        from .expo import _labels_key

        with self._lock:
            sources = list(self._sources)
        regs = [global_registry()]
        for ref in sources:
            reg = ref()
            if reg is not None and reg is not regs[0]:
                regs.append(reg)
        # walked flat — no intermediate nested snapshot; the first
        # registry to export a (kind, name) family wins, matching the
        # registry_snapshot merge the admin plane uses
        flat: dict = {}
        seen: set = set()
        for reg in regs:
            for m in reg.collect():
                name = m.name
                fam = (m.kind, name)
                if fam in seen:
                    continue
                seen.add(fam)
                if m.kind == "histogram":
                    for labels, series in m.samples():
                        lk = _labels_key(labels)
                        s = series.summary()
                        flat.setdefault(
                            (f"{name}:p50", lk), float(s["p50"])
                        )
                        flat.setdefault(
                            (f"{name}:p99", lk), float(s["p99"])
                        )
                        flat.setdefault(
                            (f"{name}:count", lk), float(s["count"])
                        )
                else:
                    for labels, series in m.samples():
                        flat.setdefault(
                            (name, _labels_key(labels)),
                            float(series.value),
                        )
        return flat

    def sample_once(self, now: float | None = None) -> int:
        """One sampler pass; returns the number of points appended."""
        if now is None:
            now = self.clock()
        ts_ms = int(now * 1000)
        flat = self._collect()
        m = tsdb_metrics()
        appended = dropped = 0
        with self._lock:
            for (name, labels), value in flat.items():
                key = (name, labels)
                s = self._series.get(key)
                if s is None:
                    if len(self._series) >= self.config.max_series:
                        dropped += 1
                        continue
                    s = self._series[key] = _Series(name, labels)
                s.append(ts_ms, value, self.config)
                appended += 1
            self._n_samples += 1
            self._n_dropped += dropped
            n_series = len(self._series)
        m.samples.inc()
        m.points.inc(appended)
        m.series.set(n_series)
        if dropped:
            m.dropped.inc(dropped)
        if self.config.directory and (
            now - self._last_persist >= self.config.persist_s
        ):
            self.persist(now=now)
        return appended

    def record(
        self, name: str, value: float, labels: str = "",
        now: float | None = None,
    ) -> None:
        """Append one point directly (the cost ledger and the capacity
        ramp feed derived series through here without registering a
        metric family)."""
        if now is None:
            now = self.clock()
        with self._lock:
            key = (name, labels)
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.config.max_series:
                    self._n_dropped += 1
                    return
                s = self._series[key] = _Series(name, labels)
            s.append(int(now * 1000), float(value), self.config)

    # -- background thread ---------------------------------------------------

    def start(self) -> "Tsdb":
        if self._thread is not None or not tsdb_enabled():
            return self
        t = threading.Thread(
            target=self._run, name="ytpu-tsdb-sampler", daemon=True
        )
        self._thread = t
        t.start()
        return self

    def _run(self) -> None:
        while not self._wake.wait(self.config.interval_s):
            try:
                self.sample_once()
            except Exception:
                # the sampler must never take the process down; the
                # next tick retries
                pass

    def close(self) -> None:
        self._wake.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
        self._wake.clear()

    # -- queries -------------------------------------------------------------

    def series_names(self) -> list:
        with self._lock:
            return sorted(self._series)

    def _pick_tier(self, s: _Series, start_ms: int) -> str:
        span = s.last_ts - start_ms
        if span <= self.config.retention_ms("raw"):
            return "raw"
        if span <= self.config.retention_ms("1m"):
            return "1m"
        return "10m"

    def query(
        self,
        name: str,
        labels: str = "",
        start: float | None = None,
        end: float | None = None,
        agg: str = "avg",
        tier: str | None = None,
    ) -> list:
        """Points ``[(ts_seconds, value), …]`` for one series in
        ``[start, end]`` (epoch seconds; default: the last hour up to
        now).  ``agg`` applies to downsample-tier buckets (raw points
        are returned as-is); ``tier`` forces raw/1m/10m, else the
        finest tier whose retention covers ``start`` is chosen."""
        if agg not in _AGGS:
            raise ValueError(f"agg must be one of {_AGGS}, not {agg!r}")
        if tier is not None and tier not in ("raw", "1m", "10m"):
            raise ValueError(f"tier must be raw/1m/10m, not {tier!r}")
        if end is None:
            end = self.clock()
        if start is None:
            start = end - 3600.0
        start_ms, end_ms = int(start * 1000), int(end * 1000)
        tsdb_metrics().queries.inc()
        with self._lock:
            s = self._series.get((name, labels))
            if s is None:
                return []
            # appends throttle retention sweeps to once a minute of
            # series time; reads settle it so results are always exact
            s.enforce_retention(s.last_ts, self.config)
            t = tier if tier is not None else self._pick_tier(s, start_ms)
            if t == "raw":
                pts = s.raw_points(start_ms, end_ms)
            else:
                pts = s.tier_points(t, start_ms, end_ms, agg)
        return [(ts / 1000.0, v) for ts, v in pts]

    def query_params(self, params: dict) -> dict:
        """The admin-plane ``/query`` surface: string params in, a
        JSON-able result out.  Raises ValueError on a missing name or
        malformed number (the handler renders it as a 400)."""
        name = params.get("name")
        if not name:
            raise ValueError("query needs ?name=<series>")

        def num(key):
            v = params.get(key)
            return None if v in (None, "") else float(v)

        tier = params.get("tier") or None
        agg = params.get("agg") or "avg"
        points = self.query(
            name,
            labels=params.get("labels", "") or "",
            start=num("start"),
            end=num("end"),
            agg=agg,
            tier=tier,
        )
        return {
            "name": name,
            "labels": params.get("labels", "") or "",
            "agg": agg,
            "tier": tier or "auto",
            "points": [[round(ts, 3), v] for ts, v in points],
        }

    def window(
        self, window_s: float, prefixes=KEY_SERIES_PREFIXES,
        max_series: int = 32, now: float | None = None,
    ) -> dict:
        """The last ``window_s`` seconds of every key series (matched
        by name prefix), as ``{"name{labels}": [[ts, v], …]}`` — the
        flight-recorder embedding (ISSUE 19 satellite)."""
        if now is None:
            now = self.clock()
        start_ms = int((now - window_s) * 1000)
        end_ms = int(now * 1000)
        out: dict = {}
        with self._lock:
            for (name, labels) in sorted(self._series):
                if len(out) >= max_series:
                    break
                if not any(name.startswith(p) for p in prefixes):
                    continue
                s = self._series[(name, labels)]
                pts = s.raw_points(start_ms, end_ms)
                if pts:
                    key = f"{name}{{{labels}}}" if labels else name
                    out[key] = [
                        [round(ts / 1000.0, 3), v] for ts, v in pts
                    ]
        return out

    def stats(self) -> dict:
        with self._lock:
            series = list(self._series.values())
            for s in series:
                # settle append-throttled retention so the counts the
                # admin plane reports never include aged-out chunks
                s.enforce_retention(s.last_ts, self.config)
            n_samples = self._n_samples
            n_dropped = self._n_dropped
            n_truncated = self._n_truncated
        return {
            "series": len(series),
            "points_raw": sum(s.point_count() for s in series),
            "points_1m": sum(len(s.tiers["1m"]) for s in series),
            "points_10m": sum(len(s.tiers["10m"]) for s in series),
            "sealed_chunks": sum(len(s.chunks) for s in series),
            "encoded_bytes": sum(s.byte_size() for s in series),
            "samples": n_samples,
            "dropped_series": n_dropped,
            "reload_truncated": n_truncated,
            "interval_s": self.config.interval_s,
            "retention_s": {
                "raw": self.config.retention_raw_s,
                "1m": self.config.retention_1m_s,
                "10m": self.config.retention_10m_s,
            },
            "dir": self.config.directory,
        }

    # -- persistence ---------------------------------------------------------

    def _encode_series(self, s: _Series) -> bytes:
        out = bytearray()
        name = s.name.encode("utf-8")
        labels = s.labels.encode("utf-8")
        out += struct.pack(">H", len(name)) + name
        out += struct.pack(">H", len(labels)) + labels
        out += struct.pack(">I", len(s.chunks))
        for c in s.chunks:
            out += struct.pack(
                ">qqII", c.start_ts, c.end_ts, c.count, len(c.data)
            )
            out += c.data
        out += struct.pack(">I", len(s.open))
        for ts, v in s.open:
            out += struct.pack(">qd", ts, v)
        for tier in _TIER_BUCKETS_MS:
            ring = s.tiers[tier]
            out += struct.pack(">I", len(ring))
            for bucket, count, total, mn, mx, last in ring:
                out += struct.pack(
                    ">qIdddd", bucket, count, total, mn, mx, last
                )
        return bytes(out)

    @staticmethod
    def _decode_series(payload: bytes) -> _Series:
        off = 0

        def take(fmt):
            nonlocal off
            size = struct.calcsize(fmt)
            vals = struct.unpack_from(fmt, payload, off)
            off += size
            return vals

        (nlen,) = take(">H")
        name = payload[off:off + nlen].decode("utf-8")
        off += nlen
        (llen,) = take(">H")
        labels = payload[off:off + llen].decode("utf-8")
        off += llen
        s = _Series(name, labels)
        (n_chunks,) = take(">I")
        for _ in range(n_chunks):
            start, end, count, nbytes = take(">qqII")
            data = payload[off:off + nbytes]
            off += nbytes
            s.chunks.append(_SealedChunk(start, end, count, data))
            s.last_ts = max(s.last_ts, end)
        (n_open,) = take(">I")
        for _ in range(n_open):
            ts, v = take(">qd")
            s.open.append((ts, v))
            s.last_ts = max(s.last_ts, ts)
        for tier in _TIER_BUCKETS_MS:
            (n,) = take(">I")
            for _ in range(n):
                s.tiers[tier].append(list(take(">qIdddd")))
        return s

    def persist(self, now: float | None = None) -> bool:
        """Write every series to ``<dir>/tsdb.bin`` via temp file +
        atomic rename.  Returns True on success; failure is counted
        and swallowed (history must never take the serving path down).
        """
        directory = self.config.directory
        if not directory:
            return False
        if now is None:
            now = self.clock()
        self._last_persist = now
        with self._lock:
            payloads = [
                self._encode_series(s) for _, s in sorted(self._series.items())
            ]
        m = tsdb_metrics()
        try:
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory, "tsdb.bin")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(_MAGIC)
                for p in payloads:
                    f.write(struct.pack(">II", len(p), zlib.crc32(p)))
                    f.write(p)
            os.replace(tmp, path)
        except OSError:
            m.persists.labels(status="error").inc()
            return False
        m.persists.labels(status="ok").inc()
        return True

    def _load(self) -> None:
        """Crash-truncation-tolerant reload: keep exactly the prefix of
        frames whose length + CRC verify; drop (and count) the torn
        tail.  Called from __init__ only — no lock needed."""
        path = os.path.join(self.config.directory, "tsdb.bin")
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return
        if not blob.startswith(_MAGIC):
            return
        off = len(_MAGIC)
        truncated = False
        while off < len(blob):
            if off + 8 > len(blob):
                truncated = True
                break
            length, crc = struct.unpack_from(">II", blob, off)
            off += 8
            payload = blob[off:off + length]
            if len(payload) < length or zlib.crc32(payload) != crc:
                truncated = True
                break
            off += length
            try:
                s = self._decode_series(payload)
            except (struct.error, UnicodeDecodeError, IndexError):
                truncated = True
                break
            self._series[(s.name, s.labels)] = s  # ytpu-lint: disable=lock-discipline -- constructor-only path: _load runs before the store is published to any other thread
        if truncated:
            self._n_truncated += 1
            tsdb_metrics().reload_truncated.inc()


# -- process-global singleton -------------------------------------------------

_TSDB: Tsdb | None = None
_TSDB_GUARD = threading.Lock()


def tsdb() -> Tsdb:
    """The process-global store (created on first use; the sampler
    thread starts on the first registry attach, not here)."""
    global _TSDB
    with _TSDB_GUARD:
        if _TSDB is None:
            _TSDB = Tsdb()
        return _TSDB


def maybe_attach_tsdb(registry) -> Tsdb | None:
    """Attach one registry to the process-global store and ensure the
    sampler runs — unless ``YTPU_TSDB_DISABLED=1``.  The provider calls
    this at construction; tests building hundreds of providers share
    one sampler thread."""
    if not tsdb_enabled():
        return None
    t = tsdb()
    t.add_registry(registry)
    t.start()
    return t


def tsdb_window(window_s: float | None = None) -> dict:
    """The flight-recorder embedding: the last
    ``YTPU_BLACKBOX_TSDB_WINDOW_S`` (default 60s) of key series from
    the process-global store; ``{}`` when the TSDB is disabled or has
    no matching history yet."""
    if not tsdb_enabled() or _TSDB is None:  # ytpu-lint: disable=lock-discipline -- double-checked fast path: publication of a fully-constructed store is atomic under the GIL
        return {}
    if window_s is None:
        window_s = _env_float("YTPU_BLACKBOX_TSDB_WINDOW_S", 60.0, 1.0)
    return _TSDB.window(window_s)


# -- cross-shard federation (supervisor scrape path) --------------------------


def query_endpoints(
    urls: dict, params: dict, timeout_s: float = 2.0
) -> dict:
    """Fan one ``/query`` out to every admin endpoint in ``urls``
    (label -> base URL); a dead or erroring endpoint contributes an
    empty result rather than failing the federation."""
    qs = urllib.parse.urlencode(
        {k: v for k, v in params.items() if v not in (None, "")}
    )
    out: dict = {}
    for label in sorted(urls):
        try:
            with urllib.request.urlopen(
                f"{urls[label]}/query?{qs}", timeout=timeout_s
            ) as r:
                res = json.load(r)
            out[label] = res if isinstance(res, dict) else {"points": []}
        except (OSError, ValueError):
            out[label] = {"points": [], "stale": True}
    return out


def merge_points(
    per_shard: dict, agg: str = "avg", bucket_s: float = 5.0
) -> list:
    """Merge per-shard point lists into one fleet series: points are
    bucketed to the sampler cadence and combined with ``agg`` across
    shards (sum for counters queried with agg=sum, avg/min/max/last
    otherwise)."""
    buckets: dict = {}
    for res in per_shard.values():
        for ts, v in res.get("points") or ():
            b = ts - ts % bucket_s
            buckets.setdefault(b, []).append(v)
    out = []
    for b in sorted(buckets):
        vals = buckets[b]
        if agg == "sum":
            v = sum(vals)
        elif agg == "min":
            v = min(vals)
        elif agg == "max":
            v = max(vals)
        elif agg == "count":
            v = float(len(vals))
        elif agg == "last":
            v = vals[-1]
        else:
            v = sum(vals) / len(vals)
        out.append([round(b, 3), v])
    return out
