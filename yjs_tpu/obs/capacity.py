"""Sessions-per-device capacity model (ISSUE 19 tentpole, the
ROADMAP's "sessions per device at interactive SLO" ask).

:func:`ramp_capacity` drives the ISSUE 10 loadgen harness against
fresh servers at geometrically increasing interactive session counts,
watching two degradation signals after each stage:

- the ISSUE 4 convergence-SLO verdict (``page`` = the multi-window
  burn rate blew the wall-clock target), and
- the tick-deterministic interactive visibility p99 against a
  configurable tick budget;

every stage's offered sessions / verdict / p99 are recorded into the
embedded TSDB (``obs/tsdb.py``), and the published figure — the
**knee**, the largest session count that still met SLO — is read back
out of that history by :func:`read_knee`, not from a side channel: the
capacity number is, by construction, a TSDB query over the ramp.

``bench_capacity`` (bench.py) wraps this into BENCH_capacity.json
(``sessions_per_device`` = knee / visible devices), gated by
scripts/check_bench.py.
"""

from __future__ import annotations

__all__ = [
    "CapacityConfig", "ramp_capacity", "read_knee", "sessions_per_device",
]

_SESSIONS_SERIES = "ytpu_capacity_sessions"
_OK_SERIES = "ytpu_capacity_ok"
_P99_SERIES = "ytpu_capacity_p99_ticks"


class CapacityConfig:
    """Shape of one capacity ramp."""

    __slots__ = (
        "start_sessions", "max_sessions", "growth", "ticks_per_stage",
        "flush_every", "p99_limit_ticks", "slo_target_ms", "seed",
    )

    def __init__(
        self,
        start_sessions: int = 8,
        max_sessions: int = 192,
        growth: float = 2.0,
        ticks_per_stage: int = 24,
        flush_every: int = 2,
        p99_limit_ticks: int | None = None,
        slo_target_ms: float = 5000.0,
        seed: int = 0,
    ):
        self.start_sessions = max(1, int(start_sessions))
        self.max_sessions = max(self.start_sessions, int(max_sessions))
        self.growth = max(1.25, float(growth))
        self.ticks_per_stage = max(4, int(ticks_per_stage))
        self.flush_every = max(1, int(flush_every))
        # interactive visibility budget: a healthy stage sees its edits
        # within a few flush intervals
        self.p99_limit_ticks = (
            p99_limit_ticks
            if p99_limit_ticks is not None
            else 4 * self.flush_every
        )
        self.slo_target_ms = float(slo_target_ms)
        self.seed = int(seed)

    def stages(self) -> list:
        out = []
        n = self.start_sessions
        while n < self.max_sessions:
            out.append(n)
            n = max(n + 1, int(n * self.growth))
        out.append(self.max_sessions)
        return out


def ramp_capacity(
    make_server, config: CapacityConfig | None = None, store=None,
    now: float | None = None,
) -> dict:
    """Ramp ``make_server(n_sessions)`` servers until the SLO verdict
    degrades; returns the ramp result with the knee read back from the
    TSDB history (module docstring).  ``store`` defaults to the
    process-global TSDB; ``now`` anchors the recorded stage timestamps
    (injectable for deterministic tests)."""
    from ..loadgen import INTERACTIVE_MIX, LoadGen, LoadGenConfig
    from .tsdb import tsdb

    config = config if config is not None else CapacityConfig()
    store = store if store is not None else tsdb()
    t = float(now) if now is not None else store.clock()
    t0 = t
    stages = []
    ceiling_hit = True
    for n in config.stages():
        server = make_server(n)
        try:
            lg = LoadGen(server, LoadGenConfig(
                seed=config.seed,
                n_clients=n,
                mix=INTERACTIVE_MIX,
                flush_every=config.flush_every,
                slo_target_ms=config.slo_target_ms,
            ))
            lg.run(config.ticks_per_stage)
            verdict = lg._worst_slo()
            p99 = lg.interactive_p99()
        finally:
            close = getattr(server, "close", None)
            if close is not None:
                close()
        ok = verdict != "page" and p99 <= config.p99_limit_ticks
        store.record(_SESSIONS_SERIES, float(n), now=t)
        store.record(_OK_SERIES, 1.0 if ok else 0.0, now=t)
        store.record(_P99_SERIES, float(p99), now=t)
        stages.append({
            "sessions": n,
            "slo_verdict": verdict,
            "interactive_p99_ticks": p99,
            "ok": ok,
        })
        t += max(1.0, store.config.interval_s)
        if not ok:
            ceiling_hit = False
            break
    knee = read_knee(store, t0 - 1.0, t + 1.0)
    return {
        "stages": stages,
        "sessions_at_slo": knee,
        "ceiling_hit": ceiling_hit,
        "p99_limit_ticks": config.p99_limit_ticks,
        "window": [t0, t],
    }


def read_knee(store, start: float, end: float) -> int:
    """The knee, from TSDB history alone: the largest offered session
    count whose stage recorded ``ok == 1`` inside ``[start, end]``."""
    sessions = store.query(
        _SESSIONS_SERIES, start=start, end=end, tier="raw"
    )
    verdicts = dict(store.query(
        _OK_SERIES, start=start, end=end, tier="raw"
    ))
    knee = 0
    for ts, n in sessions:
        if verdicts.get(ts, 0.0) >= 1.0:
            knee = max(knee, int(n))
    return knee


def sessions_per_device(result: dict) -> dict:
    """Fold a ramp result into the published figure: knee sessions
    divided by the visible device count (1 when jax is absent)."""
    try:
        import jax

        n_devices = max(1, len(jax.devices()))
    except Exception:
        n_devices = 1
    knee = int(result.get("sessions_at_slo", 0))
    return {
        "sessions_at_slo": knee,
        "n_devices": n_devices,
        "sessions_per_device": round(knee / n_devices, 2),
        "ceiling_hit": bool(result.get("ceiling_hit")),
    }
