"""Zero-dependency metrics registry: counters, gauges, log-bucketed
histograms.

Cheap enough to leave on in the flush hot path: an ``observe``/``inc`` is
an attribute walk plus a dict increment (histograms add one ``math.log``),
and every instrument the engine touches per flush is pre-created at
engine construction, so no name lookup ever happens inside a flush.

When the registry is created disabled (``YTPU_OBS_DISABLED=1`` at engine
construction), every factory returns the shared no-op metric and the
exposition surface is empty — the hot path then pays a single branch.

Labels follow the Prometheus model: a metric family is registered once
with its label NAMES; ``labels(**values)`` returns (and caches) the child
holding the actual series.  Callers on hot paths should hold the child,
not re-resolve it per event.
"""

from __future__ import annotations

import math


class _NoopMetric:
    """Shared do-nothing stand-in when observability is disabled."""

    __slots__ = ()

    def inc(self, amount=1):
        pass

    def dec(self, amount=1):
        pass

    def set(self, value):
        pass

    def observe(self, value):
        pass

    def labels(self, **label_values):
        return self

    @property
    def value(self):
        return 0


NOOP_METRIC = _NoopMetric()


class _Metric:
    """Family/child base: a family carries label names and children; an
    unlabeled metric is its own single series."""

    kind = "untyped"

    __slots__ = ("name", "help", "unit", "labelnames", "_children")

    def __init__(self, name, help="", unit="", labelnames=()):
        self.name = name
        self.help = help
        self.unit = unit
        self.labelnames = tuple(labelnames)
        self._children = {} if self.labelnames else None

    def labels(self, **label_values):
        if not self.labelnames:
            return self
        key = tuple(str(label_values[n]) for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            child = type(self)(self.name, self.help, self.unit)
            self._children[key] = child
        return child

    def samples(self):
        """Yield ``(label_dict, series)`` pairs — one per child, or the
        metric itself when unlabeled."""
        if self.labelnames:
            for key in sorted(self._children):
                yield dict(zip(self.labelnames, key)), self._children[key]
        else:
            yield {}, self


class Counter(_Metric):
    """Monotonically increasing count (events, bytes)."""

    kind = "counter"

    __slots__ = ("_value",)

    def __init__(self, name, help="", unit="", labelnames=()):
        super().__init__(name, help, unit, labelnames)
        self._value = 0

    def inc(self, amount=1):
        self._value += amount

    @property
    def value(self):
        return self._value


class Gauge(_Metric):
    """Point-in-time value (occupancy, capacity, pool width)."""

    kind = "gauge"

    __slots__ = ("_value",)

    def __init__(self, name, help="", unit="", labelnames=()):
        super().__init__(name, help, unit, labelnames)
        self._value = 0

    def set(self, value):
        self._value = value

    def inc(self, amount=1):
        self._value += amount

    def dec(self, amount=1):
        self._value -= amount

    @property
    def value(self):
        return self._value


# 8 log-spaced buckets per octave (edges at 2**(i/8)): every observation
# lands within ~4.5% of its bucket's geometric midpoint, so p50/p95/p99
# read back with bounded relative error at O(1) memory per decade
_LOG_STEP = math.log(2.0) / 8.0


class Histogram(_Metric):
    """Log-bucketed distribution with p50/p95/p99 summaries.

    Exact ``count``/``sum``/``min``/``max`` are tracked alongside the
    buckets; quantiles interpolate to a bucket's geometric midpoint and
    are clamped into ``[min, max]``.  Zero/negative observations land in
    a dedicated underflow bucket (reported as ``min``)."""

    kind = "histogram"

    __slots__ = ("_buckets", "_zero", "_count", "_sum", "_min", "_max")

    def __init__(self, name, help="", unit="", labelnames=()):
        super().__init__(name, help, unit, labelnames)
        self._buckets = {}
        self._zero = 0
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, value):
        v = float(value)
        self._count += 1
        self._sum += v
        if v < self._min:
            self._min = v
        if v > self._max:
            self._max = v
        if v <= 0.0:
            self._zero += 1
        else:
            i = math.floor(math.log(v) / _LOG_STEP)
            self._buckets[i] = self._buckets.get(i, 0) + 1

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def quantile(self, q):
        """The q-quantile (q in [0, 1]) from the bucket counts."""
        if not self._count:
            return 0.0
        target = q * self._count
        seen = self._zero
        if self._zero and seen >= target:
            return self._min
        for i in sorted(self._buckets):
            seen += self._buckets[i]
            if seen >= target:
                mid = math.exp((i + 0.5) * _LOG_STEP)
                return min(max(mid, self._min), self._max)
        return self._max

    def summary(self):
        if not self._count:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "p50": 0.0, "p95": 0.0, "p99": 0.0}
        # all three quantiles from one sorted walk (this sits on the
        # TSDB sampler tick, which summarizes every histogram)
        targets = (0.50 * self._count, 0.95 * self._count,
                   0.99 * self._count)
        qs = [self._max, self._max, self._max]
        idx = 0
        seen = self._zero
        while idx < 3 and self._zero and seen >= targets[idx]:
            qs[idx] = self._min
            idx += 1
        if idx < 3:
            for i in sorted(self._buckets):
                seen += self._buckets[i]
                while idx < 3 and seen >= targets[idx]:
                    mid = math.exp((i + 0.5) * _LOG_STEP)
                    qs[idx] = min(max(mid, self._min), self._max)
                    idx += 1
                if idx == 3:
                    break
        return {
            "count": self._count,
            "sum": self._sum,
            "min": self._min,
            "max": self._max,
            "p50": qs[0],
            "p95": qs[1],
            "p99": qs[2],
        }


class MetricsRegistry:
    """Name -> metric-family map with Prometheus-style registration.

    Re-registering an existing name returns the existing family (so
    module-level consumers and the engine can share series); a kind
    mismatch on an existing name raises."""

    def __init__(self, enabled=True):
        self.enabled = enabled
        self._metrics: dict[str, _Metric] = {}

    def _register(self, cls, name, help, unit, labelnames):
        if not self.enabled:
            return NOOP_METRIC
        m = self._metrics.get(name)
        if m is None:
            m = cls(name, help=help, unit=unit, labelnames=labelnames)
            self._metrics[name] = m
        elif not isinstance(m, cls):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}"
            )
        return m

    def counter(self, name, help="", unit="", labelnames=()):
        return self._register(Counter, name, help, unit, labelnames)

    def gauge(self, name, help="", unit="", labelnames=()):
        return self._register(Gauge, name, help, unit, labelnames)

    def histogram(self, name, help="", unit="", labelnames=()):
        return self._register(Histogram, name, help, unit, labelnames)

    def get(self, name):
        return self._metrics.get(name)

    def names(self):
        return sorted(self._metrics)

    def collect(self):
        """Metric families in name order (empty when disabled)."""
        for name in sorted(self._metrics):
            yield self._metrics[name]
