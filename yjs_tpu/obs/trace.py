"""Host-side span tracing with Chrome-trace-format JSON export.

Records phase spans (compact/plan/pack/dispatch/emit, planner-pool and
demotion events) into an in-memory bounded buffer and exports the
Chrome ``traceEvents`` JSON that Perfetto / chrome://tracing load
directly.  This LAYERS ON the existing ``jax.profiler.TraceAnnotation``
wrappers (which only surface inside an active device profiler trace) —
the host spans are always available, profiler attached or not.

``YTPU_TRACE_PATH=<file>`` makes every tracer created while the variable
is set register for an atexit dump: all their events merge into one
Chrome-trace JSON at interpreter exit.  ``Tracer.save(path)`` writes one
tracer's trace explicitly.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque

DEFAULT_MAX_EVENTS = 200_000


class _Span:
    """Reusable context manager recording one complete ("X") event."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer, name, args):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        tr = self._tracer
        tr._events.append((
            self._name,
            "X",
            (self._t0 - tr._t0) * 1e6,
            (t1 - self._t0) * 1e6,
            threading.get_ident(),
            self._args,
        ))
        return False


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Bounded in-memory span/event recorder (oldest events evicted)."""

    def __init__(self, enabled: bool = True, max_events: int | None = None):
        self.enabled = enabled
        if max_events is None:
            try:
                max_events = int(
                    os.environ.get("YTPU_TRACE_EVENTS", DEFAULT_MAX_EVENTS)
                )
            except ValueError:
                max_events = DEFAULT_MAX_EVENTS
        # (name, ph, ts_us, dur_us, tid, args) tuples
        self._events: deque = deque(maxlen=max(16, max_events))
        self._t0 = time.perf_counter()
        self.pid = os.getpid()
        if enabled and os.environ.get("YTPU_TRACE_PATH"):
            _register_for_exit_dump(self)

    def span(self, name: str, **args):
        """Context manager recording a complete span around its body."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        """Record a zero-duration marker (demotion, pool event, ...)."""
        if not self.enabled:
            return
        self._events.append((
            name,
            "i",
            (time.perf_counter() - self._t0) * 1e6,
            0.0,
            threading.get_ident(),
            args or None,
        ))

    def __len__(self) -> int:
        return len(self._events)

    def clear(self) -> None:
        self._events.clear()

    def trace_events(self) -> list[dict]:
        """Chrome ``traceEvents`` list, sorted by timestamp."""
        out = []
        for name, ph, ts, dur, tid, args in sorted(
            self._events, key=lambda e: e[2]
        ):
            ev = {
                "name": name,
                "ph": ph,
                "ts": ts,
                "pid": self.pid,
                "tid": tid,
                "cat": "ytpu",
            }
            if ph == "X":
                ev["dur"] = dur
            else:  # instant events: thread scope
                ev["s"] = "t"
            if args:
                ev["args"] = args
            out.append(ev)
        return out

    def chrome_trace(self) -> dict:
        """The full Chrome-trace JSON object (loadable by Perfetto)."""
        return {
            "traceEvents": self.trace_events(),
            "displayTimeUnit": "ms",
        }

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


# -- YTPU_TRACE_PATH atexit dump --------------------------------------------

_EXIT_TRACERS: list[Tracer] = []
_EXIT_REGISTERED = False


def _register_for_exit_dump(tracer: Tracer) -> None:
    global _EXIT_REGISTERED
    _EXIT_TRACERS.append(tracer)
    if not _EXIT_REGISTERED:
        atexit.register(_dump_exit_traces)
        _EXIT_REGISTERED = True


def _dump_exit_traces() -> None:
    path = os.environ.get("YTPU_TRACE_PATH")
    if not path or not _EXIT_TRACERS:
        return
    events: list[dict] = []
    for tr in _EXIT_TRACERS:
        events.extend(tr.trace_events())
    events.sort(key=lambda e: e["ts"])
    try:
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    except OSError:
        pass  # tracing must never take the process down at exit
