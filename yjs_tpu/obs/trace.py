"""Host-side span tracing with Chrome-trace-format JSON export.

Records phase spans (compact/plan/pack/dispatch/emit, planner-pool and
demotion events) into an in-memory bounded buffer and exports the
Chrome ``traceEvents`` JSON that Perfetto / chrome://tracing load
directly.  This LAYERS ON the existing ``jax.profiler.TraceAnnotation``
wrappers (which only surface inside an active device profiler trace) —
the host spans are always available, profiler attached or not.

``YTPU_TRACE_PATH=<file>`` makes every tracer created while the variable
is set register for an atexit dump: all their events merge into one
Chrome-trace JSON at interpreter exit.  ``Tracer.save(path)`` writes one
tracer's trace explicitly.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque

DEFAULT_MAX_EVENTS = 200_000


class _Span:
    """Reusable context manager recording one complete ("X") event."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer, name, args):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        tr = self._tracer
        tr._events.append((
            self._name,
            "X",
            (self._t0 - tr._t0) * 1e6,
            (t1 - self._t0) * 1e6,
            threading.get_ident(),
            self._args,
            None,
        ))
        return False


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Bounded in-memory span/event recorder (oldest events evicted)."""

    def __init__(self, enabled: bool = True, max_events: int | None = None):
        self.enabled = enabled
        if max_events is None:
            try:
                max_events = int(
                    os.environ.get("YTPU_TRACE_EVENTS", DEFAULT_MAX_EVENTS)
                )
            except ValueError:
                max_events = DEFAULT_MAX_EVENTS
        # (name, ph, ts_us, dur_us, tid, args, flow_id) tuples
        self._events: deque = deque(maxlen=max(16, max_events))
        self._t0 = time.perf_counter()
        self.pid = os.getpid()
        self.process_name = "ytpu"
        self._thread_names: dict[int, str] = {}
        if enabled and os.environ.get("YTPU_TRACE_PATH"):
            _register_for_exit_dump(self)

    def span(self, name: str, **args):
        """Context manager recording a complete span around its body."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        """Record a zero-duration marker (demotion, pool event, ...)."""
        if not self.enabled:
            return
        self._events.append((
            name,
            "i",
            (time.perf_counter() - self._t0) * 1e6,
            0.0,
            threading.get_ident(),
            args or None,
            None,
        ))

    def flow_start(self, name: str, flow_id: int, **args) -> None:
        """Open a flow arrow (Perfetto ``ph="s"``): call inside the span
        the arrow should leave from (e.g. a provider receive span)."""
        self._flow(name, "s", flow_id, args)

    def flow_end(self, name: str, flow_id: int, **args) -> None:
        """Close a flow arrow (``ph="f"``, ``bp="e"`` so it binds to the
        enclosing slice): call inside the span the arrow lands on (the
        flush that applied the update)."""
        self._flow(name, "f", flow_id, args)

    def _flow(self, name, ph, flow_id, args) -> None:
        if not self.enabled:
            return
        self._events.append((
            name,
            ph,
            (time.perf_counter() - self._t0) * 1e6,
            0.0,
            threading.get_ident(),
            args or None,
            int(flow_id),
        ))

    def name_thread(self, name: str) -> None:
        """Label the calling thread in exported traces (a ``thread_name``
        metadata event; unnamed threads render as ``host-<tid>``)."""
        self._thread_names[threading.get_ident()] = name

    def __len__(self) -> int:
        return len(self._events)

    def clear(self) -> None:
        self._events.clear()

    def trace_events(self) -> list[dict]:
        """Chrome ``traceEvents`` list: ``pid``/``tid`` metadata ("M")
        events first, then recorded events sorted by timestamp."""
        if not self._events:
            return []
        out = []
        tids = sorted({e[4] for e in self._events})
        meta = [{
            "name": "process_name", "ph": "M", "ts": 0.0,
            "pid": self.pid, "tid": tids[0], "cat": "__metadata",
            "args": {"name": self.process_name},
        }]
        for tid in tids:
            meta.append({
                "name": "thread_name", "ph": "M", "ts": 0.0,
                "pid": self.pid, "tid": tid, "cat": "__metadata",
                "args": {
                    "name": self._thread_names.get(tid, f"host-{tid}")
                },
            })
        for name, ph, ts, dur, tid, args, flow_id in sorted(
            self._events, key=lambda e: e[2]
        ):
            ev = {
                "name": name,
                "ph": ph,
                "ts": ts,
                "pid": self.pid,
                "tid": tid,
                "cat": "ytpu",
            }
            if ph == "X":
                ev["dur"] = dur
            elif ph == "i":  # instant events: thread scope
                ev["s"] = "t"
            if flow_id is not None:
                ev["id"] = flow_id
            if ph == "f":
                # bind the arrow to the ENCLOSING slice, not the next one
                ev["bp"] = "e"
            if args:
                ev["args"] = args
            out.append(ev)
        return meta + out

    def chrome_trace(self) -> dict:
        """The full Chrome-trace JSON object (loadable by Perfetto)."""
        return {
            "traceEvents": self.trace_events(),
            "displayTimeUnit": "ms",
        }

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


# -- YTPU_TRACE_PATH atexit dump --------------------------------------------

_EXIT_TRACERS: list[Tracer] = []
_EXIT_REGISTERED = False


def _register_for_exit_dump(tracer: Tracer) -> None:
    global _EXIT_REGISTERED
    _EXIT_TRACERS.append(tracer)
    if not _EXIT_REGISTERED:
        atexit.register(_dump_exit_traces)
        _EXIT_REGISTERED = True


def _dump_exit_traces() -> None:
    path = os.environ.get("YTPU_TRACE_PATH")
    if not path or not _EXIT_TRACERS:
        return
    events: list[dict] = []
    for tr in _EXIT_TRACERS:
        events.extend(tr.trace_events())
    events.sort(key=lambda e: e["ts"])
    try:
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    except OSError:
        pass  # tracing must never take the process down at exit
