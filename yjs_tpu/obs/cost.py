"""Per-doc / per-tenant cost attribution (ISSUE 19 tentpole).

The fleet can say *how much* device time a flush burned (ISSUE 4
profiler) and *how many* bytes the WAL wrote, but not **who** caused
it.  The :class:`CostLedger` rides the seams where attribution is
cheap and unambiguous:

- **ingress** (``receive_update`` / admitted-queue drain / session
  frames): stages each doc's pending bytes;
- **flush** (the ISSUE 12 unified flush seam): splits the flush's
  device time (``t_dispatch_s``) and host pack/plan time
  (``t_compact_s + t_plan_s + t_pack_s + t_emit_s``) across the staged
  docs proportionally to their staged bytes;
- **WAL append**, **replication fan-out**, **session frames**, and the
  ISSUE 17 **geo links** each add their own dimension at the call
  site.

Tenants derive from the ``tenant/doc`` guid convention (ISSUE 10's
``AdmissionController.tenant_of``).  Cardinality stays bounded the
Monarch way — **top-K exact + sampled tail**: up to
``YTPU_COST_MAX_DOCS`` docs (and ``YTPU_COST_MAX_TENANTS`` tenants)
are tracked exactly; when the map overflows to twice the cap it is
compacted to the K heaviest and everything else folds into one
``__other__`` bucket, whose updates may additionally be 1-in-N sampled
(``YTPU_COST_TAIL_SAMPLE``, recorded scaled so totals stay unbiased).

Per-tenant totals are exported as ``ytpu_cost_*`` counter families on
the provider's registry, so they flow into the embedded TSDB
(``obs/tsdb.py``) automatically — "who burned the device last hour"
is one ``/query``.  ``YTPU_COST_DISABLED=1`` freezes accumulation
(families still register: the exposition surface is part of the
schema contract); the ledger touches no engine state either way, so
engine output is byte-identical on or off.
"""

from __future__ import annotations

import os
import threading
from collections import deque

__all__ = ["CostLedger", "DIMS", "cost_enabled"]


def cost_enabled() -> bool:
    """Accumulation toggle — ``YTPU_COST_DISABLED=1`` freezes the
    ledger (families still register; engine state untouched either
    way)."""
    return os.environ.get("YTPU_COST_DISABLED", "") != "1"

# accumulator dimensions, in storage order
DIMS = (
    "device_s", "host_s", "wal_bytes", "repl_bytes",
    "session_frames", "geo_bytes",
)
_D_DEVICE, _D_HOST, _D_WAL, _D_REPL, _D_FRAMES, _D_GEO = range(6)
_OTHER = "__other__"
# flush epochs queued before the proportional distribution settles (it
# also settles at every read).  Keeps the flush seam itself O(1) and
# lets one settling pass run its loop cache-hot across the whole batch;
# 32 flushes is still well inside one sampler tick at any realistic
# flush cadence, so the exported families never lag a visible sample
_DRAIN_EVERY = 32


def _env_int(name: str, default: int, lo: int = 1) -> int:
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        return default
    return max(lo, v)


_tenant_fn = None


def _tenant_of(guid: str) -> str:
    # resolved once: a per-call import sits on the flush seam and costs
    # more than the accounting itself (admission imports obs, so the
    # lazy first call also breaks the cycle)
    global _tenant_fn
    if _tenant_fn is None:
        from ..admission import AdmissionController

        _tenant_fn = AdmissionController.tenant_of
    return _tenant_fn(guid)


class CostLedger:
    """Bounded per-doc / per-tenant cost accumulators (module
    docstring).  All mutation is lock-guarded: the admin plane
    snapshots concurrently with the flush path."""

    def __init__(
        self,
        registry,
        max_docs: int | None = None,
        max_tenants: int | None = None,
        tail_sample: int | None = None,
    ):
        self.max_docs = (
            max_docs
            if max_docs is not None
            else _env_int("YTPU_COST_MAX_DOCS", 512)
        )
        self.max_tenants = (
            max_tenants
            if max_tenants is not None
            else _env_int("YTPU_COST_MAX_TENANTS", 64)
        )
        self.tail_sample = (
            tail_sample
            if tail_sample is not None
            else _env_int("YTPU_COST_TAIL_SAMPLE", 1)
        )
        self.disabled = not cost_enabled()
        self._lock = threading.Lock()
        # guid -> [6 floats, tenant]; tenant -> [6 floats].  The doc
        # row carries its resolved tenant as a 7th element so the
        # flush-seam drain does one dict hit per doc, not two
        self._docs: dict = {}
        self._tenants: dict = {}
        self._tail = [0.0] * 6  # docs folded out of the exact map
        # recently-folded guids (bounded FIFO): contributions for these
        # take the sampled-tail path instead of re-entering the exact
        # map, damping fold/unfold churn under doc cardinality storms
        self._folded_ring: deque = deque(maxlen=4 * self.max_docs)
        self._folded_set: set = set()
        # bytes staged per doc since the last flush (attribution weights)
        self._staged: dict = {}
        # queued flush epochs: (staged map, device_s, host_s) awaiting
        # batched distribution (see on_flush)
        self._pending: list = []
        self._n_folded = 0
        self._tail_skip = 0  # deterministic 1-in-N tail sampling state
        # families register unconditionally (schema contract); the
        # tenant label set is bounded by the tenant cap + __other__
        r = registry
        self.m_device = r.counter(
            "ytpu_cost_device_seconds_total",
            "Device (dispatch) seconds attributed per tenant via the "
            "flush seam, staged-bytes weighted",
            labelnames=("tenant",), unit="seconds",
        )
        self.m_host = r.counter(
            "ytpu_cost_host_seconds_total",
            "Host compact+plan+pack+emit seconds attributed per tenant",
            labelnames=("tenant",), unit="seconds",
        )
        self.m_wal = r.counter(
            "ytpu_cost_wal_bytes_total",
            "WAL bytes journaled per tenant (update ingress)",
            labelnames=("tenant",), unit="bytes",
        )
        self.m_repl = r.counter(
            "ytpu_cost_repl_bytes_total",
            "Intra-fleet replication fan-out bytes enqueued per tenant",
            labelnames=("tenant",), unit="bytes",
        )
        self.m_frames = r.counter(
            "ytpu_cost_session_frames_total",
            "Session-layer frames handled per tenant",
            labelnames=("tenant",),
        )
        self.m_geo = r.counter(
            "ytpu_cost_geo_link_bytes_total",
            "Geo WAN link bytes per peer region: shipped payloads and "
            "budget-deferred bytes (counted when they finally ship)",
            labelnames=("peer", "kind"), unit="bytes",
        )
        self.m_tracked = r.gauge(
            "ytpu_cost_tracked_docs",
            "Docs currently tracked exactly by the cost ledger "
            "(bounded by YTPU_COST_MAX_DOCS)",
        )
        self.m_folded = r.counter(
            "ytpu_cost_folded_docs_total",
            "Docs folded into the sampled __other__ tail bucket by "
            "top-K compaction",
        )
        # labeled-child cache: (dim, tenant) -> counter child.  labels()
        # rebuilds a key tuple per call, which dominates the flush-seam
        # hot path; cardinality is bounded by the tenant cap x 5 dims
        self._dim_fams = (self.m_device, self.m_host, self.m_wal,
                          self.m_repl, self.m_frames)
        self._mchild: dict = {}
        # guid -> tenant memo (the staged set repeats every flush);
        # cleared wholesale when it outgrows the doc bound
        self._tenant_memo: dict = {}
        # when set (on_flush only), _metric_for accumulates here and the
        # export collapses to one inc per (dim, tenant) after the loop
        self._defer: dict | None = None

    # -- attribution hooks ---------------------------------------------------

    def staged(self, guid: str, nbytes: int) -> None:
        """One ingress update staged for the next flush (the
        attribution weight for that flush's device/host time).

        Lock-free by design: dict get/set are GIL-atomic, and the only
        concurrent reader is ``on_flush``'s swap — a write racing the
        swap can land in the outgoing dict and lose one update's
        attribution WEIGHT (never any cost: the flush's seconds are
        fully distributed over the weights that remain).  That bounded
        imprecision buys the hot ingress path out of a lock acquire."""
        if self.disabled:
            return
        s = self._staged  # ytpu-lint: disable=lock-discipline -- GIL-atomic dict ops; a racing flush swap loses at most one update's attribution weight, never cost (see docstring)
        s[guid] = s.get(guid, 0) + int(nbytes)

    def wal_bytes(self, guid: str, nbytes: int) -> None:
        if self.disabled:
            return
        self._add(guid, _D_WAL, float(nbytes))

    def repl_bytes(self, guid: str, nbytes: int) -> None:
        if self.disabled:
            return
        self._add(guid, _D_REPL, float(nbytes))

    def session_frame(self, guid: str, n: int = 1) -> None:
        if self.disabled:
            return
        self._add(guid, _D_FRAMES, float(n))

    def geo_bytes(self, peer: str, nbytes: int, kind: str = "shipped"
                  ) -> None:
        """Per-link WAN bytes (ISSUE 19 satellite): ``kind`` is
        ``shipped`` for payloads sent or ``deferred`` for bytes the
        budget held back (counted when they eventually ship)."""
        if self.disabled:
            return
        self.m_geo.labels(peer=str(peer), kind=kind).inc(int(nbytes))

    def on_flush(self, flush_metrics: dict | None) -> None:
        """Record one flush's device/host seconds against the docs
        staged since the previous flush; the staging map resets either
        way.

        The flush seam itself is O(1): each flush enqueues an epoch
        (its own staged map + its own seconds), and the proportional
        distribution settles in batches — every ``_DRAIN_EVERY`` flushes
        and at every read (:meth:`totals` / :meth:`snapshot`).  Each
        epoch keeps its own weights, so the settled numbers are
        bit-identical to distributing synchronously; only the exported
        per-tenant counter families can lag by up to the batch depth."""
        if self.disabled or not flush_metrics:
            return
        device = float(flush_metrics.get("t_dispatch_s", 0.0) or 0.0)
        host = sum(
            float(flush_metrics.get(k, 0.0) or 0.0)
            for k in ("t_compact_s", "t_plan_s", "t_pack_s", "t_emit_s")
        )
        with self._lock:
            staged, self._staged = self._staged, {}
            if not staged:
                return
            self._pending.append((staged, device, host))
            if len(self._pending) >= _DRAIN_EVERY:
                self._drain_pending()

    def _drain_pending(self) -> None:
        """Settle queued flush epochs (caller holds the lock).

        One lock hold for the whole batch (2 dims x N docs per epoch):
        per-doc locking doubles the cost for zero benefit.  The
        tracked-doc common case is inlined — two bound-method dict hits
        per doc instead of two full _add_locked calls — and the metric
        export collapses to one inc per (dim, tenant): the doc count
        per batch is unbounded but the tenant set is capped."""
        if not self._pending:  # ytpu-lint: disable=lock-discipline -- caller holds the lock: _drain_pending is only reached from on_flush's / the readers' locked sections
            return
        pending, self._pending = self._pending, []  # ytpu-lint: disable=lock-discipline -- caller holds the lock: _drain_pending is only reached from on_flush's / the readers' locked sections
        # tenant -> [device_s, host_s]: one exported inc per family
        # and tenant at the end; while the drain is active _metric_for
        # feeds the same map (only ever with the two flush dims, which
        # index the pair directly)
        defer = self._defer = {}
        docs_get = self._docs.get
        tenants_get = self._tenants.get
        defer_get = defer.get
        add_locked = self._add_locked
        D, H = _D_DEVICE, _D_HOST
        try:
            for staged, device, host in pending:
                total = sum(staged.values())
                if not total:
                    continue
                dev_u = device / total  # seconds per staged byte
                host_u = host / total
                last_tenant = trow = pair = None
                for guid, nbytes in staged.items():
                    a_dev = dev_u * nbytes
                    a_host = host_u * nbytes
                    row = docs_get(guid)
                    if row is None:
                        # new or folded doc: full bookkeeping path
                        # (compaction trigger, folded-tail sampling,
                        # tenant resolution) — it feeds `defer` itself
                        add_locked(guid, D, a_dev)
                        add_locked(guid, H, a_host)
                        continue
                    tenant = row[6]
                    row[D] += a_dev
                    row[H] += a_host
                    if tenant != last_tenant:
                        # staged maps run in guid order, so same-tenant
                        # docs cluster; one short string compare skips
                        # both lookups for the rest of the run
                        trow = tenants_get(tenant)
                        if trow is None:
                            # tenant-cap fold lives in _bump_tenant
                            eff = self._bump_tenant(tenant, D, a_dev)
                            self._bump_tenant(eff, H, a_host)
                            p = defer_get(eff)
                            if p is None:
                                defer[eff] = [a_dev, a_host]
                            else:
                                p[0] += a_dev
                                p[1] += a_host
                            last_tenant = None
                            continue
                        pair = defer_get(tenant)
                        if pair is None:
                            pair = defer[tenant] = [0.0, 0.0]
                        last_tenant = tenant
                    trow[D] += a_dev
                    trow[H] += a_host
                    pair[0] += a_dev
                    pair[1] += a_host
        finally:
            self._defer = None
            for tenant, (a_dev, a_host) in defer.items():
                self._metric_for(D, tenant, a_dev)
                self._metric_for(H, tenant, a_host)
        self.m_tracked.set(len(self._docs))

    # -- bounded accumulation ------------------------------------------------

    def _add(self, guid: str, dim: int, amount: float) -> None:
        if amount == 0.0:
            return
        with self._lock:
            self._add_locked(guid, dim, amount)
            self.m_tracked.set(len(self._docs))

    def _add_locked(self, guid: str, dim: int, amount: float) -> None:
        """Caller holds the lock (``on_flush`` batches the whole
        distribution under one hold; ``_add`` wraps for the hooks)."""
        if amount == 0.0:
            return
        row = self._docs.get(guid)
        if row is not None:
            row[dim] += amount
            eff = self._bump_tenant(row[6], dim, amount)
            self._metric_for(dim, eff, amount)
            return
        # untracked doc: resolve the tenant (memoized — folded docs
        # keep hitting this path, one per contribution)
        tenant = self._tenant_memo.get(guid)
        if tenant is None:
            if len(self._tenant_memo) >= 8 * self.max_docs:
                self._tenant_memo.clear()
            tenant = self._tenant_memo[guid] = _tenant_of(guid)
        if guid in self._folded_set:
            # a previously-folded doc: sampled tail, scaled so the
            # expected total stays unbiased (exact at N=1)
            self._tail_skip += 1
            if self._tail_skip >= self.tail_sample:
                self._tail_skip = 0
                self._tail[dim] += amount * self.tail_sample
            eff = self._bump_tenant(tenant, dim, amount)
            self._metric_for(dim, eff, amount)
            return
        if len(self._docs) >= 2 * self.max_docs:
            self._compact_docs()
        row = self._docs[guid] = [0.0, 0.0, 0.0, 0.0, 0.0, 0.0, tenant]
        row[dim] += amount
        eff = self._bump_tenant(tenant, dim, amount)
        self._metric_for(dim, eff, amount)

    def _bump_tenant(self, tenant: str, dim: int, amount: float) -> str:
        """Caller holds the lock.  Returns the effective tenant label
        (``__other__`` once the tenant cap is hit), which also bounds
        the exported families' label cardinality."""
        row = self._tenants.get(tenant)
        if row is None:
            if tenant not in self._tenants and (
                len(self._tenants) >= self.max_tenants
            ):
                tenant = _OTHER
                row = self._tenants.get(_OTHER)
            if row is None:
                row = self._tenants[tenant] = [0.0] * 6
        row[dim] += amount
        return tenant

    def _compact_docs(self) -> None:
        """Top-K compaction (caller holds the lock): keep the
        ``max_docs`` heaviest docs, fold the rest into the tail and
        remember them in the bounded folded ring."""
        ranked = sorted(
            self._docs.items(),
            key=lambda kv: (kv[1][_D_DEVICE] + kv[1][_D_HOST],
                            sum(kv[1][:6]), kv[0]),
            reverse=True,
        )
        folded = 0
        for guid, row in ranked[self.max_docs:]:
            del self._docs[guid]
            if len(self._folded_ring) == self._folded_ring.maxlen:
                self._folded_set.discard(self._folded_ring[0])
            self._folded_ring.append(guid)
            self._folded_set.add(guid)
            for d in range(6):
                self._tail[d] += row[d]
            folded += 1
        self._n_folded += folded
        self.m_folded.inc(folded)
        self.m_tracked.set(len(self._docs))

    def _metric_for(self, dim: int, tenant: str, amount: float) -> None:
        # per-tenant exported families: label cardinality bounded by
        # the tenant cap (overflow tenants land on __other__ above,
        # but the label here follows the exact tenant until then)
        if dim >= len(self._dim_fams):  # geo_bytes is metric-only
            return
        if self._defer is not None:
            # drain-active: only the two flush dims reach here, and
            # they index the [device_s, host_s] pair directly
            pair = self._defer.get(tenant)
            if pair is None:
                self._defer[tenant] = pair = [0.0, 0.0]
            pair[dim] += amount
            return
        child = self._mchild.get((dim, tenant))
        if child is None:
            child = self._dim_fams[dim].labels(tenant=tenant)
            self._mchild[(dim, tenant)] = child
        child.inc(amount if dim <= _D_HOST else int(amount))

    # -- read side -----------------------------------------------------------

    def totals(self) -> dict:
        """Conservation check surface: exact per-doc sums + tail, per
        dimension (the 10k-doc churn test pins tracked+tail == fed)."""
        with self._lock:
            self._drain_pending()
            rows = list(self._docs.values())
            tail = list(self._tail)
        return {
            dim: sum(r[i] for r in rows) + tail[i]
            for i, dim in enumerate(DIMS)
        }

    def snapshot(self, top: int = 10) -> dict:
        """JSON-able ledger view: top docs by device+host burn, every
        tracked tenant, the folded tail, and the caps."""
        with self._lock:
            self._drain_pending()
            docs = sorted(
                self._docs.items(),
                key=lambda kv: (kv[1][_D_DEVICE] + kv[1][_D_HOST],
                                sum(kv[1][:6]), kv[0]),
                reverse=True,
            )[:max(0, top)]
            tenants = {
                t: dict(zip(DIMS, row))
                for t, row in sorted(self._tenants.items())
            }
            tail = dict(zip(DIMS, self._tail))
            n_docs = len(self._docs)
            n_folded = self._n_folded
        return {
            "tracked_docs": n_docs,
            "folded_docs": n_folded,
            "max_docs": self.max_docs,
            "max_tenants": self.max_tenants,
            "tail_sample": self.tail_sample,
            "disabled": self.disabled,
            "top": [
                {"guid": g, "tenant": row[6],
                 **dict(zip(DIMS, row))}
                for g, row in docs
            ],
            "tenants": tenants,
            "tail": tail,
        }
