"""Cross-shard metrics federation (ISSUE 11 tentpole, part 3).

Merges N shard/provider metric snapshots (the
:func:`~yjs_tpu.obs.expo.registry_snapshot` shape) into ONE labeled
view:

- **counters sum** across sources per labels-key (events are additive
  across shards);
- **gauges keep per-shard series** — each source's series re-labeled
  with ``shard=<label>,role=<role>`` — plus a summed aggregate under
  the original labels-key so single-provider dashboards (``ytpu_top``
  columns, ``collect_row``) keep reading the unlabeled series;
- **histograms merge**: counts and sums add, min/max widen, and
  quantiles are count-weighted across sources (the snapshot shape
  carries summaries, not raw buckets — the weighted estimate is exact
  for count/sum/min/max and a documented approximation for p50/p95/p99).

Three input paths share the merge:

- **in-process** (``FleetRouter.metrics_snapshot``): per-shard
  engine-local registries, with the process-global registry layered in
  once, un-summed — global families are shared by every shard, so
  summing them would multiply by N;
- **file scrape** (:func:`read_snapshot_dir`): a directory of per-shard
  snapshot JSON files, one process each — what ``ytpu_top <dir>`` and
  ``ytpu_stats --merge`` consume, and the supervisor's fallback when
  the admin plane is disabled;
- **HTTP scrape** (:func:`scrape_endpoints`, ISSUE 16): GET each
  process's ``/metrics.json`` admin endpoint — the mode multi-host
  clusters use, since remote shards share no filesystem.

Both scrape paths are hardened against mid-write / mid-death races: a
file deleted between listdir and open, a truncated JSON body, or an
endpoint closing the socket mid-response all yield a **stale-marked
empty source** (counted in ``ytpu_fed_scrape_errors_total{mode}``),
never an exception — a dying shard renders a blank row, it does not
take the dashboard down with it.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import urllib.parse
import urllib.request
from typing import Iterable, Optional

__all__ = [
    "federate_snapshots",
    "read_snapshot_dir",
    "scrape_endpoints",
    "merge_summaries",
    "FederationMetrics",
    "fed_metrics",
]


def _labels_join(base: str, extra: str) -> str:
    if not base:
        return extra
    if not extra:
        return base
    return f"{base},{extra}"


def merge_summaries(parts: Iterable[dict]) -> dict:
    """Merge histogram summaries: exact count/sum/min/max, count-weighted
    quantile estimates."""
    count = 0
    total = 0.0
    mn = None
    mx = None
    q = {"p50": 0.0, "p95": 0.0, "p99": 0.0}
    for s in parts:
        c = int(s.get("count", 0))
        if not c:
            continue
        count += c
        total += float(s.get("sum", 0.0))
        smn, smx = float(s.get("min", 0.0)), float(s.get("max", 0.0))
        mn = smn if mn is None else min(mn, smn)
        mx = smx if mx is None else max(mx, smx)
        for k in q:
            q[k] += c * float(s.get(k, 0.0))
    if not count:
        return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                "p50": 0.0, "p95": 0.0, "p99": 0.0}
    out = {"count": count, "sum": total, "min": mn, "max": mx}
    for k, v in q.items():
        out[k] = min(max(v / count, mn), mx)
    return out


def federate_snapshots(sources: list[dict],
                       global_snapshot: Optional[dict] = None) -> dict:
    """Merge per-shard snapshots into one federated snapshot.

    ``sources`` is a list of ``{"label": str, "role": str,
    "snapshot": dict}`` entries (``role`` optional).  The result keeps
    the ``{counters, gauges, histograms}`` snapshot shape (so every
    existing renderer works on it) plus a ``federation`` block naming
    the sources merged.  ``global_snapshot``, when given, is layered in
    once without summing — for in-process fleets whose shards all share
    the process-global registry."""
    counters: dict = {}
    gauges: dict = {}
    histograms: dict = {}
    hist_parts: dict = {}
    roles: dict = {}

    for src in sources:
        label = str(src.get("label", "?"))
        role = str(src.get("role", "") or "")
        snap = src.get("snapshot") or {}
        roles[label] = role
        shard_labels = f"shard={label}" + (f",role={role}" if role else "")
        for name, series in (snap.get("counters") or {}).items():
            dst = counters.setdefault(name, {})
            for lk, v in series.items():
                dst[lk] = dst.get(lk, 0) + v
        for name, series in (snap.get("gauges") or {}).items():
            dst = gauges.setdefault(name, {})
            for lk, v in series.items():
                dst[_labels_join(lk, shard_labels)] = v
                dst[lk] = dst.get(lk, 0) + v
        for name, series in (snap.get("histograms") or {}).items():
            dst = hist_parts.setdefault(name, {})
            for lk, s in series.items():
                dst.setdefault(lk, []).append(s)

    for name, series in hist_parts.items():
        histograms[name] = {
            lk: merge_summaries(parts) for lk, parts in series.items()
        }

    if global_snapshot:
        for section, dst in (("counters", counters), ("gauges", gauges),
                             ("histograms", histograms)):
            for name, series in (global_snapshot.get(section) or {}).items():
                if name not in dst:
                    dst[name] = dict(series)

    return {
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "federation": {
            "sources": len(sources),
            "roles": roles,
            "stale": sorted(
                str(s.get("label", "?")) for s in sources if s.get("stale")
            ),
        },
    }


def read_snapshot_dir(path: str, cache: dict | None = None) -> list[dict]:
    """Load every ``*.json`` metrics snapshot in a directory as a
    federation source (label = file stem, role from the snapshot's own
    ``role`` key when present).  Unreadable files — deleted between
    listdir and open, or caught mid-write — contribute a stale-marked
    empty snapshot and count in
    ``ytpu_fed_scrape_errors_total{mode="file"}``: a dying shard
    renders a blank row, never crashes the dashboard.

    ``cache`` (caller-owned dict, e.g. one per ytpu_top watcher) skips
    re-parsing files whose ``(mtime_ns, size)`` did not change since
    the previous call — a ``--watch`` against a large fleet dir stops
    re-reading every snapshot every frame (ISSUE 19 satellite).
    Entries for files that vanished are pruned."""
    sources = []
    try:
        names = sorted(
            n for n in os.listdir(path) if n.endswith(".json")
        )
    except OSError:
        return sources
    seen = set()
    for n in names:
        label = n[: -len(".json")]
        full = os.path.join(path, n)
        seen.add(full)
        stamp = None
        if cache is not None:
            try:
                st = os.stat(full)
                stamp = (st.st_mtime_ns, st.st_size)
            except OSError:
                stamp = None
            hit = cache.get(full)
            if hit is not None and stamp is not None and hit[0] == stamp:
                sources.append(hit[1])
                continue
        snap: dict = {}
        stale = False
        try:
            with open(full) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            snap = {}
            stale = True
        if not isinstance(snap, dict):
            snap = {}
            stale = True
        if stale:
            fed_metrics().scrape_error("file")
        source = {
            "label": label,
            "role": str(snap.get("role", "") or ""),
            "snapshot": snap,
            "stale": stale,
        }
        # never cache a stale read: the writer may be mid-replace and
        # the next frame should retry the parse
        if cache is not None and stamp is not None and not stale:
            cache[full] = (stamp, source)
        sources.append(source)
    if cache is not None:
        for k in [k for k in cache if k not in seen]:
            del cache[k]
    return sources


def _endpoint_label(url: str) -> str:
    """A stable source label for one admin endpoint: host:port of the
    URL (the snapshot's own ``label`` key wins when present)."""
    try:
        parts = urllib.parse.urlsplit(url)
        return parts.netloc or url
    except ValueError:
        return url


def scrape_endpoints(
    urls: Iterable[str], timeout_s: float = 2.0
) -> list[dict]:
    """GET each admin endpoint's ``/metrics.json`` as a federation
    source (ISSUE 16 HTTP scrape mode).

    Each target gets its own ``timeout_s`` budget; a dead, hung, or
    mid-death endpoint (refused connection, timeout, socket closed
    mid-body, torn JSON) yields a **stale-marked empty source** and a
    ``ytpu_fed_scrape_errors_total{mode="http"}`` increment — partial
    failure is a rendering state, never a federation error.  ``urls``
    may be bare ``host:port``, a base URL, or a full ``…/metrics.json``
    path."""
    sources = []
    for url in urls:
        u = str(url).rstrip("/")
        if "://" not in u:
            u = "http://" + u
        if not u.endswith("/metrics.json"):
            u = u + "/metrics.json"
        snap: dict = {}
        stale = False
        try:
            with urllib.request.urlopen(u, timeout=timeout_s) as resp:
                body = resp.read()
            snap = json.loads(body.decode("utf-8"))
        except (OSError, ValueError, http.client.HTTPException):
            # URLError subclasses OSError (refused/timeout/reset);
            # a socket closed mid-body with a Content-Length promised
            # surfaces as http.client.IncompleteRead
            snap = {}
            stale = True
        if not isinstance(snap, dict):
            snap = {}
            stale = True
        if stale:
            fed_metrics().scrape_error("http")
        label = snap.get("label") or _endpoint_label(u)
        sources.append({
            "label": str(label),
            "role": str(snap.get("role", "") or ""),
            "snapshot": snap,
            "stale": stale,
            "url": str(url),
        })
    return sources


class FederationMetrics:
    """``ytpu_fed_*`` families on the process-global registry."""

    def __init__(self, registry=None) -> None:
        if registry is None:
            from . import global_registry

            registry = global_registry()
        self.sources = registry.gauge(
            "ytpu_fed_sources",
            "Shard/provider metric sources merged by the last "
            "federation pass",
        )
        self.merges = registry.counter(
            "ytpu_fed_merges_total",
            "Federated metric merges performed (fleet snapshots + file "
            "scrapes)",
        )
        self.scrape_errors = registry.counter(
            "ytpu_fed_scrape_errors_total",
            "Federation sources skipped as stale (unreadable snapshot "
            "file, or an admin endpoint that died mid-scrape), by "
            "scrape mode",
            labelnames=("mode",),
        )

    def observe(self, n_sources: int) -> None:
        self.sources.set(int(n_sources))
        self.merges.inc()

    def scrape_error(self, mode: str) -> None:
        self.scrape_errors.labels(mode=mode).inc()


_FED_METRICS: Optional[FederationMetrics] = None
_FED_LOCK = threading.Lock()


def fed_metrics() -> FederationMetrics:
    """Process-wide :class:`FederationMetrics` singleton — the module
    scrape functions have no registry handle of their own."""
    # cold path (one call per scrape pass): plain lock, like rpc_metrics
    global _FED_METRICS
    with _FED_LOCK:
        if _FED_METRICS is None:
            _FED_METRICS = FederationMetrics()
        return _FED_METRICS
