"""XML tree types: YXmlFragment, YXmlElement, YXmlText, YXmlHook, plus the
DFS tree walker (reference src/types/YXmlFragment.js, YXmlElement.js,
YXmlText.js, YXmlHook.js, YXmlEvent.js)."""

from __future__ import annotations

from ..core import (
    YXML_ELEMENT_REF_ID,
    YXML_FRAGMENT_REF_ID,
    YXML_HOOK_REF_ID,
    YXML_TEXT_REF_ID,
    transact,
    type_refs,
)
from .abstract import (
    AbstractType,
    call_type_observers,
    type_list_delete,
    type_list_for_each,
    type_list_get,
    type_list_insert_generics,
    type_list_insert_generics_after,
    type_list_map,
    type_list_slice,
    type_list_to_array,
    type_map_delete,
    type_map_get,
    type_map_get_all,
    type_map_set,
)
from .events import YEvent
from .ymap import YMap
from .ytext import YText


class YXmlEvent(YEvent):
    def __init__(self, target, subs, transaction):
        super().__init__(target, transaction)
        self.child_list_changed = False
        self.attributes_changed = set()
        for sub in subs:
            if sub is None:
                self.child_list_changed = True
            else:
                self.attributes_changed.add(sub)


class YXmlTreeWalker:
    """Depth-first walker over an XML subtree
    (reference YXmlFragment.js:55-116)."""

    def __init__(self, root, f=None):
        self._filter = f if f is not None else (lambda type_: True)
        self._root = root
        self._current_node = root._start
        self._first_call = True

    def __iter__(self):
        return self

    def __next__(self):
        n = self._current_node
        if n is None:
            raise StopIteration
        # gc'd children carry ContentDeleted with no .type; the reference's
        # short-circuit on n.deleted tolerates the undefined read
        type_ = getattr(n.content, "type", None)
        if not self._first_call or n.deleted or not self._filter(type_):
            while True:
                type_ = getattr(n.content, "type", None)
                if (
                    not n.deleted
                    and (type(type_) is YXmlElement or type(type_) is YXmlFragment)
                    and type_._start is not None
                ):
                    # walk down
                    n = type_._start
                else:
                    # walk right or up
                    while n is not None:
                        if n.right is not None:
                            n = n.right
                            break
                        elif n.parent is self._root:
                            n = None
                        else:
                            n = n.parent._item
                if n is None or (not n.deleted and self._filter(n.content.type)):
                    break
        self._first_call = False
        if n is None:
            raise StopIteration
        self._current_node = n
        return n.content.type

    # JS-style iteration protocol used by querySelector
    def next(self):
        try:
            return {"value": self.__next__(), "done": False}
        except StopIteration:
            return {"value": None, "done": True}


class YXmlFragment(AbstractType):
    def __init__(self):
        super().__init__()
        self._prelim_content: list | None = []

    @property
    def first_child(self):
        first = self._first
        return first.content.get_content()[0] if first else None

    def _integrate(self, y, item) -> None:
        super()._integrate(y, item)
        self.insert(0, self._prelim_content)
        self._prelim_content = None

    def _copy(self) -> "YXmlFragment":
        return YXmlFragment()

    def clone(self) -> "YXmlFragment":
        el = YXmlFragment()
        el.insert(
            0, [item.clone() if isinstance(item, AbstractType) else item for item in self.to_array()]
        )
        return el

    @property
    def length(self) -> int:
        return self._length if self._prelim_content is None else len(self._prelim_content)

    def __len__(self) -> int:
        return self.length

    def create_tree_walker(self, filter_) -> YXmlTreeWalker:
        return YXmlTreeWalker(self, filter_)

    def query_selector(self, query: str):
        query = query.upper()
        walker = YXmlTreeWalker(
            self,
            lambda element: getattr(element, "node_name", None) is not None
            and element.node_name.upper() == query,
        )
        nxt = walker.next()
        return None if nxt["done"] else nxt["value"]

    def query_selector_all(self, query: str) -> list:
        query = query.upper()
        return list(
            YXmlTreeWalker(
                self,
                lambda element: getattr(element, "node_name", None) is not None
                and element.node_name.upper() == query,
            )
        )

    def _call_observer(self, transaction, parent_subs) -> None:
        call_type_observers(self, transaction, YXmlEvent(self, parent_subs, transaction))

    def to_string(self) -> str:
        return "".join(type_list_map(self, lambda xml, i, t: xml.to_string()))

    def __str__(self) -> str:
        return self.to_string()

    def to_json(self) -> str:
        return self.to_string()

    def insert(self, index: int, content: list) -> None:
        if self.doc is not None:
            transact(self.doc, lambda txn: type_list_insert_generics(txn, self, index, content))
        else:
            self._prelim_content[index:index] = content

    def insert_after(self, ref, content: list) -> None:
        if self.doc is not None:
            def _ins(transaction):
                ref_item = ref._item if isinstance(ref, AbstractType) else ref
                type_list_insert_generics_after(transaction, self, ref_item, content)

            transact(self.doc, _ins)
        else:
            pc = self._prelim_content
            if ref is None:
                index = 0
            else:
                try:
                    index = pc.index(ref) + 1
                except ValueError:
                    raise LookupError("Reference item not found")
            pc[index:index] = content

    def delete(self, index: int, length: int = 1) -> None:
        if self.doc is not None:
            transact(self.doc, lambda txn: type_list_delete(txn, self, index, length))
        else:
            del self._prelim_content[index:index + length]

    def to_array(self) -> list:
        return type_list_to_array(self)

    def push(self, content: list) -> None:
        self.insert(self.length, content)

    def unshift(self, content: list) -> None:
        self.insert(0, content)

    def get(self, index: int):
        return type_list_get(self, index)

    def slice(self, start: int = 0, end: int | None = None) -> list:
        return type_list_slice(self, start, end if end is not None else self.length)

    def for_each(self, f) -> None:
        type_list_for_each(self, f)

    def _write(self, encoder) -> None:
        encoder.write_type_ref(YXML_FRAGMENT_REF_ID)


class YXmlElement(YXmlFragment):
    def __init__(self, node_name: str = "UNDEFINED"):
        super().__init__()
        self.node_name = node_name
        self._prelim_attrs: dict | None = {}

    @property
    def next_sibling(self):
        n = self._item.next if self._item else None
        return n.content.type if n else None

    @property
    def prev_sibling(self):
        n = self._item.prev if self._item else None
        return n.content.type if n else None

    def _integrate(self, y, item) -> None:
        super()._integrate(y, item)
        for key, value in self._prelim_attrs.items():
            self.set_attribute(key, value)
        self._prelim_attrs = None

    def _copy(self) -> "YXmlElement":
        return YXmlElement(self.node_name)

    def clone(self) -> "YXmlElement":
        el = YXmlElement(self.node_name)
        attrs = self.get_attributes()
        for key, value in attrs.items():
            el.set_attribute(key, value)
        el.insert(
            0, [item.clone() if isinstance(item, AbstractType) else item for item in self.to_array()]
        )
        return el

    def to_string(self) -> str:
        """Sorted-attribute XML serialization (reference YXmlElement.js:97-113)."""
        attrs = self.get_attributes()
        attrs_string = " ".join(f'{key}="{attrs[key]}"' for key in sorted(attrs.keys()))
        node_name = self.node_name.lower()
        inner = "".join(type_list_map(self, lambda xml, i, t: xml.to_string()))
        sep = " " + attrs_string if attrs_string else ""
        return f"<{node_name}{sep}>{inner}</{node_name}>"

    def remove_attribute(self, attribute_name: str) -> None:
        if self.doc is not None:
            transact(self.doc, lambda txn: type_map_delete(txn, self, attribute_name))
        else:
            self._prelim_attrs.pop(attribute_name, None)

    def set_attribute(self, attribute_name: str, attribute_value) -> None:
        if self.doc is not None:
            transact(self.doc, lambda txn: type_map_set(txn, self, attribute_name, attribute_value))
        else:
            self._prelim_attrs[attribute_name] = attribute_value

    def get_attribute(self, attribute_name: str):
        return type_map_get(self, attribute_name)

    def get_attributes(self, snapshot=None) -> dict:
        return type_map_get_all(self)

    def _write(self, encoder) -> None:
        encoder.write_type_ref(YXML_ELEMENT_REF_ID)
        encoder.write_key(self.node_name)


class YXmlText(YText):
    @property
    def next_sibling(self):
        n = self._item.next if self._item else None
        return n.content.type if n else None

    @property
    def prev_sibling(self):
        n = self._item.prev if self._item else None
        return n.content.type if n else None

    def _copy(self) -> "YXmlText":
        return YXmlText()

    def clone(self) -> "YXmlText":
        text = YXmlText()
        text.apply_delta(self.to_delta())
        return text

    def to_string(self) -> str:
        """Render delta attributes as nested sorted tags
        (reference YXmlText.js:65-97)."""
        out = []
        for delta in self.to_delta():
            nested_nodes = []
            for node_name in delta.get("attributes", {}):
                attrs = [
                    {"key": key, "value": delta["attributes"][node_name][key]}
                    for key in delta["attributes"][node_name]
                ]
                attrs.sort(key=lambda a: a["key"])
                nested_nodes.append({"nodeName": node_name, "attrs": attrs})
            nested_nodes.sort(key=lambda n: n["nodeName"])
            s = ""
            for node in nested_nodes:
                s += f"<{node['nodeName']}"
                for attr in node["attrs"]:
                    s += f" {attr['key']}=\"{attr['value']}\""
                s += ">"
            s += str(delta["insert"])
            for node in reversed(nested_nodes):
                s += f"</{node['nodeName']}>"
            out.append(s)
        return "".join(out)

    def __str__(self) -> str:
        return self.to_string()

    def to_json(self) -> str:
        return self.to_string()

    def _write(self, encoder) -> None:
        encoder.write_type_ref(YXML_TEXT_REF_ID)


class YXmlHook(YMap):
    def __init__(self, hook_name: str = "UNDEFINED"):
        super().__init__()
        self.hook_name = hook_name

    def _copy(self) -> "YXmlHook":
        return YXmlHook(self.hook_name)

    def clone(self) -> "YXmlHook":
        el = YXmlHook(self.hook_name)

        def _cp(value, key, _t):
            el.set(key, value)

        self.for_each(_cp)
        return el

    def _write(self, encoder) -> None:
        encoder.write_type_ref(YXML_HOOK_REF_ID)
        encoder.write_key(self.hook_name)


def read_yxml_fragment(_decoder) -> YXmlFragment:
    return YXmlFragment()


def read_yxml_element(decoder) -> YXmlElement:
    return YXmlElement(decoder.read_key())


def read_yxml_text(_decoder) -> YXmlText:
    return YXmlText()


def read_yxml_hook(decoder) -> YXmlHook:
    return YXmlHook(decoder.read_key())


type_refs[YXML_FRAGMENT_REF_ID] = read_yxml_fragment
type_refs[YXML_ELEMENT_REF_ID] = read_yxml_element
type_refs[YXML_TEXT_REF_ID] = read_yxml_text
type_refs[YXML_HOOK_REF_ID] = read_yxml_hook
