"""YMap: shared last-writer-wins map (reference src/types/YMap.js)."""

from __future__ import annotations

from ..core import YMAP_REF_ID, transact, type_refs
from .abstract import (
    AbstractType,
    call_type_observers,
    create_map_iterator,
    type_map_delete,
    type_map_get,
    type_map_has,
    type_map_set,
)
from .events import YEvent


class YMapEvent(YEvent):
    def __init__(self, ymap, transaction, subs):
        super().__init__(ymap, transaction)
        self.keys_changed = subs


class YMap(AbstractType):
    def __init__(self, entries=None):
        super().__init__()
        self._prelim_content: dict | None = dict(entries) if entries is not None else {}

    def _integrate(self, y, item) -> None:
        super()._integrate(y, item)
        for key, value in self._prelim_content.items():
            self.set(key, value)
        self._prelim_content = None

    def _copy(self) -> "YMap":
        return YMap()

    def clone(self) -> "YMap":
        m = YMap()

        def _cp(value, key, _t):
            m.set(key, value.clone() if isinstance(value, AbstractType) else value)

        self.for_each(_cp)
        return m

    def _call_observer(self, transaction, parent_subs) -> None:
        call_type_observers(self, transaction, YMapEvent(self, transaction, parent_subs))

    def to_json(self) -> dict:
        result = {}
        for key, item in self._map.items():
            if not item.deleted:
                v = item.content.get_content()[item.length - 1]
                result[key] = v.to_json() if isinstance(v, AbstractType) else v
        return result

    @property
    def size(self) -> int:
        return sum(1 for _ in create_map_iterator(self._map))

    def __len__(self) -> int:
        return self.size

    def keys(self):
        return (v[0] for v in create_map_iterator(self._map))

    def values(self):
        return (v[1].content.get_content()[v[1].length - 1] for v in create_map_iterator(self._map))

    def entries(self):
        return (
            (v[0], v[1].content.get_content()[v[1].length - 1])
            for v in create_map_iterator(self._map)
        )

    def for_each(self, f) -> None:
        for key, item in self._map.items():
            if not item.deleted:
                f(item.content.get_content()[item.length - 1], key, self)

    def __iter__(self):
        return self.entries()

    def delete(self, key: str) -> None:
        if self.doc is not None:
            transact(self.doc, lambda txn: type_map_delete(txn, self, key))
        else:
            self._prelim_content.pop(key, None)

    def set(self, key: str, value):
        if self.doc is not None:
            transact(self.doc, lambda txn: type_map_set(txn, self, key, value))
        else:
            self._prelim_content[key] = value
        return value

    def get(self, key: str):
        return type_map_get(self, key)

    def __getitem__(self, key: str):
        return self.get(key)

    def __setitem__(self, key: str, value):
        self.set(key, value)

    def has(self, key: str) -> bool:
        return type_map_has(self, key)

    def __contains__(self, key: str) -> bool:
        return self.has(key)

    def _write(self, encoder) -> None:
        encoder.write_type_ref(YMAP_REF_ID)


def read_ymap(_decoder) -> YMap:
    return YMap()


type_refs[YMAP_REF_ID] = read_ymap
