"""Shared types (L3): YArray, YMap, YText, YXml*.

Importing this package registers every type's read-constructor in
``yjs_tpu.core.type_refs`` (the wire dispatch table, reference
src/structs/ContentType.js:19-35).
"""

from .abstract import AbstractType  # noqa: F401
from .events import YEvent  # noqa: F401
from .yarray import YArray, YArrayEvent  # noqa: F401
from .ymap import YMap, YMapEvent  # noqa: F401
from .ytext import YText, YTextEvent  # noqa: F401
from .yxml import (  # noqa: F401
    YXmlElement,
    YXmlEvent,
    YXmlFragment,
    YXmlHook,
    YXmlText,
)
