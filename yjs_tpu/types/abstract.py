"""AbstractType: base of all shared types + list/map primitives + the
search-marker index cache (reference src/types/AbstractType.js)."""

from __future__ import annotations

from ..core import (
    ContentAny,
    ContentBinary,
    ContentDoc,
    ContentType,
    Doc,
    Item,
    add_event_handler_listener,
    call_event_handler_listeners,
    create_event_handler,
    get_item_clean_start,
    get_state,
    remove_event_handler_listener,
)
from ..ids import create_id

MAX_SEARCH_MARKER = 80

_global_search_marker_timestamp = 0


def _next_timestamp() -> int:
    global _global_search_marker_timestamp
    _global_search_marker_timestamp += 1
    return _global_search_marker_timestamp


class ArraySearchMarker:
    """Cached (item, index) pair for ~O(1) index→item lookups near recent
    edit positions (reference AbstractType.js:33-44)."""

    __slots__ = ("p", "index", "timestamp")

    def __init__(self, p: Item, index: int):
        p.marker = True
        self.p = p
        self.index = index
        self.timestamp = _next_timestamp()


def _refresh_marker_timestamp(marker: ArraySearchMarker) -> None:
    marker.timestamp = _next_timestamp()


def _overwrite_marker(marker: ArraySearchMarker, p: Item, index: int) -> None:
    marker.p.marker = False
    marker.p = p
    p.marker = True
    marker.index = index
    marker.timestamp = _next_timestamp()


def _mark_position(search_marker: list, p: Item, index: int) -> ArraySearchMarker:
    if len(search_marker) >= MAX_SEARCH_MARKER:
        marker = min(search_marker, key=lambda a: a.timestamp)
        _overwrite_marker(marker, p, index)
        return marker
    pm = ArraySearchMarker(p, index)
    search_marker.append(pm)
    return pm


def find_marker(yarray: "AbstractType", index: int) -> ArraySearchMarker | None:
    """Find (and refresh) the best marker for `index`
    (reference AbstractType.js:97-168)."""
    if yarray._start is None or index == 0 or yarray._search_marker is None:
        return None
    sm = yarray._search_marker
    marker = min(sm, key=lambda a: abs(index - a.index)) if sm else None
    p = yarray._start
    pindex = 0
    if marker is not None:
        p = marker.p
        pindex = marker.index
        _refresh_marker_timestamp(marker)
    # iterate right
    while p.right is not None and pindex < index:
        if not p.deleted and p.countable:
            if index < pindex + p.length:
                break
            pindex += p.length
        p = p.right
    # iterate left if we overshot
    while p.left is not None and pindex > index:
        p = p.left
        if not p.deleted and p.countable:
            pindex -= p.length
    # ensure p cannot be merged with its left neighbour
    while (
        p.left is not None
        and p.left.id.client == p.id.client
        and p.left.id.clock + p.left.length == p.id.clock
    ):
        p = p.left
        if not p.deleted and p.countable:
            pindex -= p.length
    if (
        marker is not None
        and abs(marker.index - pindex) < p.parent._length / MAX_SEARCH_MARKER
    ):
        _overwrite_marker(marker, p, pindex)
        return marker
    return _mark_position(sm, p, pindex)


def update_marker_changes(search_marker: list, index: int, length: int) -> None:
    """Shift markers after an insert (len>0) or delete (len<0); call before
    deleting (reference AbstractType.js:179-210)."""
    for i in range(len(search_marker) - 1, -1, -1):
        m = search_marker[i]
        if length > 0:
            p = m.p
            p.marker = False
            # move marker to the prev undeleted countable position
            while p is not None and (p.deleted or not p.countable):
                p = p.left
                if p is not None and not p.deleted and p.countable:
                    m.index -= p.length
            if p is None or p.marker:
                del search_marker[i]
                continue
            m.p = p
            p.marker = True
        if index < m.index or (length > 0 and index == m.index):
            m.index = max(index, m.index + length)


def get_type_children(t: "AbstractType") -> list:
    s = t._start
    arr = []
    while s is not None:
        arr.append(s)
        s = s.right
    return arr


def call_type_observers(type_, transaction, event) -> None:
    """Fire observers and propagate the event to all ancestors' deep
    observers (reference AbstractType.js:237-249)."""
    changed_type = type_
    changed_parent_types = transaction.changed_parent_types
    while True:
        changed_parent_types.setdefault(type_, []).append(event)
        if type_._item is None:
            break
        type_ = type_._item.parent
    call_event_handler_listeners(changed_type._eh, event, transaction)


class AbstractType:
    def __init__(self):
        self._item: Item | None = None
        self._map: dict[str, Item] = {}
        self._start: Item | None = None
        self.doc: Doc | None = None
        self._length = 0
        self._eh = create_event_handler()
        self._deh = create_event_handler()
        self._search_marker: list | None = None

    @property
    def parent(self):
        return self._item.parent if self._item else None

    def _integrate(self, y: Doc, item: Item | None) -> None:
        self.doc = y
        self._item = item

    def _copy(self) -> "AbstractType":
        raise NotImplementedError

    def clone(self) -> "AbstractType":
        raise NotImplementedError

    def _write(self, encoder) -> None:
        pass

    @property
    def _first(self):
        n = self._start
        while n is not None and n.deleted:
            n = n.right
        return n

    def _call_observer(self, transaction, parent_subs) -> None:
        if not transaction.local and self._search_marker is not None:
            self._search_marker.clear()

    def observe(self, f) -> None:
        add_event_handler_listener(self._eh, f)

    def observe_deep(self, f) -> None:
        add_event_handler_listener(self._deh, f)

    def unobserve(self, f) -> None:
        remove_event_handler_listener(self._eh, f)

    def unobserve_deep(self, f) -> None:
        remove_event_handler_listener(self._deh, f)

    def to_json(self):
        pass


# ---------------------------------------------------------------------------
# List primitives (reference AbstractType.js:407-774)
# ---------------------------------------------------------------------------

def type_list_slice(type_: AbstractType, start: int, end: int) -> list:
    if start < 0:
        start = type_._length + start
    if end < 0:
        end = type_._length + end
    length = end - start
    cs = []
    n = type_._start
    while n is not None and length > 0:
        if n.countable and not n.deleted:
            c = n.content.get_content()
            if len(c) <= start:
                start -= len(c)
            else:
                for i in range(start, len(c)):
                    if length <= 0:
                        break
                    cs.append(c[i])
                    length -= 1
                start = 0
        n = n.right
    return cs


def type_list_to_array(type_: AbstractType) -> list:
    cs = []
    n = type_._start
    while n is not None:
        if n.countable and not n.deleted:
            cs.extend(n.content.get_content())
        n = n.right
    return cs


def type_list_to_array_snapshot(type_: AbstractType, snapshot) -> list:
    from ..utils.snapshot import is_visible

    cs = []
    n = type_._start
    while n is not None:
        if n.countable and is_visible(n, snapshot):
            cs.extend(n.content.get_content())
        n = n.right
    return cs


def type_list_for_each(type_: AbstractType, f) -> None:
    index = 0
    n = type_._start
    while n is not None:
        if n.countable and not n.deleted:
            for c in n.content.get_content():
                f(c, index, type_)
                index += 1
        n = n.right


def type_list_map(type_: AbstractType, f) -> list:
    result = []

    def _collect(c, i, _t):
        result.append(f(c, i, _t))

    type_list_for_each(type_, _collect)
    return result


def type_list_create_iterator(type_: AbstractType):
    n = type_._start
    while n is not None:
        if not n.deleted and n.countable:
            yield from n.content.get_content()
        n = n.right


def type_list_for_each_snapshot(type_: AbstractType, f, snapshot) -> None:
    from ..utils.snapshot import is_visible

    index = 0
    n = type_._start
    while n is not None:
        if n.countable and is_visible(n, snapshot):
            for c in n.content.get_content():
                f(c, index, type_)
                index += 1
        n = n.right


def type_list_get(type_: AbstractType, index: int):
    marker = find_marker(type_, index)
    n = type_._start
    if marker is not None:
        n = marker.p
        index -= marker.index
    while n is not None:
        if not n.deleted and n.countable:
            if index < n.length:
                return n.content.get_content()[index]
            index -= n.length
        n = n.right
    return None


def type_list_insert_generics_after(transaction, parent: AbstractType, reference_item, content: list) -> None:
    """Pack plain values into ContentAny/Binary/Doc/Type runs and integrate
    (reference AbstractType.js:631-680)."""
    left = reference_item
    doc = transaction.doc
    own_client_id = doc.client_id
    store = doc.store
    right = parent._start if reference_item is None else reference_item.right
    json_content: list = []

    def pack_json_content():
        nonlocal left, json_content
        if json_content:
            left = Item(
                create_id(own_client_id, get_state(store, own_client_id)),
                left,
                left.last_id if left else None,
                right,
                right.id if right else None,
                parent,
                None,
                ContentAny(json_content),
            )
            left.integrate(transaction, 0)
            json_content = []

    for c in content:
        if c is None or isinstance(c, (int, float, bool, str, list, dict)):
            json_content.append(c)
        else:
            pack_json_content()
            if isinstance(c, (bytes, bytearray, memoryview)):
                content_obj = ContentBinary(bytes(c))
            elif isinstance(c, Doc):
                content_obj = ContentDoc(c)
            elif isinstance(c, AbstractType):
                content_obj = ContentType(c)
            else:
                raise TypeError("Unexpected content type in insert operation")
            left = Item(
                create_id(own_client_id, get_state(store, own_client_id)),
                left,
                left.last_id if left else None,
                right,
                right.id if right else None,
                parent,
                None,
                content_obj,
            )
            left.integrate(transaction, 0)
    pack_json_content()


def type_list_insert_generics(transaction, parent: AbstractType, index: int, content: list) -> None:
    if index == 0:
        if parent._search_marker is not None:
            update_marker_changes(parent._search_marker, index, len(content))
        return type_list_insert_generics_after(transaction, parent, None, content)
    start_index = index
    marker = find_marker(parent, index)
    n = parent._start
    if marker is not None:
        n = marker.p
        index -= marker.index
        if index == 0:
            # step one item left so the insertion-point scan below works
            n = n.prev
            index += n.length if (n is not None and n.countable and not n.deleted) else 0
    while n is not None:
        if not n.deleted and n.countable:
            if index <= n.length:
                if index < n.length:
                    # split for an in-between insert
                    get_item_clean_start(
                        transaction, create_id(n.id.client, n.id.clock + index)
                    )
                break
            index -= n.length
        n = n.right
    if parent._search_marker is not None:
        update_marker_changes(parent._search_marker, start_index, len(content))
    return type_list_insert_generics_after(transaction, parent, n, content)


def type_list_delete(transaction, parent: AbstractType, index: int, length: int) -> None:
    if length == 0:
        return
    start_index = index
    start_length = length
    marker = find_marker(parent, index)
    n = parent._start
    if marker is not None:
        n = marker.p
        index -= marker.index
    # find the first item to delete
    while n is not None and index > 0:
        if not n.deleted and n.countable:
            if index < n.length:
                get_item_clean_start(transaction, create_id(n.id.client, n.id.clock + index))
            index -= n.length
        n = n.right
    # delete until done
    while length > 0 and n is not None:
        if not n.deleted:
            if length < n.length:
                get_item_clean_start(transaction, create_id(n.id.client, n.id.clock + length))
            n.delete(transaction)
            length -= n.length
        n = n.right
    if length > 0:
        raise IndexError("array length exceeded")
    if parent._search_marker is not None:
        update_marker_changes(parent._search_marker, start_index, -start_length + length)


# ---------------------------------------------------------------------------
# Map primitives (reference AbstractType.js:784-903)
# ---------------------------------------------------------------------------

def type_map_delete(transaction, parent: AbstractType, key: str) -> None:
    c = parent._map.get(key)
    if c is not None:
        c.delete(transaction)


def type_map_set(transaction, parent: AbstractType, key: str, value) -> None:
    left = parent._map.get(key)
    doc = transaction.doc
    own_client_id = doc.client_id
    if value is None or isinstance(value, (int, float, bool, str, list, dict)):
        content = ContentAny([value])
    elif isinstance(value, (bytes, bytearray, memoryview)):
        content = ContentBinary(bytes(value))
    elif isinstance(value, Doc):
        content = ContentDoc(value)
    elif isinstance(value, AbstractType):
        content = ContentType(value)
    else:
        raise TypeError("Unexpected content type")
    Item(
        create_id(own_client_id, get_state(doc.store, own_client_id)),
        left,
        left.last_id if left else None,
        None,
        None,
        parent,
        key,
        content,
    ).integrate(transaction, 0)


def type_map_get(parent: AbstractType, key: str):
    val = parent._map.get(key)
    return val.content.get_content()[val.length - 1] if val is not None and not val.deleted else None


def type_map_get_all(parent: AbstractType) -> dict:
    res = {}
    for key, value in parent._map.items():
        if not value.deleted:
            res[key] = value.content.get_content()[value.length - 1]
    return res


def type_map_has(parent: AbstractType, key: str) -> bool:
    val = parent._map.get(key)
    return val is not None and not val.deleted


def type_map_get_snapshot(parent: AbstractType, key: str, snapshot):
    from ..utils.snapshot import is_visible

    v = parent._map.get(key)
    while v is not None and (
        v.id.client not in snapshot.sv or v.id.clock >= snapshot.sv.get(v.id.client, 0)
    ):
        v = v.left
    return v.content.get_content()[v.length - 1] if v is not None and is_visible(v, snapshot) else None


def create_map_iterator(map_: dict):
    return ((key, item) for key, item in map_.items() if not item.deleted)
