"""YText: rich text over ContentString/Embed/Format runs
(reference src/types/YText.js).

Indices are UTF-16 code units (JS string semantics); see lib0/u16.py.
User-facing strings (toString / deltas) are ordinary Python strings; the
internal representation is u16 form.
"""

from __future__ import annotations

from ..core import (
    GC,
    ContentEmbed,
    ContentFormat,
    ContentString,
    Item,
    YTEXT_REF_ID,
    get_item_clean_start,
    get_state,
    iterate_deleted_structs,
    iterate_structs,
    transact,
    type_refs,
)
from ..ids import create_id
from ..lib0.u16 import from_u16, to_u16
from .abstract import (
    AbstractType,
    call_type_observers,
    find_marker,
    type_map_delete,
    type_map_get,
    type_map_get_all,
    type_map_set,
    update_marker_changes,
)
from .events import YEvent


def _js_falsy(v) -> bool:
    return (
        v is None
        or v is False
        or (isinstance(v, (int, float)) and (v == 0 or v != v))
        or (isinstance(v, str) and v == "")
    )


def _or_null(v):
    """JS `v || null`."""
    return None if _js_falsy(v) else v


def _js_strict_eq(a, b) -> bool:
    """JS `===`: identity for objects/arrays, value equality for primitives
    (bool and number are distinct JS types)."""
    if isinstance(a, (dict, list)) or isinstance(b, (dict, list)):
        return a is b
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return a == b


def equal_attrs(a, b) -> bool:
    """JS `===` or flat object equality (reference YText.js:41)."""
    if a is b:
        return True
    if isinstance(a, dict) and isinstance(b, dict):
        return len(a) == len(b) and all(k in b and b[k] == v for k, v in a.items())
    if isinstance(a, list) and isinstance(b, list):
        return a == b
    if isinstance(a, (dict, list)) or isinstance(b, (dict, list)):
        return False
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if a is None or b is None:
        return a is b
    return a == b


class ItemTextListPosition:
    """Walker through a YText's item list tracking index + active formatting
    attributes (reference YText.js:43-80)."""

    __slots__ = ("left", "right", "index", "current_attributes")

    def __init__(self, left, right, index: int, current_attributes: dict):
        self.left = left
        self.right = right
        self.index = index
        self.current_attributes = current_attributes

    def forward(self) -> None:
        if self.right is None:
            raise RuntimeError("position out of range")
        content = self.right.content
        if type(content) in (ContentEmbed, ContentString):
            if not self.right.deleted:
                self.index += self.right.length
        elif type(content) is ContentFormat:
            if not self.right.deleted:
                update_current_attributes(self.current_attributes, content)
        self.left = self.right
        self.right = self.right.right


def find_next_position(transaction, pos: ItemTextListPosition, count: int) -> ItemTextListPosition:
    while pos.right is not None and count > 0:
        content = pos.right.content
        tc = type(content)
        if tc in (ContentEmbed, ContentString):
            if not pos.right.deleted:
                if count < pos.right.length:
                    # split right
                    get_item_clean_start(
                        transaction, create_id(pos.right.id.client, pos.right.id.clock + count)
                    )
                pos.index += pos.right.length
                count -= pos.right.length
        elif tc is ContentFormat:
            if not pos.right.deleted:
                update_current_attributes(pos.current_attributes, content)
        pos.left = pos.right
        pos.right = pos.right.right
    return pos


def find_position(transaction, parent, index: int) -> ItemTextListPosition:
    current_attributes: dict = {}
    marker = find_marker(parent, index)
    if marker is not None:
        pos = ItemTextListPosition(marker.p.left, marker.p, marker.index, current_attributes)
        return find_next_position(transaction, pos, index - marker.index)
    pos = ItemTextListPosition(None, parent._start, 0, current_attributes)
    return find_next_position(transaction, pos, index)


def insert_negated_attributes(transaction, parent, curr_pos: ItemTextListPosition, negated_attributes: dict) -> None:
    """Close formatting ranges after an insert (reference YText.js:150-173)."""
    while curr_pos.right is not None and (
        curr_pos.right.deleted
        or (
            type(curr_pos.right.content) is ContentFormat
            and equal_attrs(
                negated_attributes.get(curr_pos.right.content.key),
                curr_pos.right.content.value,
            )
        )
    ):
        if not curr_pos.right.deleted:
            negated_attributes.pop(curr_pos.right.content.key, None)
        curr_pos.forward()
    doc = transaction.doc
    own_client_id = doc.client_id
    left = curr_pos.left
    right = curr_pos.right
    for key, val in negated_attributes.items():
        left = Item(
            create_id(own_client_id, get_state(doc.store, own_client_id)),
            left,
            left.last_id if left else None,
            right,
            right.id if right else None,
            parent,
            None,
            ContentFormat(key, val),
        )
        left.integrate(transaction, 0)


def update_current_attributes(current_attributes: dict, fmt: ContentFormat) -> None:
    if fmt.value is None:
        current_attributes.pop(fmt.key, None)
    else:
        current_attributes[fmt.key] = fmt.value


def minimize_attribute_changes(curr_pos: ItemTextListPosition, attributes: dict) -> None:
    """Skip over formats that already match (reference YText.js:198-210)."""
    while True:
        if curr_pos.right is None:
            break
        if curr_pos.right.deleted or (
            type(curr_pos.right.content) is ContentFormat
            and equal_attrs(
                _or_null(attributes.get(curr_pos.right.content.key)),
                curr_pos.right.content.value,
            )
        ):
            pass
        else:
            break
        curr_pos.forward()


def insert_attributes(transaction, parent, curr_pos: ItemTextListPosition, attributes: dict) -> dict:
    doc = transaction.doc
    own_client_id = doc.client_id
    negated_attributes: dict = {}
    for key, val in attributes.items():
        current_val = _or_null(curr_pos.current_attributes.get(key))
        if not equal_attrs(current_val, val):
            negated_attributes[key] = current_val
            left = curr_pos.left
            right = curr_pos.right
            curr_pos.right = Item(
                create_id(own_client_id, get_state(doc.store, own_client_id)),
                left,
                left.last_id if left else None,
                right,
                right.id if right else None,
                parent,
                None,
                ContentFormat(key, val),
            )
            curr_pos.right.integrate(transaction, 0)
            curr_pos.forward()
    return negated_attributes


def insert_text(transaction, parent, curr_pos: ItemTextListPosition, text, attributes: dict) -> None:
    """(reference YText.js:252-274). ``text`` is a u16-form str or an embed
    dict."""
    for key in curr_pos.current_attributes:
        if key not in attributes:
            attributes[key] = None
    doc = transaction.doc
    own_client_id = doc.client_id
    minimize_attribute_changes(curr_pos, attributes)
    negated_attributes = insert_attributes(transaction, parent, curr_pos, attributes)
    content = ContentString(text) if isinstance(text, str) else ContentEmbed(text)
    left = curr_pos.left
    right = curr_pos.right
    index = curr_pos.index
    if parent._search_marker is not None:
        update_marker_changes(parent._search_marker, curr_pos.index, content.get_length())
    right = Item(
        create_id(own_client_id, get_state(doc.store, own_client_id)),
        left,
        left.last_id if left else None,
        right,
        right.id if right else None,
        parent,
        None,
        content,
    )
    right.integrate(transaction, 0)
    curr_pos.right = right
    curr_pos.index = index
    curr_pos.forward()
    insert_negated_attributes(transaction, parent, curr_pos, negated_attributes)


def format_text(transaction, parent, curr_pos: ItemTextListPosition, length: int, attributes: dict) -> None:
    """(reference YText.js:286-333)."""
    doc = transaction.doc
    own_client_id = doc.client_id
    minimize_attribute_changes(curr_pos, attributes)
    negated_attributes = insert_attributes(transaction, parent, curr_pos, attributes)
    while length > 0 and curr_pos.right is not None:
        if not curr_pos.right.deleted:
            content = curr_pos.right.content
            tc = type(content)
            if tc is ContentFormat:
                if content.key in attributes:
                    attr = attributes[content.key]
                    if equal_attrs(attr, content.value):
                        negated_attributes.pop(content.key, None)
                    else:
                        negated_attributes[content.key] = content.value
                    curr_pos.right.delete(transaction)
            elif tc in (ContentEmbed, ContentString):
                if length < curr_pos.right.length:
                    get_item_clean_start(
                        transaction,
                        create_id(curr_pos.right.id.client, curr_pos.right.id.clock + length),
                    )
                length -= curr_pos.right.length
        curr_pos.forward()
    # Quill assumes the editor ends with a newline; pad if formatting past end
    if length > 0:
        newlines = "\n" * length
        curr_pos.right = Item(
            create_id(own_client_id, get_state(doc.store, own_client_id)),
            curr_pos.left,
            curr_pos.left.last_id if curr_pos.left else None,
            curr_pos.right,
            curr_pos.right.id if curr_pos.right else None,
            parent,
            None,
            ContentString(newlines),
        )
        curr_pos.right.integrate(transaction, 0)
        curr_pos.forward()
    insert_negated_attributes(transaction, parent, curr_pos, negated_attributes)


def cleanup_formatting_gap(transaction, start, end, start_attributes: dict, end_attributes: dict) -> int:
    """Delete redundant format markers inside a deleted gap
    (reference YText.js:348-374)."""
    while end is not None and type(end.content) is not ContentString and type(end.content) is not ContentEmbed:
        if not end.deleted and type(end.content) is ContentFormat:
            update_current_attributes(end_attributes, end.content)
        end = end.right
    cleanups = 0
    while start is not end:
        if not start.deleted:
            content = start.content
            if type(content) is ContentFormat:
                # the reference compares with JS === here (identity for
                # objects), not deep equality (YText.js:362)
                if not _js_strict_eq(
                    _or_null(end_attributes.get(content.key)), content.value
                ) or _js_strict_eq(
                    _or_null(start_attributes.get(content.key)), content.value
                ):
                    start.delete(transaction)
                    cleanups += 1
        start = start.right
    return cleanups


def cleanup_contextless_formatting_gap(transaction, item) -> None:
    """(reference YText.js:380-398)."""
    while item is not None and item.right is not None and (
        item.right.deleted
        or (
            type(item.right.content) is not ContentString
            and type(item.right.content) is not ContentEmbed
        )
    ):
        item = item.right
    attrs = set()
    while item is not None and (
        item.deleted
        or (type(item.content) is not ContentString and type(item.content) is not ContentEmbed)
    ):
        if not item.deleted and type(item.content) is ContentFormat:
            key = item.content.key
            if key in attrs:
                item.delete(transaction)
            else:
                attrs.add(key)
        item = item.left


def cleanup_ytext_formatting(type_: "YText") -> int:
    """Full two-pass formatting cleanup (reference YText.js:412-437)."""
    res = 0

    def _run(transaction):
        nonlocal res
        start = type_._start
        end = type_._start
        start_attributes: dict = {}
        current_attributes = dict(start_attributes)
        while end is not None:
            if end.deleted is False:
                tc = type(end.content)
                if tc is ContentFormat:
                    update_current_attributes(current_attributes, end.content)
                elif tc in (ContentEmbed, ContentString):
                    res += cleanup_formatting_gap(
                        transaction, start, end, start_attributes, current_attributes
                    )
                    start_attributes = dict(current_attributes)
                    start = end
            end = end.right

    transact(type_.doc, _run)
    return res


def delete_text(transaction, curr_pos: ItemTextListPosition, length: int) -> ItemTextListPosition:
    """(reference YText.js:448-475)."""
    start_length = length
    start_attrs = dict(curr_pos.current_attributes)
    start = curr_pos.right
    while length > 0 and curr_pos.right is not None:
        if curr_pos.right.deleted is False:
            tc = type(curr_pos.right.content)
            if tc in (ContentEmbed, ContentString):
                if length < curr_pos.right.length:
                    get_item_clean_start(
                        transaction,
                        create_id(curr_pos.right.id.client, curr_pos.right.id.clock + length),
                    )
                length -= curr_pos.right.length
                curr_pos.right.delete(transaction)
        curr_pos.forward()
    if start is not None:
        cleanup_formatting_gap(
            transaction, start, curr_pos.right, start_attrs, dict(curr_pos.current_attributes)
        )
    parent = (curr_pos.left if curr_pos.left is not None else curr_pos.right).parent
    if parent._search_marker is not None:
        update_marker_changes(parent._search_marker, curr_pos.index, -start_length + length)
    return curr_pos


class YTextEvent(YEvent):
    """(reference YText.js:515-733)."""

    def __init__(self, ytext, transaction, subs):
        super().__init__(ytext, transaction)
        self._delta = None
        self.child_list_changed = False
        self.keys_changed = set()
        for sub in subs:
            if sub is None:
                self.child_list_changed = True
            else:
                self.keys_changed.add(sub)

    @property
    def delta(self) -> list:
        if self._delta is None:
            y = self.target.doc
            self._delta = []

            def _compute(transaction):
                delta = self._delta
                current_attributes: dict = {}
                old_attributes: dict = {}
                item = self.target._start
                state = {"action": None, "insert": "", "retain": 0, "delete_len": 0}
                attributes: dict = {}

                def add_op():
                    action = state["action"]
                    if action is not None:
                        if action == "delete":
                            op = {"delete": state["delete_len"]}
                            state["delete_len"] = 0
                        elif action == "insert":
                            ins = state["insert"]
                            op = {"insert": from_u16(ins) if isinstance(ins, str) else ins}
                            if current_attributes:
                                op["attributes"] = {
                                    key: value
                                    for key, value in current_attributes.items()
                                    if value is not None
                                }
                            state["insert"] = ""
                        else:  # retain
                            op = {"retain": state["retain"]}
                            if attributes:
                                op["attributes"] = dict(attributes)
                            state["retain"] = 0
                        delta.append(op)
                        state["action"] = None

                while item is not None:
                    tc = type(item.content)
                    if tc is ContentEmbed:
                        if self.adds(item):
                            if not self.deletes(item):
                                add_op()
                                state["action"] = "insert"
                                state["insert"] = item.content.embed
                                add_op()
                        elif self.deletes(item):
                            if state["action"] != "delete":
                                add_op()
                                state["action"] = "delete"
                            state["delete_len"] += 1
                        elif not item.deleted:
                            if state["action"] != "retain":
                                add_op()
                                state["action"] = "retain"
                            state["retain"] += 1
                    elif tc is ContentString:
                        if self.adds(item):
                            if not self.deletes(item):
                                if state["action"] != "insert":
                                    add_op()
                                    state["action"] = "insert"
                                state["insert"] += item.content.str
                        elif self.deletes(item):
                            if state["action"] != "delete":
                                add_op()
                                state["action"] = "delete"
                            state["delete_len"] += item.length
                        elif not item.deleted:
                            if state["action"] != "retain":
                                add_op()
                                state["action"] = "retain"
                            state["retain"] += item.length
                    elif tc is ContentFormat:
                        key = item.content.key
                        value = item.content.value
                        if self.adds(item):
                            if not self.deletes(item):
                                cur_val = _or_null(current_attributes.get(key))
                                if not equal_attrs(cur_val, value):
                                    if state["action"] == "retain":
                                        add_op()
                                    if equal_attrs(value, _or_null(old_attributes.get(key))):
                                        attributes.pop(key, None)
                                    else:
                                        attributes[key] = value
                                else:
                                    item.delete(transaction)
                        elif self.deletes(item):
                            old_attributes[key] = value
                            cur_val = _or_null(current_attributes.get(key))
                            if not equal_attrs(cur_val, value):
                                if state["action"] == "retain":
                                    add_op()
                                attributes[key] = cur_val
                        elif not item.deleted:
                            old_attributes[key] = value
                            if key in attributes:
                                attr = attributes[key]
                                if not equal_attrs(attr, value):
                                    if state["action"] == "retain":
                                        add_op()
                                    if value is None:
                                        attributes[key] = value
                                    else:
                                        attributes.pop(key, None)
                                else:
                                    item.delete(transaction)
                        if not item.deleted:
                            if state["action"] == "insert":
                                add_op()
                            update_current_attributes(current_attributes, item.content)
                    item = item.right
                add_op()
                while delta:
                    last_op = delta[-1]
                    if "retain" in last_op and "attributes" not in last_op:
                        delta.pop()
                    else:
                        break

            transact(y, _compute)
        return self._delta


class YText(AbstractType):
    def __init__(self, string: str | None = None):
        super().__init__()
        self._pending: list | None = (
            [lambda: self.insert(0, string)] if string is not None else []
        )
        self._search_marker = []

    @property
    def length(self) -> int:
        return self._length

    def __len__(self) -> int:
        return self._length

    def _integrate(self, y, item) -> None:
        super()._integrate(y, item)
        try:
            for f in self._pending:
                f()
        except Exception as e:  # reference logs and continues (YText.js:776-780)
            import sys

            print(e, file=sys.stderr)
        self._pending = None

    def _copy(self) -> "YText":
        return YText()

    def clone(self) -> "YText":
        text = YText()
        text.apply_delta(self.to_delta())
        return text

    def _call_observer(self, transaction, parent_subs) -> None:
        super()._call_observer(transaction, parent_subs)
        event = YTextEvent(self, transaction, parent_subs)
        doc = transaction.doc
        if not transaction.local:
            # remote change: clean up potential formatting duplicates
            # (reference YText.js:803-856)
            found_formatting_item = False
            for client, after_clock in transaction.after_state.items():
                clock = transaction.before_state.get(client, 0)
                if after_clock == clock:
                    continue

                def _check(item):
                    nonlocal found_formatting_item
                    if (
                        not item.deleted
                        and type(item) is Item
                        and type(item.content) is ContentFormat
                    ):
                        found_formatting_item = True

                iterate_structs(
                    transaction, doc.store.clients[client], clock, after_clock, _check
                )
                if found_formatting_item:
                    break
            if not found_formatting_item:
                def _check_deleted(item):
                    nonlocal found_formatting_item
                    if type(item) is GC or found_formatting_item:
                        return
                    if item.parent is self and type(item.content) is ContentFormat:
                        found_formatting_item = True

                iterate_deleted_structs(transaction, transaction.delete_set, _check_deleted)

            def _cleanup(t):
                if found_formatting_item:
                    cleanup_ytext_formatting(self)
                else:
                    def _gap(item):
                        if type(item) is GC:
                            return
                        if item.parent is self:
                            cleanup_contextless_formatting_gap(t, item)

                    iterate_deleted_structs(t, t.delete_set, _gap)

            transact(doc, _cleanup)
        call_type_observers(self, transaction, event)

    def to_string(self) -> str:
        parts = []
        n = self._start
        while n is not None:
            if not n.deleted and n.countable and type(n.content) is ContentString:
                parts.append(n.content.str)
            n = n.right
        return from_u16("".join(parts))

    def __str__(self) -> str:
        return self.to_string()

    def to_json(self) -> str:
        return self.to_string()

    def apply_delta(self, delta: list, sanitize: bool = True) -> None:
        """(reference YText.js:898-924)."""
        if self.doc is not None:
            def _apply(transaction):
                curr_pos = ItemTextListPosition(None, self._start, 0, {})
                for i, op in enumerate(delta):
                    if "insert" in op:
                        ins = op["insert"]
                        if (
                            not sanitize
                            and isinstance(ins, str)
                            and i == len(delta) - 1
                            and curr_pos.right is None
                            and ins.endswith("\n")
                        ):
                            ins = ins[:-1]
                        if not isinstance(ins, str) or len(ins) > 0:
                            if isinstance(ins, str):
                                ins = to_u16(ins)
                            insert_text(
                                transaction, self, curr_pos, ins, dict(op.get("attributes", {}))
                            )
                    elif "retain" in op:
                        format_text(
                            transaction,
                            self,
                            curr_pos,
                            op["retain"],
                            dict(op.get("attributes", {})),
                        )
                    elif "delete" in op:
                        delete_text(transaction, curr_pos, op["delete"])

            transact(self.doc, _apply)
        else:
            self._pending.append(lambda: self.apply_delta(delta, sanitize))

    def to_delta(self, snapshot=None, prev_snapshot=None, compute_ychange=None) -> list:
        """Delta representation, optionally as a two-snapshot diff with
        ychange attribution (reference YText.js:936-1030)."""
        from ..utils.snapshot import is_visible, split_snapshot_affected_structs

        ops: list = []
        current_attributes: dict = {}
        doc = self.doc
        parts: list[str] = []

        def pack_str():
            if parts:
                s = from_u16("".join(parts))
                op = {"insert": s}
                if current_attributes:
                    op["attributes"] = dict(current_attributes)
                ops.append(op)
                parts.clear()

        def _compute(transaction):
            nonlocal current_attributes
            if snapshot is not None:
                split_snapshot_affected_structs(transaction, snapshot)
            if prev_snapshot is not None:
                split_snapshot_affected_structs(transaction, prev_snapshot)
            n = self._start
            while n is not None:
                if is_visible(n, snapshot) or (
                    prev_snapshot is not None and is_visible(n, prev_snapshot)
                ):
                    tc = type(n.content)
                    if tc is ContentString:
                        cur = current_attributes.get("ychange")
                        if snapshot is not None and not is_visible(n, snapshot):
                            if (
                                cur is None
                                or cur.get("user") != n.id.client
                                or cur.get("state") != "removed"
                            ):
                                pack_str()
                                current_attributes["ychange"] = (
                                    compute_ychange("removed", n.id)
                                    if compute_ychange
                                    else {"type": "removed"}
                                )
                        elif prev_snapshot is not None and not is_visible(n, prev_snapshot):
                            if (
                                cur is None
                                or cur.get("user") != n.id.client
                                or cur.get("state") != "added"
                            ):
                                pack_str()
                                current_attributes["ychange"] = (
                                    compute_ychange("added", n.id)
                                    if compute_ychange
                                    else {"type": "added"}
                                )
                        elif cur is not None:
                            pack_str()
                            current_attributes.pop("ychange", None)
                        parts.append(n.content.str)
                    elif tc is ContentEmbed:
                        pack_str()
                        op = {"insert": n.content.embed}
                        if current_attributes:
                            op["attributes"] = dict(current_attributes)
                        ops.append(op)
                    elif tc is ContentFormat:
                        if is_visible(n, snapshot):
                            pack_str()
                            update_current_attributes(current_attributes, n.content)
                n = n.right
            pack_str()

        transact(doc, _compute, split_snapshot_affected_structs)
        return ops

    def insert(self, index: int, text: str, attributes: dict | None = None) -> None:
        if len(text) <= 0:
            return
        y = self.doc
        if y is not None:
            u16text = to_u16(text)

            def _ins(transaction):
                pos = find_position(transaction, self, index)
                attrs = attributes
                if attrs is None:
                    attrs = dict(pos.current_attributes)
                insert_text(transaction, self, pos, u16text, dict(attrs))

            transact(y, _ins)
        else:
            self._pending.append(lambda: self.insert(index, text, attributes))

    def insert_embed(self, index: int, embed: dict, attributes: dict | None = None) -> None:
        if not isinstance(embed, dict):
            raise TypeError("Embed must be a dict")
        y = self.doc
        if y is not None:
            def _ins(transaction):
                pos = find_position(transaction, self, index)
                insert_text(transaction, self, pos, embed, dict(attributes or {}))

            transact(y, _ins)
        else:
            self._pending.append(lambda: self.insert_embed(index, embed, attributes))

    def delete(self, index: int, length: int) -> None:
        if length == 0:
            return
        y = self.doc
        if y is not None:
            transact(
                y, lambda txn: delete_text(txn, find_position(txn, self, index), length)
            )
        else:
            self._pending.append(lambda: self.delete(index, length))

    def format(self, index: int, length: int, attributes: dict) -> None:
        if length == 0:
            return
        y = self.doc
        if y is not None:
            def _fmt(transaction):
                pos = find_position(transaction, self, index)
                if pos.right is None:
                    return
                format_text(transaction, self, pos, length, dict(attributes))

            transact(y, _fmt)
        else:
            self._pending.append(lambda: self.format(index, length, attributes))

    def remove_attribute(self, attribute_name: str) -> None:
        if self.doc is not None:
            transact(self.doc, lambda txn: type_map_delete(txn, self, attribute_name))
        else:
            self._pending.append(lambda: self.remove_attribute(attribute_name))

    def set_attribute(self, attribute_name: str, attribute_value) -> None:
        if self.doc is not None:
            transact(
                self.doc, lambda txn: type_map_set(txn, self, attribute_name, attribute_value)
            )
        else:
            self._pending.append(lambda: self.set_attribute(attribute_name, attribute_value))

    def get_attribute(self, attribute_name: str):
        return type_map_get(self, attribute_name)

    def get_attributes(self, snapshot=None) -> dict:
        return type_map_get_all(self)

    def _write(self, encoder) -> None:
        encoder.write_type_ref(YTEXT_REF_ID)


def read_ytext(_decoder) -> YText:
    return YText()


type_refs[YTEXT_REF_ID] = read_ytext
