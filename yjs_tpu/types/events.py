"""YEvent: change description delivered to observers
(reference src/utils/YEvent.js:13-228)."""

from __future__ import annotations

from ..core import is_deleted
from ..lib0.encoding import UNDEFINED


class YEvent:
    def __init__(self, target, transaction):
        self.target = target
        self.current_target = target
        self.transaction = transaction
        self._changes = None

    @property
    def path(self):
        return get_path_to(self.current_target, self.target)

    def deletes(self, struct) -> bool:
        """True if `struct` was deleted by this event's transaction (also
        when added-then-deleted)."""
        return is_deleted(self.transaction.delete_set, struct.id)

    def adds(self, struct) -> bool:
        return struct.id.clock >= self.transaction.before_state.get(struct.id.client, 0)

    @property
    def changes(self) -> dict:
        """Lazily computed {added, deleted, delta, keys}
        (reference YEvent.js:85-187)."""
        changes = self._changes
        if changes is None:
            target = self.target
            added: set = set()
            deleted: set = set()
            delta: list = []
            keys: dict = {}
            changes = {"added": added, "deleted": deleted, "delta": delta, "keys": keys}
            changed = self.transaction.changed.get(target, set())
            if None in changed:
                last_op = None

                def pack_op():
                    if last_op is not None:
                        delta.append(last_op)

                item = target._start
                while item is not None:
                    if item.deleted:
                        if self.deletes(item) and not self.adds(item):
                            if last_op is None or "delete" not in last_op:
                                pack_op()
                                last_op = {"delete": 0}
                            last_op["delete"] += item.length
                            deleted.add(item)
                    else:
                        if self.adds(item):
                            if last_op is None or "insert" not in last_op:
                                pack_op()
                                last_op = {"insert": []}
                            last_op["insert"] = last_op["insert"] + item.content.get_content()
                            added.add(item)
                        else:
                            if last_op is None or "retain" not in last_op:
                                pack_op()
                                last_op = {"retain": 0}
                            last_op["retain"] += item.length
                    item = item.right
                if last_op is not None and "retain" not in last_op:
                    pack_op()
            for key in changed:
                if key is not None:
                    item = target._map.get(key)
                    if self.adds(item):
                        prev = item.left
                        while prev is not None and self.adds(prev):
                            prev = prev.left
                        if self.deletes(item):
                            if prev is not None and self.deletes(prev):
                                action = "delete"
                                old_value = prev.content.get_content()[-1]
                            else:
                                continue
                        else:
                            if prev is not None and self.deletes(prev):
                                action = "update"
                                old_value = prev.content.get_content()[-1]
                            else:
                                action = "add"
                                old_value = UNDEFINED
                    else:
                        if self.deletes(item):
                            action = "delete"
                            old_value = item.content.get_content()[-1]
                        else:
                            continue
                    keys[key] = {"action": action, "oldValue": old_value}
            self._changes = changes
        return changes


def get_path_to(parent, child) -> list:
    """Path of keys/indices from `parent` down to `child`
    (reference YEvent.js:207-228)."""
    path: list = []
    while child._item is not None and child is not parent:
        if child._item.parent_sub is not None:
            path.insert(0, child._item.parent_sub)
        else:
            i = 0
            c = child._item.parent._start
            while c is not child._item and c is not None:
                if not c.deleted:
                    i += 1
                c = c.right
            path.insert(0, i)
        child = child._item.parent
    return path
