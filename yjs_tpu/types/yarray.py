"""YArray: shared list (reference src/types/YArray.js)."""

from __future__ import annotations

from ..core import YARRAY_REF_ID, transact, type_refs
from .abstract import (
    AbstractType,
    call_type_observers,
    type_list_create_iterator,
    type_list_delete,
    type_list_for_each,
    type_list_get,
    type_list_insert_generics,
    type_list_map,
    type_list_slice,
    type_list_to_array,
)
from .events import YEvent


class YArrayEvent(YEvent):
    pass


class YArray(AbstractType):
    def __init__(self):
        super().__init__()
        self._prelim_content: list | None = []
        self._search_marker = []

    @staticmethod
    def from_(items: list) -> "YArray":
        a = YArray()
        a.push(items)
        return a

    def _integrate(self, y, item) -> None:
        super()._integrate(y, item)
        self.insert(0, self._prelim_content)
        self._prelim_content = None

    def _copy(self) -> "YArray":
        return YArray()

    def clone(self) -> "YArray":
        arr = YArray()
        arr.insert(
            0,
            [el.clone() if isinstance(el, AbstractType) else el for el in self.to_array()],
        )
        return arr

    @property
    def length(self) -> int:
        return self._length if self._prelim_content is None else len(self._prelim_content)

    def __len__(self) -> int:
        return self.length

    def _call_observer(self, transaction, parent_subs) -> None:
        super()._call_observer(transaction, parent_subs)
        call_type_observers(self, transaction, YArrayEvent(self, transaction))

    def insert(self, index: int, content: list) -> None:
        if self.doc is not None:
            transact(self.doc, lambda txn: type_list_insert_generics(txn, self, index, content))
        else:
            self._prelim_content[index:index] = content

    def push(self, content: list) -> None:
        self.insert(self.length, content)

    def unshift(self, content: list) -> None:
        self.insert(0, content)

    def delete(self, index: int, length: int = 1) -> None:
        if self.doc is not None:
            transact(self.doc, lambda txn: type_list_delete(txn, self, index, length))
        else:
            del self._prelim_content[index:index + length]

    def get(self, index: int):
        return type_list_get(self, index)

    def __getitem__(self, index: int):
        return self.get(index)

    def to_array(self) -> list:
        return type_list_to_array(self)

    def slice(self, start: int = 0, end: int | None = None) -> list:
        return type_list_slice(self, start, end if end is not None else self.length)

    def to_json(self) -> list:
        return self.map(lambda c, i, t: c.to_json() if isinstance(c, AbstractType) else c)

    def map(self, f) -> list:
        return type_list_map(self, f)

    def for_each(self, f) -> None:
        type_list_for_each(self, f)

    def __iter__(self):
        return type_list_create_iterator(self)

    def _write(self, encoder) -> None:
        encoder.write_type_ref(YARRAY_REF_ID)


def read_yarray(_decoder) -> YArray:
    return YArray()


type_refs[YARRAY_REF_ID] = read_yarray
