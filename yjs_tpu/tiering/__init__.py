"""Heat-driven doc lifecycle tiering for the provider fleet (ISSUE 7).

Two pieces:

- :mod:`heat` — :class:`HeatTracker`: exponentially-decayed per-doc
  touch counters fed from the provider's receive/session/``doc_id``
  seams;
- :mod:`manager` — :class:`TierManager` + :class:`TierConfig`: the
  hot (device slot) / warm (detached host columns) / cold (WAL tier
  record) lifecycle with demand promotion, coldest-first auto-eviction
  behind ``doc_id`` (opt-in: ``YTPU_TIER_ENABLED``), tombstone/GC
  compaction for long-lived hot docs, and crash-consistent ``KIND_TIER``
  journaling so recovery lands every doc in exactly one tier.

Metrics land in the ``ytpu_tier_*`` families; knobs are the
``YTPU_TIER_*`` env vars documented in README "Tiered lifecycle".
"""

from .heat import HeatTracker
from .manager import COLD, HOT, WARM, TierConfig, TierManager

__all__ = [
    "COLD",
    "HOT",
    "WARM",
    "HeatTracker",
    "TierConfig",
    "TierManager",
]
