"""Per-doc heat: exponentially-decayed touch counters (ISSUE 7).

Every provider seam that means "someone cares about this doc right now"
— ``doc_id`` resolution, update receive, session admission — feeds a
weighted touch.  Heat decays continuously with a configurable half-life,
so "touched 50 times an hour ago" loses to "touched twice just now"
once the half-life has passed.  The score is the tiering policy's only
input: demotion victims are the coldest eligible docs, the fleet
rebalancer sheds the coldest rooms first, and a migrated or recovered
doc carries its heat along so it lands in the tier it deserves.

The tracker is pure host-side bookkeeping — a dict of
``guid -> (heat, last_touch_ts)`` — and decays lazily at read time
(``0.5 ** (dt / half_life)``), so an idle fleet pays nothing.  The
clock is injectable for deterministic tests.
"""

from __future__ import annotations

import time
from collections.abc import Iterable


class HeatTracker:
    """Decayed per-guid touch counters with an injectable clock."""

    __slots__ = ("half_life_s", "_clock", "_h")

    def __init__(self, half_life_s: float = 300.0, clock=None):
        self.half_life_s = max(1e-6, float(half_life_s))
        self._clock = clock if clock is not None else time.monotonic
        self._h: dict[str, tuple[float, float]] = {}

    def __len__(self) -> int:
        return len(self._h)

    def __contains__(self, guid: str) -> bool:
        return guid in self._h

    def touch(self, guid: str, weight: float = 1.0) -> float:
        """Fold one access of ``weight`` into the doc's decayed score."""
        now = self._clock()
        prev = self._h.get(guid)
        if prev is None:
            heat = float(weight)
        else:
            h, ts = prev
            heat = h * 0.5 ** ((now - ts) / self.half_life_s) + weight
        self._h[guid] = (heat, now)
        return heat

    def score(self, guid: str, now: float | None = None) -> float:
        """Current decayed heat; 0.0 for a never-touched doc."""
        rec = self._h.get(guid)
        if rec is None:
            return 0.0
        h, ts = rec
        if now is None:
            now = self._clock()
        return h * 0.5 ** (max(0.0, now - ts) / self.half_life_s)

    def set(self, guid: str, heat: float) -> None:
        """Adopt an externally-carried score (migration / recovery)."""
        self._h[guid] = (max(0.0, float(heat)), self._clock())

    def forget(self, guid: str) -> None:
        self._h.pop(guid, None)

    def coldest(self, guids: Iterable[str]) -> list[str]:
        """``guids`` ordered coldest-first (score, then guid — the tie
        break keeps eviction deterministic for never-touched docs)."""
        now = self._clock()
        return sorted(guids, key=lambda g: (self.score(g, now), g))
