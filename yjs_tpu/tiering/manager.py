"""TierManager: the heat-driven hot/warm/cold doc lifecycle (ISSUE 7).

Every provider owns exactly one manager.  Three tiers:

- **hot** — the doc holds an engine slot: packed columns on device,
  mirror on host, updates integrate batched like always;
- **warm** — the doc's host mirror is detached (struct-of-arrays
  columns + interned payloads, no engine references) and the slot is
  freed.  Promotion scatters the columns straight back into a slot —
  ``Engine.hydrate_doc_columns`` — with NO decode round-trip;
- **cold** — the doc is folded into a durable ``KIND_TIER`` WAL record
  (full ``encode_state_as_update`` bytes + meta) and only a
  ``(segment, offset, length)`` locator is kept in memory (a compressed
  blob when the provider has no WAL).  Promotion replays the encoded
  state through the normal decode path, exactly like the PR 3
  snapshot-then-tail recovery.

Demotion journals BEFORE the slot is freed, so a crash mid-demotion
recovers the doc in exactly one tier: the tier record lost → the
journaled updates still replay it hot; the record present → recovery
places it demoted (unless later records show it was touched again).
Dead letters attributed to the slot ride the tier record the same way
(they must not be misattributed to the slot's next tenant and must not
vanish — ISSUE 7 satellite).

The whole subsystem is **opt-in** (``TierConfig(enabled=True)`` or
``YTPU_TIER_ENABLED=1``): with it off, the manager is inert bookkeeping
— ``doc_id()`` keeps raising ``ProviderFullError`` and every existing
contract holds bit-for-bit.  Metrics (the ``ytpu_tier_*`` families)
register unconditionally so exposition and the schema checker see them
either way.
"""

from __future__ import annotations

import base64
import os
import time
import zlib

from ..obs import TierMetrics
from ..persistence.records import (
    KIND_TIER,
    decode_tier_payload,
    encode_tier_payload,
    try_decode_at,
)
from .heat import HeatTracker

HOT = "hot"
WARM = "warm"
COLD = "cold"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class TierConfig:
    """Tiering policy knobs (env-derived defaults, constructor wins).

    - ``YTPU_TIER_ENABLED`` — master switch (default off: the provider
      keeps its hard-capped ``ProviderFullError`` contract);
    - ``YTPU_TIER_HALF_LIFE_S`` — heat half-life in seconds (300);
    - ``YTPU_TIER_WARM_MAX`` — max docs held warm before the coldest
      spill to the cold tier (0 = unbounded);
    - ``YTPU_TIER_SESSION_WEIGHT`` — extra touch weight a session
      admission adds (8.0 — an attached peer outweighs stray reads);
    - ``YTPU_TIER_OVERCOMMIT`` — virtual-capacity multiplier the fleet
      router advertises per tiered shard (64);
    - ``YTPU_TIER_GC_MIN_ROWS`` / ``YTPU_TIER_GC_DELETED_RATIO`` — a
      hot doc qualifies for a forced tombstone/GC compaction pass once
      it holds at least MIN_ROWS packed rows of which at least
      DELETED_RATIO are deleted content (512 / 0.5);
    - ``YTPU_TIER_GC_MAX_DOCS`` — GC'd docs per ``tick`` pass (8).
    """

    __slots__ = (
        "enabled", "half_life_s", "warm_max", "session_weight",
        "overcommit", "gc_min_rows", "gc_deleted_ratio", "gc_max_docs",
    )

    def __init__(
        self,
        enabled: bool | None = None,
        half_life_s: float | None = None,
        warm_max: int | None = None,
        session_weight: float | None = None,
        overcommit: int | None = None,
        gc_min_rows: int | None = None,
        gc_deleted_ratio: float | None = None,
        gc_max_docs: int | None = None,
    ):
        if enabled is None:
            enabled = os.environ.get("YTPU_TIER_ENABLED", "0") in (
                "1", "true", "yes",
            )
        self.enabled = bool(enabled)
        if half_life_s is None:
            half_life_s = _env_float("YTPU_TIER_HALF_LIFE_S", 300.0)
        self.half_life_s = max(1e-6, float(half_life_s))
        if warm_max is None:
            warm_max = _env_int("YTPU_TIER_WARM_MAX", 0)
        self.warm_max = max(0, int(warm_max))
        if session_weight is None:
            session_weight = _env_float("YTPU_TIER_SESSION_WEIGHT", 8.0)
        self.session_weight = max(0.0, float(session_weight))
        if overcommit is None:
            overcommit = _env_int("YTPU_TIER_OVERCOMMIT", 64)
        self.overcommit = max(1, int(overcommit))
        if gc_min_rows is None:
            gc_min_rows = _env_int("YTPU_TIER_GC_MIN_ROWS", 512)
        self.gc_min_rows = max(1, int(gc_min_rows))
        if gc_deleted_ratio is None:
            gc_deleted_ratio = _env_float("YTPU_TIER_GC_DELETED_RATIO", 0.5)
        self.gc_deleted_ratio = min(1.0, max(0.0, float(gc_deleted_ratio)))
        if gc_max_docs is None:
            gc_max_docs = _env_int("YTPU_TIER_GC_MAX_DOCS", 8)
        self.gc_max_docs = max(0, int(gc_max_docs))

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class _WarmEntry:
    __slots__ = ("mirror", "letters", "log", "nbytes")

    def __init__(self, mirror, letters: list, log: list):
        self.mirror = mirror
        self.letters = letters
        # the slot's replay journal (engine ``_update_log`` invariant:
        # replays to the doc's full state) — restored on promotion so a
        # later CPU-demotion rollback still has history to rebuild from
        self.log = log
        self.nbytes = mirror.host_nbytes() + sum(
            len(u) for u, _v2 in log
        )


class _ColdEntry:
    __slots__ = ("ref", "blob", "letters", "nbytes")

    def __init__(self, ref, blob, letters: list):
        self.ref = ref  # (segment path, offset, length) WAL locator
        self.blob = blob  # zlib'd state (no-WAL providers / checkpoints)
        self.letters = letters
        self.nbytes = ref[2] if ref is not None else len(blob)


def _dump_letters(letters) -> list[dict]:
    """DeadLetter objects → the JSON-able shape tier records carry."""
    return [
        {
            "v2": bool(e.v2),
            "reason": e.reason,
            "update": base64.b64encode(e.update).decode("ascii"),
        }
        for e in letters
    ]


def _restore_letters(dumped: list, doc: int, dlq) -> None:
    for d in dumped:
        dlq.append(
            doc,
            base64.b64decode(d.get("update", "")),
            bool(d.get("v2")),
            str(d.get("reason", "tiered")),
        )


class TierManager:
    """Hot/warm/cold lifecycle bound to one :class:`TpuProvider`."""

    def __init__(self, provider, config: TierConfig | None = None):
        self.provider = provider
        self.config = config if config is not None else TierConfig()
        self.heat = HeatTracker(self.config.half_life_s)
        self.metrics = TierMetrics(provider.engine.obs.registry)
        self.warm: dict[str, _WarmEntry] = {}
        self.cold: dict[str, _ColdEntry] = {}
        self._warm_bytes = 0
        self._cold_bytes = 0

    # -- policy inputs -------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    def touch(self, guid: str, weight: float = 1.0) -> None:
        """One access through any provider seam; free when disabled."""
        if self.config.enabled:
            self.heat.touch(guid, weight)

    def heat_of(self, guid: str) -> float:
        """Decayed heat score; 0.0 when tiering is off or never touched
        — callers sorting by heat degrade to their old order."""
        return self.heat.score(guid)

    def tier_of(self, guid: str) -> str | None:
        if guid in self.provider._guids:
            return HOT
        if guid in self.warm:
            return WARM
        if guid in self.cold:
            return COLD
        return None

    def resident_count(self) -> int:
        return len(self.provider._guids) + len(self.warm) + len(self.cold)

    def resident_guids(self) -> list[str]:
        return sorted(
            set(self.provider._guids) | set(self.warm) | set(self.cold)
        )

    # -- demotion ------------------------------------------------------------

    def demote(self, guid: str, tier: str = WARM) -> bool:
        """Move a doc down to ``tier`` (``"warm"`` or ``"cold"``).

        Hot docs are flushed, their final state journaled as a
        ``KIND_TIER`` record (with the slot's dead letters riding
        along), the mirror detached, and the slot freed — journal
        BEFORE free, so a crash in between recovers the doc in exactly
        one tier.  Docs pinned to their slot (CPU fallback, registered
        observers, quarantine-parked updates) raise.  Returns False
        only for a warm→cold fold blocked by parked causal deps."""
        if tier not in (WARM, COLD):
            raise ValueError(f"unknown destination tier {tier!r}")
        prov = self.provider
        if guid not in prov._guids:
            if tier == COLD and guid in self.warm:
                return self._warm_to_cold(guid)
            if self.tier_of(guid) == tier:
                return True
            raise KeyError(f"unknown doc {guid!r}")
        t0 = time.perf_counter()
        eng = prov.engine
        i = prov._guids[guid]
        if i in eng.fallback:
            raise ValueError(
                f"{guid!r} is CPU-served; its fallback doc is bound to "
                "the slot and cannot be tiered"
            )
        if i in eng._event_listeners:
            raise ValueError(
                f"{guid!r} has observers bound to its slot; "
                "unobserve before demoting"
            )
        prov.flush()
        if eng.mirrors[i]._incoming:
            raise RuntimeError(
                f"{guid!r} still holds un-integrated updates after a "
                "flush (quarantine backoff); not demotable until "
                "re-admitted"
            )
        mirror = eng.export_doc_columns(i)
        # fold the slot's replay journal when the doc is causally whole
        # (the engine's own >64-entry fold idiom); keep it raw when
        # structs are parked — encoded state would drop them
        if mirror.has_pending():
            log = list(eng._update_log[i])
        else:
            log = [(mirror.encode_state_as_update(), False)]
        letters = _dump_letters(eng.dead_letters.take(doc=i))
        score = self.heat.score(guid)
        if prov.wal is not None:
            prov.wal.append(
                KIND_TIER,
                guid,
                encode_tier_payload(
                    WARM, score, mirror.encode_state_as_update(), letters
                ),
            )
        eng.reset_doc(i)
        del prov._guids[guid]
        del prov._guid_of[i]
        prov._free.append(i)
        self.warm[guid] = e = _WarmEntry(mirror, letters, log)
        self._warm_bytes += e.nbytes
        self.metrics.transition(HOT, WARM)
        self.metrics.demoted(WARM, time.perf_counter() - t0)
        ok = True
        if tier == COLD:
            ok = self._warm_to_cold(guid)
        else:
            self._enforce_warm_bound()
        self._refresh_gauges()
        return ok

    def _warm_to_cold(self, guid: str) -> bool:
        """Fold a warm mirror into a durable cold record.  Refuses (and
        keeps the doc warm) when the mirror parks causally-unready
        updates — encoded state would silently drop them."""
        e = self.warm[guid]
        if e.mirror.has_pending():
            return False
        t0 = time.perf_counter()
        del self.warm[guid]
        self._warm_bytes -= e.nbytes
        update = e.mirror.encode_state_as_update()
        prov = self.provider
        if prov.wal is not None:
            ref = prov.wal.append(
                KIND_TIER,
                guid,
                encode_tier_payload(
                    COLD, self.heat.score(guid), update, e.letters
                ),
            )
            ce = _ColdEntry(ref, None, e.letters)
        else:
            ce = _ColdEntry(None, zlib.compress(update), e.letters)
        self.cold[guid] = ce
        self._cold_bytes += ce.nbytes
        self.metrics.transition(WARM, COLD)
        self.metrics.demoted(COLD, time.perf_counter() - t0)
        self._refresh_gauges()
        return True

    def _enforce_warm_bound(self) -> None:
        cap = self.config.warm_max
        if not cap:
            return
        while len(self.warm) > cap:
            for guid in self.heat.coldest(self.warm):
                if self._warm_to_cold(guid):
                    break
            else:
                return  # every warm doc has parked deps: stop spilling

    # -- promotion -----------------------------------------------------------

    def promote(self, guid: str) -> int:
        """Bring a demoted doc back into a device slot; returns it.

        Warm: the detached mirror hydrates straight into the slot (no
        decode).  Cold: the journaled state replays through the normal
        decode path.  Either way the doc's dead letters return to the
        slot, and a ``KIND_TIER`` "hot" marker is journaled so recovery
        knows the demote marker no longer stands.

        Pipeline note (ISSUE 12): hydration only STAGES host rows; the
        device scatter is deferred to the next flush, where it rides
        the engine's single ``_dispatch`` seam as a donated
        ``scatter_rows`` stage.  The staged host copy belongs to the
        engine, so the warm mirror released here never aliases a
        donated device buffer."""
        src = self.tier_of(guid)
        if src not in (WARM, COLD):
            raise KeyError(f"{guid!r} is not demoted (tier={src})")
        t0 = time.perf_counter()
        prov = self.provider
        i = self._alloc_slot(guid)
        # re-resolve: make_room inside _alloc_slot can spill THIS doc
        # warm→cold while we were looking
        if guid in self.warm:
            src = WARM
            e: _WarmEntry | _ColdEntry = self.warm.pop(guid)
            self._warm_bytes -= e.nbytes
            prov.engine.hydrate_doc_columns(i, e.mirror)
            prov.engine._update_log[i] = list(e.log)
        else:
            src = COLD
            e = self.cold.pop(guid)
            self._cold_bytes -= e.nbytes
            prov.engine.queue_update(i, self._cold_update(guid, e))
            prov._dirty = True
            # materialize now: callers flush-then-doc_id (text, sync
            # step answers), so the replay must not stay queued past
            # the promotion — and promote latency should honestly
            # include the decode+integrate cost warm promotion skips
            prov.flush()
        _restore_letters(e.letters, i, prov.engine.dead_letters)
        if prov.wal is not None:
            prov.wal.append(
                KIND_TIER,
                guid,
                encode_tier_payload(HOT, self.heat.score(guid), b""),
            )
        self.metrics.transition(src, HOT)
        self.metrics.promoted(src, time.perf_counter() - t0)
        self._refresh_gauges()
        return i

    def _alloc_slot(self, guid: str) -> int:
        """A free slot for ``guid``, evicting the coldest eligible hot
        doc when the provider is full; registers the slot maps."""
        prov = self.provider
        if prov._free:
            i = prov._free.pop()
        elif prov._next < prov.engine.n_docs:
            i = prov._next
            prov._next += 1
        else:
            if not self.make_room():
                from ..provider import ProviderFullError

                raise ProviderFullError(
                    f"provider is full ({prov.engine.n_docs} docs) and "
                    f"no hot doc is evictable (all pinned by fallback/"
                    f"observers/quarantine); cannot admit {guid!r}"
                )
            i = prov._free.pop()
        prov._guids[guid] = i
        prov._guid_of[i] = guid
        return i

    def make_room(self) -> bool:
        """Demote the coldest eligible hot doc to warm (the auto-evict
        behind ``doc_id``); False when nothing is evictable."""
        prov = self.provider
        eng = prov.engine
        prov.flush()
        sessioned = {g for (g, _p) in getattr(prov, "_sessions", {})}
        eligible = [
            g
            for g, i in prov._guids.items()
            if i not in eng.fallback
            and i not in eng._event_listeners
            and not eng.mirrors[i]._incoming
        ]
        if not eligible:
            return False
        now = self.heat._clock()
        eligible.sort(
            key=lambda g: (g in sessioned, self.heat.score(g, now), g)
        )
        self.demote(eligible[0], WARM)
        self.metrics.evicted()
        return True

    def _cold_update(self, guid: str, e: _ColdEntry) -> bytes:
        if e.blob is not None:
            return zlib.decompress(e.blob)
        path, offset, length = e.ref
        with open(path, "rb") as f:
            f.seek(offset)
            buf = f.read(length)
        status, rec, _end = try_decode_at(buf, 0)
        if status != "ok" or rec.kind != KIND_TIER:
            raise RuntimeError(
                f"cold record for {guid!r} unreadable at "
                f"{path}:{offset} ({status})"
            )
        _meta, update = decode_tier_payload(rec.payload)
        return update

    # -- release / checkpoint / recovery ------------------------------------

    def release(self, guid: str):
        """Drop a DEMOTED doc for good: returns ``(final_state_bytes,
        letters)`` or None when the guid holds no demoted entry."""
        if guid in self.warm:
            e: _WarmEntry | _ColdEntry = self.warm.pop(guid)
            self._warm_bytes -= e.nbytes
            update = e.mirror.encode_state_as_update()
        elif guid in self.cold:
            e = self.cold.pop(guid)
            self._cold_bytes -= e.nbytes
            update = self._cold_update(guid, e)
        else:
            return None
        self.heat.forget(guid)
        self._refresh_gauges()
        return update, e.letters

    def forget(self, guid: str) -> None:
        """Heat bookkeeping for a doc released from the hot tier."""
        self.heat.forget(guid)

    def adopt_heat(self, guid: str, score: float) -> None:
        """Carry a migrated/recovered doc's heat across providers."""
        if self.config.enabled and score > 0.0:
            self.heat.set(guid, score)

    def demoted_snapshots(self) -> list[tuple[str, bytes]]:
        """(guid, full-state bytes) for every demoted doc — they join
        the hot docs in the provider checkpoint so compaction covers
        all tiers.  Cold locators are materialized into blobs here,
        BEFORE ``wal.checkpoint`` deletes the segments they point at;
        :meth:`rejournal` re-anchors them afterwards."""
        out = []
        for guid in sorted(self.warm):
            out.append(
                (guid, self.warm[guid].mirror.encode_state_as_update())
            )
        for guid in sorted(self.cold):
            e = self.cold[guid]
            update = self._cold_update(guid, e)
            if e.blob is None:
                e.blob = zlib.compress(update)
            out.append((guid, update))
        return out

    def rejournal(self) -> None:
        """Re-append every demote marker after a checkpoint (the
        ack-floor idiom): compaction deleted the segments the markers —
        and the cold locators — lived in."""
        wal = self.provider.wal
        if wal is None:
            return
        for guid in sorted(self.warm):
            e = self.warm[guid]
            wal.append(
                KIND_TIER,
                guid,
                encode_tier_payload(
                    WARM,
                    self.heat.score(guid),
                    e.mirror.encode_state_as_update(),
                    e.letters,
                ),
            )
        for guid in sorted(self.cold):
            ce = self.cold[guid]
            update = self._cold_update(guid, ce)
            ref = wal.append(
                KIND_TIER,
                guid,
                encode_tier_payload(
                    COLD, self.heat.score(guid), update, ce.letters
                ),
            )
            self._cold_bytes += ref[2] - ce.nbytes
            ce.ref = ref
            ce.nbytes = ref[2]
            ce.blob = None

    def place_recovered(self, markers: dict) -> dict:
        """Post-replay tier placement: demote each doc whose LAST WAL
        record is a standing demote marker (recovery replayed its state
        hot first).  Returns ``{guid: tier}`` for the docs placed."""
        placed: dict[str, str] = {}
        prov = self.provider
        for guid in sorted(markers):
            meta = markers[guid]
            tier = meta.get("tier")
            if tier not in (WARM, COLD):
                continue
            if guid not in prov._guids:
                continue
            self.heat.set(guid, float(meta.get("heat", 0.0)))
            # the recorded letters return to the slot first, so the
            # demote scoops them together with anything replay itself
            # dead-lettered there
            _restore_letters(
                meta.get("letters") or [],
                prov._guids[guid],
                prov.engine.dead_letters,
            )
            try:
                self.demote(guid, tier)
            except (ValueError, RuntimeError):
                continue  # pinned (fallback/observers): stays hot
            # a cold request can legitimately settle warm (parked deps)
            placed[guid] = self.tier_of(guid) or tier
        return placed

    # -- GC / maintenance ----------------------------------------------------

    def gc_pass(self, max_docs: int | None = None) -> dict:
        """Forced tombstone/GC compaction over qualifying hot docs (≥
        ``gc_min_rows`` rows, ≥ ``gc_deleted_ratio`` deleted) — the
        long-lived-hot-doc bound the amortized doubling pass misses."""
        out = {"docs": 0, "rows_reclaimed": 0, "bytes_reclaimed": 0}
        if not self.config.enabled:
            return out
        prov = self.provider
        eng = prov.engine
        prov.flush()
        cfg = self.config
        cand = []
        for guid in sorted(prov._guids):
            i = prov._guids[guid]
            if i in eng.fallback or eng.mirrors[i]._incoming:
                continue
            m = eng.mirrors[i]
            if m.n_rows < cfg.gc_min_rows:
                continue
            if m.deleted_ratio() < cfg.gc_deleted_ratio:
                continue
            cand.append(i)
        limit = cfg.gc_max_docs if max_docs is None else max_docs
        if limit:
            cand = cand[:limit]
        if not cand:
            return out
        before = sum(eng.mirrors[i].host_nbytes() for i in cand)
        stats = eng.compact_docs(cand, gc=True)
        after = sum(eng.mirrors[i].host_nbytes() for i in cand)
        out["docs"] = len(stats)
        out["rows_reclaimed"] = max(
            0, sum(s["rows_before"] - s["rows_after"] for s in stats)
        )
        out["bytes_reclaimed"] = max(0, before - after)
        self.metrics.gc(out["rows_reclaimed"], out["bytes_reclaimed"])
        return out

    def tick(self) -> dict:
        """One background maintenance pass: warm-bound spill + GC."""
        if not self.config.enabled:
            return {"docs": 0, "rows_reclaimed": 0, "bytes_reclaimed": 0}
        self._enforce_warm_bound()
        out = self.gc_pass()
        self._refresh_gauges()
        return out

    # -- exposition ----------------------------------------------------------

    def _refresh_gauges(self) -> None:
        self.metrics.occupancy(
            {
                HOT: len(self.provider._guids),
                WARM: len(self.warm),
                COLD: len(self.cold),
            },
            {
                HOT: 0,
                WARM: max(0, self._warm_bytes),
                COLD: max(0, self._cold_bytes),
            },
        )

    def snapshot(self) -> dict:
        """JSON-able tier state (rides ``provider.metrics_snapshot``)."""
        self._refresh_gauges()
        hot = len(self.provider._guids)
        return {
            "enabled": self.config.enabled,
            "hot": hot,
            "warm": len(self.warm),
            "cold": len(self.cold),
            "resident": hot + len(self.warm) + len(self.cold),
            "capacity": self.provider.engine.n_docs,
            "warm_bytes": max(0, self._warm_bytes),
            "cold_bytes": max(0, self._cold_bytes),
            "config": self.config.as_dict(),
        }
