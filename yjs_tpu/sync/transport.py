"""Transport seam for :mod:`yjs_tpu.sync.session`.

A :class:`Transport` is the narrowest thing a session needs from a
network: ``send(frame)`` one length-delimited byte frame, surface
inbound frames through ``on_frame``, and report loss through
``on_close``.  Framing, threading, and reconnection policy live with
the transport's owner — the session only ever sees whole frames and a
liveness signal.

Two implementations ship here:

- :class:`CallbackTransport` — adapter for callers that already have a
  delivery mechanism (a socket writer thread, a websocket, a test
  harness): construct with a ``send_fn`` and feed inbound bytes to
  :meth:`CallbackTransport.deliver`.
- :class:`PipeNetwork` / :class:`PipeTransport` — a deterministic
  in-memory network for tests and benchmarks: frames queue in-flight
  and deliver on explicit :meth:`PipeNetwork.pump` rounds, optionally
  through a :class:`yjs_tpu.resilience.chaos.NetworkFaultInjector`
  (drop / delay / duplicate / reorder / partition at this exact seam).
"""

from __future__ import annotations


class Transport:
    """Contract: ``send`` whole frames out, get whole frames in via
    ``on_frame``, learn about loss via ``on_close``.  ``send`` returns
    False (never raises) when the transport is down — the session
    treats that as a loss signal and keeps the frame for retransmit."""

    def __init__(self):
        self.on_frame = None  # callable(frame: bytes)
        self.on_close = None  # callable()
        self.alive = True

    def send(self, frame: bytes) -> bool:  # pragma: no cover - contract
        raise NotImplementedError

    def close(self) -> None:
        if not self.alive:
            return
        self.alive = False
        cb = self.on_close
        if cb is not None:
            cb()


class CallbackTransport(Transport):
    """Adapter transport: outbound frames go to ``send_fn(frame)``
    (return False or raise to signal loss); the owner pushes inbound
    frames with :meth:`deliver`."""

    def __init__(self, send_fn):
        super().__init__()
        self._send_fn = send_fn

    def send(self, frame: bytes) -> bool:
        if not self.alive:
            return False
        try:
            ok = self._send_fn(frame)
        except Exception:
            self.close()
            return False
        if ok is False:
            self.close()
            return False
        return True

    def deliver(self, frame: bytes) -> None:
        if self.alive and self.on_frame is not None:
            self.on_frame(bytes(frame))


class PipeTransport(Transport):
    """One endpoint of an in-memory :class:`PipeNetwork` link."""

    def __init__(self, network: "PipeNetwork", name: str):
        super().__init__()
        self.network = network
        self.name = name
        self.peer: "PipeTransport | None" = None

    def send(self, frame: bytes) -> bool:
        if not self.alive or self.peer is None or not self.peer.alive:
            return False
        self.network._enqueue(self, self.peer, bytes(frame))
        return True


class PipeNetwork:
    """Deterministic in-memory frame network.

    Frames sent on one endpoint queue in-flight and reach the peer's
    ``on_frame`` only during :meth:`pump` — tests control time.  An
    optional injector (see
    :class:`yjs_tpu.resilience.chaos.NetworkFaultInjector`) decides
    each frame's fate at enqueue time (drop / duplicate / delay) and
    each pump round's shape (reorder, partition)."""

    def __init__(self, injector=None):
        self.injector = injector
        self.round = 0
        # in-flight entries: (due_round, dst_transport, frame)
        self._inflight: list[tuple[int, "PipeTransport", bytes]] = []

    def pair(
        self, a_name: str = "a", b_name: str = "b"
    ) -> tuple[PipeTransport, PipeTransport]:
        a = PipeTransport(self, a_name)
        b = PipeTransport(self, b_name)
        a.peer, b.peer = b, a
        # WAN-profile injectors pick one-way partition victims from the
        # registered endpoint names (getattr: simple test fakes lack it)
        reg = getattr(self.injector, "register_link", None)
        if reg is not None:
            reg(a_name, b_name)
        return a, b

    def _enqueue(self, src, dst, frame: bytes) -> None:
        inj = self.injector
        if inj is None:
            self._inflight.append((self.round + 1, dst, frame))
            return
        for delay in inj.fates(frame):
            if delay is None:
                continue  # dropped
            self._inflight.append((self.round + 1 + delay, dst, frame))

    @property
    def in_flight(self) -> int:
        return len(self._inflight)

    def kill(self, *transports: PipeTransport) -> None:
        """Sever endpoints (transport loss, NOT process loss): their
        in-flight frames vanish and ``on_close`` fires — the session
        on each side goes ``reconnecting`` and keeps its state."""
        dead = set(transports)
        self._inflight = [
            e for e in self._inflight if e[1] not in dead
        ]
        for t in transports:
            t.close()

    def pump(self, rounds: int = 1) -> int:
        """Advance time; deliver every due frame.  Returns frames
        delivered (dropped/partitioned frames do not count)."""
        delivered = 0
        inj = self.injector
        for _ in range(rounds):
            self.round += 1
            due = [e for e in self._inflight if e[0] <= self.round]
            if not due:
                continue
            self._inflight = [
                e for e in self._inflight if e[0] > self.round
            ]
            partitioned = inj is not None and inj.partitioned()
            if partitioned:
                continue  # the link is down: everything due is lost
            # WAN shaping (one-way partitions, flap windows, bandwidth
            # caps) is direction-aware, so it filters per frame rather
            # than felling the whole round; over-budget frames re-queue
            # for the next round instead of being lost
            filt = getattr(inj, "filter_due", None)
            if filt is not None:
                due, defer = filt(due, self.round)
                for _due_round, dst, frame in defer:
                    self._inflight.append((self.round + 1, dst, frame))
            if inj is not None and len(due) > 1:
                due = inj.maybe_reorder(due)
            for _due_round, dst, frame in due:
                if dst.alive and dst.on_frame is not None:
                    dst.on_frame(frame)
                    delivered += 1
        return delivered

    def settle(
        self, tick_fns=(), max_rounds: int = 200, idle_rounds: int = 1
    ) -> int:
        """Pump (interleaving the given session ``tick`` callables)
        until the wire stays empty for ``idle_rounds`` consecutive
        rounds; returns rounds used.  Under fault injection an empty
        wire is NOT settled — a dropped frame regenerates only when its
        retransmit backoff expires — so lossy callers must pass an
        ``idle_rounds`` larger than the worst backoff gap (e.g.
        ``retry_cap * (1 + retry_jitter)`` ticks)."""
        idle = 0
        for n in range(max_rounds):
            if not self._inflight:
                idle += 1
                if n > 0 and idle >= idle_rounds:
                    return n
            else:
                idle = 0
            self.pump()
            for fn in tick_fns:
                fn()
        return max_rounds
