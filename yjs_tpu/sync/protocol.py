"""The 2-step state-vector sync handshake.

The reference keeps this in the external y-protocols package (see reference
INTERNALS.md:145-166 and tests/testHelper.js:6,51-52,160); here it is a
first-class framework module, byte-compatible with y-protocols/sync.js:

- step 1: send your state vector
- step 2: reply with `encode_state_as_update(doc, remote_sv)`
- update: incremental broadcast

Transport framing beyond these 3 message types (websocket, webrtc, ...)
remains provider territory (yjs_tpu/provider).
"""

from __future__ import annotations

from ..core import Doc
from ..lib0 import decoding, encoding
from ..lib0.decoding import Decoder
from ..lib0.encoding import Encoder
from ..obs import global_registry
from ..updates import apply_update, encode_state_as_update, encode_state_vector

MESSAGE_YJS_SYNC_STEP_1 = 0
MESSAGE_YJS_SYNC_STEP_2 = 1
MESSAGE_YJS_UPDATE = 2

# skip-and-count marker returned by read_sync_message for frames it
# tolerated but could not dispatch (unknown type / malformed payload)
MESSAGE_UNKNOWN = -1

_TYPE_NAMES = {
    MESSAGE_YJS_SYNC_STEP_1: "step1",
    MESSAGE_YJS_SYNC_STEP_2: "step2",
    MESSAGE_YJS_UPDATE: "update",
}

# per-frame counters live on the process-global registry (these are free
# functions with no engine handle); engine/provider exposition merges it
_frames = global_registry().get("ytpu_sync_messages_total")


def _count(direction: str, message_type: int) -> None:
    if _frames is not None:
        # unknown types count under "unknown" instead of KeyError'ing —
        # a hostile peer must never be able to crash the frame counter
        name = _TYPE_NAMES.get(message_type, "unknown")
        _frames.labels(dir=direction, type=name).inc()


def write_sync_step1(encoder: Encoder, doc: Doc) -> None:
    encoding.write_var_uint(encoder, MESSAGE_YJS_SYNC_STEP_1)
    encoding.write_var_uint8_array(encoder, encode_state_vector(doc))
    _count("write", MESSAGE_YJS_SYNC_STEP_1)


def write_sync_step2(encoder: Encoder, doc: Doc, encoded_state_vector: bytes | None = None) -> None:
    encoding.write_var_uint(encoder, MESSAGE_YJS_SYNC_STEP_2)
    encoding.write_var_uint8_array(encoder, encode_state_as_update(doc, encoded_state_vector))
    _count("write", MESSAGE_YJS_SYNC_STEP_2)


def read_sync_step1(decoder: Decoder, encoder: Encoder, doc: Doc) -> None:
    _count("read", MESSAGE_YJS_SYNC_STEP_1)
    write_sync_step2(encoder, doc, decoding.read_var_uint8_array(decoder))


def read_sync_step2(decoder: Decoder, doc: Doc, transaction_origin=None,
                    slo=None) -> None:
    _count("read", MESSAGE_YJS_SYNC_STEP_2)
    _apply(decoder, doc, transaction_origin, slo)


def _apply(decoder: Decoder, doc: Doc, transaction_origin, slo) -> None:
    """Apply one framed update payload, optionally stamping convergence
    timestamps on a :class:`yjs_tpu.obs.slo.ConvergenceTracker` — the
    receive seam for CPU-doc deployments (a Doc integrates synchronously,
    so receive → integrate → visible collapse into this one call; the
    bytes on the wire are untouched)."""
    u = decoding.read_var_uint8_array(decoder)
    if slo is None:
        apply_update(doc, u, transaction_origin)
        return
    key = slo.receive(u)
    try:
        apply_update(doc, u, transaction_origin)
    except Exception:
        slo.rejected(key)
        raise
    slo.integrated(key)
    slo.visible()


def write_update(encoder: Encoder, update: bytes) -> None:
    encoding.write_var_uint(encoder, MESSAGE_YJS_UPDATE)
    encoding.write_var_uint8_array(encoder, update)
    _count("write", MESSAGE_YJS_UPDATE)


def read_update_message(decoder: Decoder, doc: Doc, transaction_origin=None,
                        slo=None) -> None:
    """Same wire handling as read_sync_step2 (an update IS a partial
    step-2 payload); counted separately so frame-type traffic is visible."""
    _count("read", MESSAGE_YJS_UPDATE)
    _apply(decoder, doc, transaction_origin, slo)


def read_sync_message(decoder: Decoder, encoder: Encoder, doc: Doc,
                      transaction_origin=None, slo=None) -> int:
    """Dispatch one sync frame; returns its message type.

    Tolerant by contract (y-protocols sync.js readSyncMessage logs and
    continues): a frame whose type is unknown — a newer protocol
    revision, or transport corruption of the type varint — is counted
    as ``ytpu_sync_messages_total{type="unknown"}`` and skipped, and
    :data:`MESSAGE_UNKNOWN` is returned so callers can surface it.  A
    truncated/garbage type varint raises ``ValueError`` as before (there
    is no frame to skip past)."""
    message_type = decoding.read_var_uint(decoder)
    if message_type == MESSAGE_YJS_SYNC_STEP_1:
        read_sync_step1(decoder, encoder, doc)
    elif message_type == MESSAGE_YJS_SYNC_STEP_2:
        read_sync_step2(decoder, doc, transaction_origin, slo=slo)
    elif message_type == MESSAGE_YJS_UPDATE:
        read_update_message(decoder, doc, transaction_origin, slo=slo)
    else:
        _count("read", message_type)
        return MESSAGE_UNKNOWN
    return message_type
