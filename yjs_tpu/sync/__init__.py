from .protocol import (  # noqa: F401
    MESSAGE_YJS_SYNC_STEP_1,
    MESSAGE_YJS_SYNC_STEP_2,
    MESSAGE_YJS_UPDATE,
    read_sync_message,
    read_sync_step1,
    read_sync_step2,
    write_sync_step1,
    write_sync_step2,
    write_update,
)
