from .protocol import (  # noqa: F401
    MESSAGE_YJS_SYNC_STEP_1,
    MESSAGE_YJS_SYNC_STEP_2,
    MESSAGE_YJS_UPDATE,
    read_sync_message,
    read_sync_step1,
    read_sync_step2,
    write_sync_step1,
    write_sync_step2,
    write_update,
)
from .session import (  # noqa: F401
    CONNECTING,
    LAGGING,
    LIVE,
    MESSAGE_YTPU_SESSION,
    RECONNECTING,
    SYNCING,
    CLOSED,
    DocSessionHost,
    SessionConfig,
    SessionMetrics,
    SyncSession,
)
from .transport import (  # noqa: F401
    CallbackTransport,
    PipeNetwork,
    PipeTransport,
    Transport,
)
