"""SyncSession: resumable per-peer sessions over the y-protocols wire.

The sync protocol (:mod:`yjs_tpu.sync.protocol`) is a byte-compatible
port of y-protocols' 2-step handshake and is deliberately network-
agnostic — which means a lost, duplicated, or stalled frame silently
diverges a peer until the next full handshake.  This module makes the
FRAMEWORK own peer-session lifecycle (ISSUE 5 tentpole) without
changing one wire byte of the v13.4.9-compatible frames: session
control rides a new ENVELOPE message type that a plain y-protocols
peer's tolerant frame reader skips and counts as unknown, so sessions
negotiate DOWN to the plain protocol automatically when the far side
never speaks envelope.

Per-peer state machine::

    connecting ──► syncing ──► live ◄──► lagging
        ▲            ▲          │
        │            └──attach──┤ (transport loss / liveness timeout)
        └── (first attach)      ▼
                           reconnecting ──► closed

- **connecting**: transport attached, HELLO sent, peer not yet heard.
- **syncing**: handshake frames exchanged; the initial delta (computed
  against the peer's HELLO/WELCOME state vector) is in flight.
- **live**: steady state — updates flow as seq-numbered DATA frames,
  cumulative ACKs flow back, unacked frames retransmit with
  exponential backoff + jitter and dead-letter after the retry cap.
- **lagging**: the bounded outbox crossed its high watermark; new
  updates coalesce into ONE pending delta (computed against the
  peer's last-known state vector) that is sent when ACKs drain the
  outbox below the low watermark — intermediate deltas are shed in
  preference to disconnecting the peer.
- **reconnecting**: transport lost; all session state (seq spaces,
  outbox, peer identity) is retained so :meth:`SyncSession.attach`
  resumes with delta catch-up instead of a full resync.
- **closed**: terminal.

An **anti-entropy repair loop** (every ``YTPU_NET_ANTIENTROPY`` ticks
in ``live``) exchanges state-vector digests and heals silent divergence
— anything retransmission could not deliver (retry-cap dead letters,
frames shed under backpressure, partitions outliving the outbox) — via
targeted diffs, counted in ``ytpu_net_antientropy_repairs_total``.

Time is counted in TICKS (the caller drives :meth:`SyncSession.tick`),
the same deterministic-clock choice as the resilience health tracker:
backoff, heartbeat, liveness, and anti-entropy behavior all replay
exactly under test.  All ``YTPU_NET_*`` knobs are documented in README
"Replication & sessions".
"""

from __future__ import annotations

import itertools
import os
import random

from ..lib0 import decoding, encoding
from ..lib0.decoding import Decoder
from ..lib0.encoding import Encoder
from ..obs import global_registry
from ..obs.blackbox import flight_recorder
from ..obs.dist import (
    TraceContext,
    current_context,
    mint_for_update,
    trace_metrics,
    use_context,
)
from ..updates import (
    apply_update,
    decode_state_vector,
    encode_state_as_update,
    encode_state_vector,
)
from . import protocol

# the envelope message type: any varint the plain protocol does not
# know is skipped-and-counted by read_sync_message (PR 2 made that
# tolerance a contract), so plain peers survive our control frames and
# we detect them by their bare step-1 — that IS the negotiation
MESSAGE_YTPU_SESSION = 121

K_HELLO = 0
K_WELCOME = 1
K_DATA = 2
K_ACK = 3
K_PING = 4
K_PONG = 5
K_DIGEST = 6
# cooperative backpressure (ISSUE 10): "back off for N ticks".  Sent
# when a session enters lagging (before more frames would be shed) and
# as the admission layer's reply to a rejected write.  Plain
# y-protocols peers skip the whole envelope; enhanced peers coalesce
# their sends into one pending delta until the window passes.
K_BUSY = 7

_KIND_NAMES = {
    K_HELLO: "hello",
    K_WELCOME: "welcome",
    K_DATA: "data",
    K_ACK: "ack",
    K_PING: "ping",
    K_PONG: "pong",
    K_DIGEST: "digest",
    K_BUSY: "busy",
}

CONNECTING = "connecting"
SYNCING = "syncing"
LIVE = "live"
LAGGING = "lagging"
RECONNECTING = "reconnecting"
CLOSED = "closed"

STATES = (CONNECTING, SYNCING, LIVE, LAGGING, RECONNECTING, CLOSED)

# session ids are process-local instance handles (never persisted as
# identity, only echoed back for resume matching); 0 means "none"
_SID = itertools.count(1)

# an empty V1 update (0 client struct-lists + empty delete set) — a
# diff at or below this size carries nothing and is not worth a frame
_EMPTY_UPDATE_LEN = 2


def encode_busy(retry_after: int) -> bytes:
    """One BUSY envelope frame: ``121 | K_BUSY | varint retry_after``.
    Module-level (not a session method) because the provider's
    admission seam emits it as a ``handle_sync_message`` reply without
    owning a session object."""
    enc = Encoder()
    encoding.write_var_uint(enc, MESSAGE_YTPU_SESSION)
    encoding.write_var_uint(enc, K_BUSY)
    encoding.write_var_uint(enc, max(1, int(retry_after)))
    return enc.to_bytes()


def _env_int(name: str, default: int, lo: int = 0,
             hi: int = 1 << 30) -> int:
    try:
        return max(lo, min(hi, int(os.environ.get(name, default))))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return max(0.0, float(os.environ.get(name, default)))
    except (TypeError, ValueError):
        return default


class SessionConfig:
    """Knobs, env-defaulted (``YTPU_NET_*``), ticks unless noted.

    - ``retry_base`` / ``retry_cap``: exponential backoff window for
      unacked DATA frames (``YTPU_NET_RETRY_BASE`` default 2,
      ``YTPU_NET_RETRY_CAP`` default 64).
    - ``retry_max``: retransmit attempts before the frame's payload is
      dead-lettered through the host (``YTPU_NET_RETRY_MAX`` default 8;
      anti-entropy then owns the repair).
    - ``retry_jitter``: fractional jitter on each backoff
      (``YTPU_NET_RETRY_JITTER`` default 0.25, deterministic per
      session seed).
    - ``outbox_high`` / ``outbox_low``: backpressure watermarks on the
      per-peer outbox (``YTPU_NET_OUTBOX_HIGH`` default 256,
      ``YTPU_NET_OUTBOX_LOW`` default 64).
    - ``heartbeat``: idle ticks before a PING (``YTPU_NET_HEARTBEAT``
      default 8; 0 disables).
    - ``liveness``: ticks without ANY inbound frame before the
      transport is declared dead (``YTPU_NET_LIVENESS`` default 32;
      0 disables).
    - ``antientropy``: ticks between state-vector digests in ``live``
      (``YTPU_NET_ANTIENTROPY`` default 16; 0 disables).
    - ``hello_timeout``: ticks in ``connecting`` before falling back to
      a bare plain-protocol step 1 for peers that never initiate
      (``YTPU_NET_HELLO_TIMEOUT`` default 4; 0 disables).
    - ``busy_retry``: retry-after ticks carried by the BUSY frame a
      lagging session sends before shedding more frames
      (``YTPU_NET_BUSY_RETRY`` default 4; 0 disables sending — BUSY
      frames are still honored on receive).
    """

    __slots__ = ("retry_base", "retry_cap", "retry_max", "retry_jitter",
                 "outbox_high", "outbox_low", "heartbeat", "liveness",
                 "antientropy", "hello_timeout", "busy_retry", "seed")

    def __init__(
        self,
        retry_base: int | None = None,
        retry_cap: int | None = None,
        retry_max: int | None = None,
        retry_jitter: float | None = None,
        outbox_high: int | None = None,
        outbox_low: int | None = None,
        heartbeat: int | None = None,
        liveness: int | None = None,
        antientropy: int | None = None,
        hello_timeout: int | None = None,
        busy_retry: int | None = None,
        seed: int = 0,
    ):
        def pick(v, name, default, lo=0):
            return v if v is not None else _env_int(name, default, lo)

        self.retry_base = pick(retry_base, "YTPU_NET_RETRY_BASE", 2, 1)
        self.retry_cap = pick(retry_cap, "YTPU_NET_RETRY_CAP", 64, 1)
        self.retry_max = pick(retry_max, "YTPU_NET_RETRY_MAX", 8, 1)
        self.retry_jitter = (
            retry_jitter
            if retry_jitter is not None
            else _env_float("YTPU_NET_RETRY_JITTER", 0.25)
        )
        self.outbox_high = pick(outbox_high, "YTPU_NET_OUTBOX_HIGH", 256, 1)
        self.outbox_low = pick(outbox_low, "YTPU_NET_OUTBOX_LOW", 64, 0)
        self.heartbeat = pick(heartbeat, "YTPU_NET_HEARTBEAT", 8)
        self.liveness = pick(liveness, "YTPU_NET_LIVENESS", 32)
        self.antientropy = pick(antientropy, "YTPU_NET_ANTIENTROPY", 16)
        self.hello_timeout = pick(
            hello_timeout, "YTPU_NET_HELLO_TIMEOUT", 4
        )
        self.busy_retry = pick(busy_retry, "YTPU_NET_BUSY_RETRY", 4)
        self.seed = seed

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class SessionMetrics:
    """The ``ytpu_net_*`` metric families (registered once per
    registry; provider construction registers them unconditionally so
    exposition and the schema checker see the full surface)."""

    def __init__(self, registry=None):
        r = registry if registry is not None else global_registry()
        self.sessions = r.gauge(
            "ytpu_net_sessions",
            "Live peer sessions by state",
            labelnames=("state",),
        )
        self.frames = r.counter(
            "ytpu_net_frames_total",
            "Session frames by direction and envelope kind (plain-"
            "protocol passthrough counts as kind=plain)",
            labelnames=("dir", "kind"),
        )
        self.retransmits = r.counter(
            "ytpu_net_retransmits_total",
            "DATA frames retransmitted after backoff expiry",
        )
        self.acks = r.counter(
            "ytpu_net_acks_total",
            "Cumulative-ack frames processed",
        )
        self.resumes = r.counter(
            "ytpu_net_resumes_total",
            "Reconnect handshakes resumed via delta catch-up (no full "
            "resync)",
        )
        self.full_resyncs = r.counter(
            "ytpu_net_full_resyncs_total",
            "Handshakes that established a fresh session (initial "
            "connect, or resume state lost)",
        )
        self.repairs = r.counter(
            "ytpu_net_antientropy_repairs_total",
            "Targeted diffs sent because a digest exposed peer "
            "divergence",
        )
        self.rounds = r.counter(
            "ytpu_net_antientropy_rounds_total",
            "State-vector digests initiated by the repair loop",
        )
        self.coalesced = r.counter(
            "ytpu_net_coalesced_updates_total",
            "Updates folded into a pending delta instead of queueing "
            "(backpressure / pre-sync buffering)",
        )
        self.shed = r.counter(
            "ytpu_net_shed_frames_total",
            "Queued-but-unsent outbox frames dropped when entering "
            "lagging (superseded by the coalesced delta)",
        )
        self.dead_lettered = r.counter(
            "ytpu_net_dead_lettered_total",
            "DATA payloads dead-lettered after the retransmit cap",
        )
        self.heartbeats = r.counter(
            "ytpu_net_heartbeats_total",
            "PING/PONG liveness frames",
            labelnames=("dir",),
        )
        self.liveness_timeouts = r.counter(
            "ytpu_net_liveness_timeouts_total",
            "Sessions declared dead after the liveness window",
        )
        self.negotiated_down = r.counter(
            "ytpu_net_negotiated_down_total",
            "Sessions that fell back to the plain y-protocols flow "
            "(peer never spoke envelope)",
        )
        self.outbox_depth = r.gauge(
            "ytpu_net_outbox_depth",
            "Deepest per-peer outbox across the session fleet "
            "(refreshed on tick/snapshot)",
        )
        self.busy_backoffs = r.counter(
            "ytpu_net_busy_backoffs_total",
            "BUSY/retry-after frames honored (sends coalesced until "
            "the advertised window passed)",
        )

    def set_state_gauges(self, sessions) -> None:
        counts = {s: 0 for s in STATES}
        deepest = 0
        for sess in sessions:
            counts[sess.state] = counts.get(sess.state, 0) + 1
            deepest = max(deepest, len(sess._outbox))
        for state, n in counts.items():
            self.sessions.labels(state=state).set(n)
        self.outbox_depth.set(deepest)


class DocSessionHost:
    """Session host over a CPU :class:`yjs_tpu.core.Doc` — the seam a
    :class:`SyncSession` drives (``TpuProvider`` rooms use
    :class:`yjs_tpu.provider._ProviderSessionHost`, same shape).

    ``slo`` (optional :class:`yjs_tpu.obs.slo.ConvergenceTracker`)
    stamps the receive/integrate/visible stages on every applied inner
    frame — the session layer inherits PR 4's convergence SLOs with
    zero wire changes."""

    def __init__(self, doc, origin=None, slo=None):
        self.doc = doc
        self.origin = origin if origin is not None else self
        self.slo = slo
        self.dead_letters: list[tuple[bytes, str]] = []

    def state_vector(self) -> bytes:
        return encode_state_vector(self.doc)

    def diff_update(self, sv: bytes | None) -> bytes:
        return encode_state_as_update(self.doc, sv)

    def apply_update(self, update: bytes) -> None:
        apply_update(self.doc, update, self.origin)

    def handle_frame(self, frame: bytes) -> bytes | None:
        dec = Decoder(frame)
        enc = Encoder()
        protocol.read_sync_message(
            dec, enc, self.doc, self.origin, slo=self.slo
        )
        out = enc.to_bytes()
        return out or None

    def dead_letter(self, payload: bytes, reason: str) -> None:
        self.dead_letters.append((bytes(payload), reason))

    def journal_ack(self, sid: int, seq: int) -> None:
        pass  # durable ack floors are a provider concern (WAL)


class SyncSession:
    """One peer's session state machine (see module docstring).

    Not thread-safe: the owner serializes :meth:`tick`, transport
    callbacks, and :meth:`send_update` (``examples/socket_connector.py``
    shows the lock discipline for a threaded transport).
    """

    def __init__(
        self,
        host,
        config: SessionConfig | None = None,
        metrics: SessionMetrics | None = None,
        peer: str = "peer",
    ):
        self.host = host
        self.config = config if config is not None else SessionConfig()
        self.metrics = metrics if metrics is not None else SessionMetrics()
        self.peer = peer
        self.sid = next(_SID)
        self.state = CLOSED  # no transport yet; attach() arms it
        self._closed = False  # set by close(); CLOSED-state alone just
        # means "not attached yet" (registries must not discard those)
        self.transport = None
        self.plain_mode = False
        self._peer_enhanced = False
        self._rng = random.Random((self.config.seed << 8) ^ self.sid)
        # anti-entropy jitter (ISSUE 17): per-peer seeded stream, kept
        # SEPARATE from the retransmit-backoff RNG so adding digest
        # jitter never perturbs the pinned backoff sequences.  Same
        # keyed-stream pattern as the failover FailureDetector; spreads
        # N links' digests so a partition heal doesn't fire one
        # synchronized digest storm across every WAN link at once.
        # Keyed by the stable peer label, NOT the process-global sid:
        # sids depend on how many sessions existed before this one, so
        # a sid-keyed stream would make same-seed replays within one
        # process diverge.
        self._ae_rng = random.Random(f"ae:{self.config.seed}:{self.peer}")
        self._ae_jitter = 0

        # clocks (ticks)
        self._tick = 0
        self._attached_at = 0
        self._last_recv = 0
        self._last_send = 0
        self._last_ack = 0
        self._last_digest = 0

        # send side: seq-numbered outbox of unacked DATA frames
        self._send_seq = 0
        self._outbox: list[dict] = []
        self._pending_delta = False

        # admission policy (ISSUE 10): the owning provider/fleet's
        # AdmissionController, read dynamically for its brownout flags
        # (force_coalesce, antientropy_paused); None for client-side
        # sessions.  _busy_until is the peer-advertised backoff window.
        self.policy = None
        self._busy_until = 0
        self.n_busy_backoffs = 0

        # receive side: cumulative ack + out-of-order window
        self._peer_sid = 0
        self._recv_cum = 0
        self._recv_seen: set[int] = set()
        self._peer_sv: bytes | None = None

        # resume hint for sessions rebuilt from WAL recovery: HELLO
        # claims this (peer sid, recv floor) so the surviving peer
        # resumes retransmission instead of a full resync
        self._resume_hint: tuple[int, int] | None = None

        # fleet routing-table epoch this session last re-homed at
        # (ISSUE 6); 0 = never owned by a fleet
        self.routing_epoch = 0

        # per-epoch handshake bookkeeping
        self._hs_counted = False
        self._hs_diff_sent = False
        self._hs_seq_settled = False
        self._sent_plain_step1 = False
        # HELLO is retried on its own backoff — a lossy link that eats
        # the first frame must not wedge the session in "connecting"
        self._hello_attempts = 0
        self._next_hello = 0

        # per-session stats (metrics are fleet-wide; snapshots need
        # per-peer numbers and must survive YTPU_OBS_DISABLED)
        self.n_sent = 0
        self.n_received = 0
        self.n_retransmits = 0
        self.n_resumes = 0
        self.n_full_resyncs = 0
        self.n_repairs = 0
        self.n_coalesced = 0
        self.n_shed = 0
        self.n_dead_lettered = 0
        self.n_liveness_timeouts = 0

        self.on_state_change = None  # callable(session, old, new)

    # -- lifecycle -----------------------------------------------------------

    def _set_state(self, new: str) -> None:
        old = self.state
        if old == new:
            return
        self.state = new
        if self.on_state_change is not None:
            self.on_state_change(self, old, new)

    def connect(self, transport) -> None:
        """First attach + handshake kick-off."""
        self.attach(transport)

    def attach(self, transport) -> None:
        """Bind a (new) transport and start a handshake epoch.  All
        resume state — seq spaces, outbox, peer identity — carries
        over, so a reconnect replays deltas instead of full state."""
        if self._closed:
            raise RuntimeError("session is closed")
        self.transport = transport
        transport.on_frame = self._on_transport_frame
        transport.on_close = self._on_transport_close
        self._attached_at = self._tick
        self._last_recv = self._tick
        self._hs_counted = False
        self._hs_diff_sent = False
        self._hs_seq_settled = False
        self._sent_plain_step1 = False
        self._hello_attempts = 0
        self._set_state(CONNECTING)
        if self._resume_hint is not None and self._peer_sid == 0:
            self._peer_sid, self._recv_cum = self._resume_hint
        self._send_hello()
        # everything already in the outbox predates this transport:
        # schedule an immediate retransmit pass once the handshake
        # settles (marked here; _on_welcome/_on_hello prune first)
        for e in self._outbox:
            e["next_retry"] = self._tick

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._set_state(CLOSED)
        t, self.transport = self.transport, None
        if t is not None:
            t.on_close = None
            t.close()

    def _on_transport_close(self) -> None:
        self._transport_lost()

    def _transport_lost(self) -> None:
        if self.state in (CLOSED, RECONNECTING):
            return
        t, self.transport = self.transport, None
        if t is not None:
            t.on_close = None
            t.close()
        self._set_state(RECONNECTING)

    # -- wire helpers --------------------------------------------------------

    def _send_frame(self, frame: bytes, kind: str) -> bool:
        t = self.transport
        if t is None:
            return False
        ok = t.send(frame)
        if not ok:
            self._transport_lost()
            return False
        self._last_send = self._tick
        self.metrics.frames.labels(dir="send", kind=kind).inc()
        return True

    def _envelope(self, kind: int) -> Encoder:
        enc = Encoder()
        encoding.write_var_uint(enc, MESSAGE_YTPU_SESSION)
        encoding.write_var_uint(enc, kind)
        return enc

    def _send_hello(self) -> None:
        self._hello_attempts += 1
        self._next_hello = self._tick + self._backoff(
            min(self._hello_attempts, 8)
        )
        enc = self._envelope(K_HELLO)
        encoding.write_var_uint(enc, self.sid)
        encoding.write_var_uint(enc, self._peer_sid)
        encoding.write_var_uint(enc, self._recv_cum)
        encoding.write_var_uint8_array(enc, self.host.state_vector())
        self._send_frame(enc.to_bytes(), "hello")

    def _send_welcome(self, resumed: bool) -> None:
        enc = self._envelope(K_WELCOME)
        encoding.write_var_uint(enc, self.sid)
        encoding.write_var_uint(enc, 1 if resumed else 0)
        encoding.write_var_uint(enc, self._recv_cum)
        encoding.write_var_uint8_array(enc, self.host.state_vector())
        self._send_frame(enc.to_bytes(), "welcome")

    def _send_ack(self) -> None:
        enc = self._envelope(K_ACK)
        encoding.write_var_uint(enc, self._recv_cum)
        self._send_frame(enc.to_bytes(), "ack")

    def _send_digest(self) -> None:
        enc = self._envelope(K_DIGEST)
        encoding.write_var_uint8_array(enc, self.host.state_vector())
        self._last_digest = self._tick
        # re-draw the next interval's jitter (0..antientropy/4 ticks)
        # so consecutive digests desynchronize across sessions even
        # when they were armed on the same tick (partition heal)
        span = max(1, self.config.antientropy // 4)
        self._ae_jitter = self._ae_rng.randrange(span + 1)
        self.metrics.rounds.inc()
        self._send_frame(enc.to_bytes(), "digest")

    def _send_busy(self, retry_after: int) -> None:
        self._send_frame(encode_busy(retry_after), "busy")

    def _on_busy(self, dec: Decoder) -> None:
        retry = decoding.read_var_uint(dec)
        until = self._tick + max(1, int(retry))
        if until > self._busy_until:
            self._busy_until = until
        self.n_busy_backoffs += 1
        self.metrics.busy_backoffs.inc()

    def _data_frame(self, seq: int, inner: bytes,
                    trace: TraceContext | None = None) -> bytes:
        """``121 | K_DATA | varint seq | varint8array inner`` plus — for
        a SAMPLED trace context (ISSUE 11) — one trailing varint8array
        carrying the 25-byte trace blob.  Pre-PR readers decode only
        seq + inner and never touch trailing decoder bytes; stock
        y-protocols v13.4.9 readers skip the whole unknown type-121
        message — zero wire change either way.  Unsampled traffic omits
        the key entirely, so the absent path is exercised routinely."""
        enc = self._envelope(K_DATA)
        encoding.write_var_uint(enc, seq)
        encoding.write_var_uint8_array(enc, inner)
        if trace is not None and trace.sampled:
            encoding.write_var_uint8_array(enc, trace.to_bytes())
            trace_metrics().carried.labels(dir="send").inc()
        return enc.to_bytes()

    def _queue_data(self, inner: bytes,
                    trace: TraceContext | None = None) -> None:
        """Seq-number one inner frame, queue for ack tracking, send.
        The trace context is stored on the outbox entry so retransmits
        re-carry the SAME causal identity."""
        if trace is None:
            trace = current_context()
        self._send_seq += 1
        entry = {
            "seq": self._send_seq,
            "inner": inner,
            "attempts": 0,
            "next_retry": self._tick + self._backoff(1),
            "sent": False,
            "trace": trace,
        }
        self._outbox.append(entry)
        entry["sent"] = self._send_frame(
            self._data_frame(entry["seq"], inner, trace), "data"
        )
        self.n_sent += 1

    def _backoff(self, attempts: int) -> int:
        cfg = self.config
        base = min(cfg.retry_cap, cfg.retry_base * (1 << (attempts - 1)))
        jitter = 1.0 + cfg.retry_jitter * self._rng.random()
        return max(1, int(base * jitter))

    # -- outbound updates ----------------------------------------------------

    def send_update(self, update: bytes) -> None:
        """Ship one local update to the peer.

        Live sessions send a seq-numbered DATA frame.  Under
        backpressure (outbox at the high watermark) or before the
        handshake settles, the update is NOT queued — it is coalesced
        into one pending delta served from the host's current state,
        preferring shed intermediates over a disconnect."""
        if self.state == CLOSED:
            return
        if self.plain_mode:
            enc = Encoder()
            protocol.write_update(enc, update)
            self._send_frame(enc.to_bytes(), "plain")
            self.n_sent += 1
            return
        if self.state in (CONNECTING, SYNCING, RECONNECTING):
            self._pending_delta = True
            self.n_coalesced += 1
            self.metrics.coalesced.inc()
            return
        pol = self.policy
        if self._tick < self._busy_until or (
            pol is not None and getattr(pol, "force_coalesce", False)
        ):
            # peer asked us to back off (BUSY) or the brownout level
            # forces lagging-style coalescing: fold into the pending
            # delta, flushed by tick() once the window allows
            self._pending_delta = True
            self.n_coalesced += 1
            self.metrics.coalesced.inc()
            return
        if self.state == LAGGING or len(self._outbox) >= self.config.outbox_high:
            self._enter_lagging()
            self._pending_delta = True
            self.n_coalesced += 1
            self.metrics.coalesced.inc()
            return
        inner = Encoder()
        protocol.write_update(inner, update)
        # the trace is minted from the RAW update bytes (not the framed
        # inner), matching what a receiving provider would mint for the
        # same payload — carried and minted identities agree (ISSUE 11)
        self._queue_data(
            inner.to_bytes(),
            trace=current_context() or mint_for_update(update),
        )

    def _enter_lagging(self) -> None:
        if self.state == LAGGING:
            return
        # cooperative backpressure first: tell the peer to back off
        # BEFORE frames start shedding, so a well-behaved sender
        # coalesces at its end instead of flooding a lagging link.
        # Gated on an admission policy being live — without one the
        # wire behavior is exactly the pre-ISSUE-10 protocol.
        pol = self.policy
        if (
            self.config.busy_retry
            and not self.plain_mode
            and pol is not None
            and getattr(pol, "enabled", False)
        ):
            self._send_busy(self.config.busy_retry)
        # shed queued-but-never-sent frames: the coalesced delta
        # supersedes them (sent-once frames stay for ack accounting —
        # the peer may already hold them)
        kept = []
        for e in self._outbox:
            if e["sent"]:
                kept.append(e)
            else:
                self.n_shed += 1
                self.metrics.shed.inc()
        self._outbox = kept
        self._set_state(LAGGING)

    def _maybe_flush_delta(self) -> None:
        """Send the coalesced catch-up delta once the peer can absorb
        it (post-handshake, or outbox drained below the low mark)."""
        if not self._pending_delta or self.plain_mode:
            return
        if self.state not in (LIVE, LAGGING):
            return
        if self._tick < self._busy_until:
            return  # peer asked us to hold off; tick() flushes later
        if len(self._outbox) > self.config.outbox_low:
            return
        self._pending_delta = False
        diff = self.host.diff_update(self._peer_sv)
        if len(diff) > _EMPTY_UPDATE_LEN:
            inner = Encoder()
            protocol.write_update(inner, diff)
            self._queue_data(inner.to_bytes())
        if self.state == LAGGING:
            self._set_state(LIVE)

    # -- handshake -----------------------------------------------------------

    def _reset_recv(self, peer_sid: int) -> None:
        self._peer_sid = peer_sid
        self._recv_cum = 0
        self._recv_seen.clear()

    def _reset_send(self) -> None:
        self._send_seq = 0
        self._outbox = []

    def _count_handshake(self, resumed: bool) -> None:
        if self._hs_counted:
            return
        self._hs_counted = True
        if resumed:
            self.n_resumes += 1
            self.metrics.resumes.inc()
        else:
            self.n_full_resyncs += 1
            self.metrics.full_resyncs.inc()

    def _finish_handshake(self) -> None:
        if self.state in (CONNECTING, RECONNECTING):
            self._set_state(SYNCING)
        if not self._hs_diff_sent:
            self._hs_diff_sent = True
            diff = self.host.diff_update(self._peer_sv)
            if len(diff) > _EMPTY_UPDATE_LEN:
                inner = Encoder()
                protocol.write_update(inner, diff)
                self._queue_data(inner.to_bytes())
        if self.state == SYNCING and not self._outbox:
            self._set_state(LIVE)
            self._maybe_flush_delta()

    def _on_hello(self, dec: Decoder) -> None:
        sid = decoding.read_var_uint(dec)
        resume_sid = decoding.read_var_uint(dec)
        resume_seq = decoding.read_var_uint(dec)
        self._peer_sv = decoding.read_var_uint8_array(dec)
        self._peer_enhanced = True
        self.plain_mode = False
        # the two directions resume INDEPENDENTLY.  `resumed` judges
        # the peer's claim about MY send stream; `recv_resumed` is my
        # own receive-side continuity for the PEER's stream — true when
        # the HELLO names the sid my receive floor belongs to (a live
        # floor, or one re-armed from a journaled WAL record).  The
        # WELCOME must carry `recv_resumed`: it is what tells the peer
        # to prune-and-retransmit instead of restarting its seq space,
        # and conflating it with `resumed` makes a recovered region's
        # peers full-resync whenever the WELCOME races ahead of the
        # recovered side's own HELLO (reordered or lossy WAN links).
        recv_resumed = sid == self._peer_sid and sid != 0
        if sid != self._peer_sid:
            # a new peer instance: its receive history died with it
            self._reset_recv(sid)
        resumed = resume_sid == self.sid
        if not self._hs_seq_settled:
            # settle the send-side seq space ONCE per epoch: HELLO and
            # WELCOME both carry the verdict and both arrive — a second
            # reset would recycle seqs the peer has already seen
            self._hs_seq_settled = True
            if resumed:
                # the peer holds everything up to resume_seq from THIS
                # session: prune, then retransmit the survivors now
                self._drop_acked(resume_seq)
                for e in self._outbox:
                    e["next_retry"] = self._tick
            else:
                # peer has no memory of our frames: restart the seq
                # space (the handshake delta below carries all history)
                self._reset_send()
        # classify as a resume only when a prior handshake completed —
        # a duplicate HELLO inside a lossy INITIAL handshake names a
        # sid we already learned, which is continuity on the wire but
        # not a resumed session
        self._count_handshake(
            resumed and (self.n_resumes + self.n_full_resyncs) > 0
        )
        self._send_welcome(recv_resumed)
        self._finish_handshake()

    def _on_welcome(self, dec: Decoder) -> None:
        sid = decoding.read_var_uint(dec)
        resumed = bool(decoding.read_var_uint(dec))
        recv_seq = decoding.read_var_uint(dec)
        self._peer_sv = decoding.read_var_uint8_array(dec)
        self._peer_enhanced = True
        self.plain_mode = False
        if sid != self._peer_sid:
            self._reset_recv(sid)
        if not self._hs_seq_settled:
            self._hs_seq_settled = True
            if resumed:
                self._drop_acked(recv_seq)
                for e in self._outbox:
                    e["next_retry"] = self._tick
            else:
                self._reset_send()
        self._count_handshake(
            resumed and (self.n_resumes + self.n_full_resyncs) > 0
        )
        self._finish_handshake()

    # -- data / ack ----------------------------------------------------------

    def _drop_acked(self, cum: int) -> None:
        if self._outbox:
            self._outbox = [e for e in self._outbox if e["seq"] > cum]

    def _on_data(self, dec: Decoder) -> None:
        seq = decoding.read_var_uint(dec)
        inner = decoding.read_var_uint8_array(dec)
        # optional trailing trace-context key (ISSUE 11): absent on
        # unsampled traffic and on frames from pre-PR senders; any
        # parse trouble degrades to "no context" — never to a dead
        # frame (the inner payload was already read intact)
        ctx = None
        try:
            if dec.has_content():
                ctx = TraceContext.from_bytes(
                    decoding.read_var_uint8_array(dec)
                )
        except Exception:
            ctx = None
        if ctx is not None:
            trace_metrics().carried.labels(dir="recv").inc()
        if seq <= self._recv_cum or seq in self._recv_seen:
            self._send_ack()  # duplicate: the peer missed our ack
            return
        self.n_received += 1
        with use_context(ctx):
            reply = self.host.handle_frame(bytes(inner))
        if reply is not None and reply[0] == MESSAGE_YTPU_SESSION:
            # an envelope reply (admission BUSY) means the host REFUSED
            # this frame — it was neither applied nor journaled.  Leave
            # the seq un-acked so the peer keeps it in its outbox and
            # retransmits once its backoff expires; acking a rejected
            # update would silently lose it.
            self.n_received -= 1
            self._send_frame(reply, "busy")
            return
        self._recv_seen.add(seq)
        while (self._recv_cum + 1) in self._recv_seen:
            self._recv_cum += 1
            self._recv_seen.discard(self._recv_cum)
        self._send_ack()
        self.host.journal_ack(self._peer_sid, self._recv_cum)
        if reply is not None:
            if self.state in (LIVE, SYNCING, LAGGING):
                self._queue_data(reply)
            else:
                self._send_frame(reply, "plain")

    def _on_ack(self, dec: Decoder) -> None:
        cum = decoding.read_var_uint(dec)
        self.metrics.acks.inc()
        self._last_ack = self._tick
        self._drop_acked(cum)
        if self.state == SYNCING and not self._outbox:
            self._set_state(LIVE)
        if len(self._outbox) <= self.config.outbox_low:
            self._maybe_flush_delta()

    def _on_digest(self, dec: Decoder) -> None:
        peer_sv = decoding.read_var_uint8_array(dec)
        self._peer_sv = peer_sv
        pol = self.policy
        if pol is not None and getattr(pol, "antientropy_paused", False):
            # shed-background: answering repairs is exactly the
            # expensive diff work this level exists to shed; the peer's
            # own digest loop retries once the brownout lifts
            return
        mine = decode_state_vector(self.host.state_vector())
        theirs = decode_state_vector(bytes(peer_sv))
        ahead = any(
            clock > theirs.get(client, 0) for client, clock in mine.items()
        )
        behind = any(
            clock > mine.get(client, 0) for client, clock in theirs.items()
        )
        if ahead:
            # silent divergence detected: targeted repair diff
            diff = self.host.diff_update(bytes(peer_sv))
            if len(diff) > _EMPTY_UPDATE_LEN:
                self.n_repairs += 1
                self.metrics.repairs.inc()
                inner = Encoder()
                protocol.write_update(inner, diff)
                self._queue_data(inner.to_bytes())
        if behind and self._tick - self._last_digest >= 2:
            # solicit the peer's repair path without a digest storm
            self._send_digest()

    # -- inbound dispatch ----------------------------------------------------

    def _on_transport_frame(self, frame: bytes) -> None:
        if self.state == CLOSED or not frame:
            return
        self._last_recv = self._tick
        try:
            dec = Decoder(frame)
            mtype = decoding.read_var_uint(dec)
        except Exception:
            self.host.dead_letter(frame, "net-bad-frame")
            return
        if mtype != MESSAGE_YTPU_SESSION:
            self.metrics.frames.labels(dir="recv", kind="plain").inc()
            self._on_plain_frame(frame)
            return
        try:
            kind = decoding.read_var_uint(dec)
        except Exception:
            self.host.dead_letter(frame, "net-bad-envelope")
            return
        self.metrics.frames.labels(
            dir="recv", kind=_KIND_NAMES.get(kind, "unknown")
        ).inc()
        try:
            if kind == K_HELLO:
                self._on_hello(dec)
            elif kind == K_WELCOME:
                self._on_welcome(dec)
            elif kind == K_DATA:
                self._on_data(dec)
            elif kind == K_ACK:
                self._on_ack(dec)
            elif kind == K_PING:
                self.metrics.heartbeats.labels(dir="recv").inc()
                self._send_frame(self._envelope(K_PONG).to_bytes(), "pong")
            elif kind == K_PONG:
                self.metrics.heartbeats.labels(dir="recv").inc()
            elif kind == K_DIGEST:
                self._on_digest(dec)
            elif kind == K_BUSY:
                self._on_busy(dec)
            # unknown envelope kinds: a newer revision — skip (the
            # same tolerance contract as the plain frame reader)
        except Exception as e:
            self.host.dead_letter(
                frame, f"net-envelope: {type(e).__name__}: {e}"
            )

    def _on_plain_frame(self, frame: bytes) -> None:
        """A bare y-protocols frame: the peer speaks the plain
        protocol (or our own fallback step 1 crossed a slow HELLO).
        Negotiate down — acks/retransmit/heartbeats all require the
        envelope; plain mode is pure passthrough."""
        if not self._peer_enhanced and not self.plain_mode:
            self.plain_mode = True
            self.metrics.negotiated_down.inc()
        reply = self.host.handle_frame(frame)
        self.n_received += 1
        if self.plain_mode:
            if not self._sent_plain_step1:
                self._sent_plain_step1 = True
                enc = Encoder()
                encoding.write_var_uint(
                    enc, protocol.MESSAGE_YJS_SYNC_STEP_1
                )
                encoding.write_var_uint8_array(
                    enc, self.host.state_vector()
                )
                self._send_frame(enc.to_bytes(), "plain")
            if reply is not None:
                self._send_frame(reply, "plain")
            if self.state in (CONNECTING, SYNCING):
                self._count_handshake(False)
                self._set_state(LIVE)
        elif reply is not None:
            if reply[0] == MESSAGE_YTPU_SESSION:
                self._send_frame(reply, "busy")
            else:
                # enhanced peer sent a stray bare frame: answer in kind
                self._queue_data(reply)

    # -- the clock -----------------------------------------------------------

    def tick(self) -> None:
        """One unit of session time: drives retransmission backoff,
        the plain-protocol fallback, heartbeats, liveness, and the
        anti-entropy repair loop.  The owner calls this at its own
        cadence (a provider flush loop, a transport ticker thread)."""
        if self.state == CLOSED:
            return
        self._tick += 1
        cfg = self.config
        if self.state == RECONNECTING:
            return  # waiting on attach(); no wire to drive
        if self.plain_mode:
            return  # no envelope: nothing to retransmit or probe
        if (
            self.state == CONNECTING
            and cfg.hello_timeout
            and not self._sent_plain_step1
            and self._tick - self._attached_at >= cfg.hello_timeout
        ):
            # peer silent: maybe it is a plain server awaiting step 1
            self._sent_plain_step1 = True
            enc = Encoder()
            encoding.write_var_uint(enc, protocol.MESSAGE_YJS_SYNC_STEP_1)
            encoding.write_var_uint8_array(enc, self.host.state_vector())
            self._send_frame(enc.to_bytes(), "plain")
        # the handshake itself rides the lossy link: retry HELLO on
        # backoff until the peer answers (a plain peer skips the
        # envelope, so over-sending never hurts interop)
        if self.state == CONNECTING and self._tick >= self._next_hello:
            self._send_hello()
        # retransmission with exponential backoff + jitter; a BUSY
        # window pauses the whole pass (attempts included) — the server
        # asked us to hold, so burning the retry budget against its
        # admission gate would dead-letter frames it WILL take later
        if (
            self.state in (SYNCING, LIVE, LAGGING)
            and self._outbox
            and self._tick >= self._busy_until
        ):
            expired = []
            for e in self._outbox:
                if e["next_retry"] > self._tick:
                    continue
                e["attempts"] += 1
                if e["attempts"] > cfg.retry_max:
                    expired.append(e)
                    continue
                e["next_retry"] = self._tick + self._backoff(e["attempts"])
                if self._send_frame(
                    self._data_frame(e["seq"], e["inner"], e.get("trace")),
                    "data",
                ):
                    e["sent"] = True
                    self.n_retransmits += 1
                    self.metrics.retransmits.inc()
                else:
                    return  # transport died mid-pass
            if expired:
                dead = {e["seq"] for e in expired}
                self._outbox = [
                    e for e in self._outbox if e["seq"] not in dead
                ]
                for e in expired:
                    self.n_dead_lettered += 1
                    self.metrics.dead_lettered.inc()
                    # a retry-capped frame is an acked-loss near-miss on
                    # a WAN link: force-sample the frame's own trace so
                    # the drop is always visible in Perfetto/blackbox
                    # even at production sampling rates, then dead-letter
                    # under that context so the DLQ seam sees it too
                    ctx = e.get("trace")
                    if ctx is not None:
                        ctx = ctx.force("geo-retry-cap")
                    flight_recorder().record(
                        "session", "retry_cap_dead_letter",
                        severity="warning",
                        trace=(None if ctx is None else ctx.trace_hex),
                        peer=self.peer, seq=e["seq"], state=self.state,
                        attempts=e["attempts"],
                    )
                    with use_context(ctx):
                        self.host.dead_letter(
                            e["inner"],
                            f"net-retry-exhausted: seq {e['seq']} after "
                            f"{cfg.retry_max} attempts",
                        )
                # the peer never confirmed those frames: let the
                # anti-entropy loop close the gap promptly
                self._last_digest = min(
                    self._last_digest, self._tick - cfg.antientropy
                )
                # a WAN storm can dead-letter the ENTIRE initial sync;
                # syncing -> live otherwise fires only on send/ack
                # success, and anti-entropy is live-gated — without
                # this promotion the session wedges in syncing with
                # the healer that would close the gap never running
                if self.state == SYNCING and not self._outbox:
                    self._set_state(LIVE)
        # liveness: nothing heard for the whole window → transport dead
        if (
            cfg.liveness
            and self.state in (SYNCING, LIVE, LAGGING)
            and self._tick - self._last_recv >= cfg.liveness
        ):
            self.n_liveness_timeouts += 1
            self.metrics.liveness_timeouts.inc()
            self._transport_lost()
            return
        # busy/forced coalescing has no ack to trigger the delta flush:
        # drive it from the clock once the advertised window passes
        # (guarded to the ISSUE 10 paths so classic lagging recovery
        # stays ack-driven, byte-for-byte)
        pol = self.policy
        if (
            self._pending_delta
            and self.state in (LIVE, LAGGING)
            and self._tick >= self._busy_until
            and (
                self._busy_until
                or (pol is not None and getattr(pol, "force_coalesce", False))
            )
        ):
            self._maybe_flush_delta()
        # heartbeat: keep an idle link observably alive
        if (
            cfg.heartbeat
            and self.state == LIVE
            and self._tick - self._last_send >= cfg.heartbeat
        ):
            self.metrics.heartbeats.labels(dir="send").inc()
            self._send_frame(self._envelope(K_PING).to_bytes(), "ping")
        # anti-entropy: periodic digest exchange heals silent divergence
        # (paused under brownout — digest repair is background work the
        # shed-background level exists to shed)
        if (
            cfg.antientropy
            and self.state == LIVE
            and self._tick - self._last_digest >= cfg.antientropy + self._ae_jitter
            and not (
                pol is not None
                and getattr(pol, "antientropy_paused", False)
            )
        ):
            self._send_digest()

    # -- introspection -------------------------------------------------------

    @property
    def outbox_depth(self) -> int:
        return len(self._outbox)

    @property
    def last_ack_age(self) -> int:
        return self._tick - self._last_ack

    def rehome(self, epoch: int) -> None:
        """The host's routing epoch changed (fleet doc migration moved
        the room to another shard).  Seq spaces, outbox, and peer
        identity all survive — the host facade re-points transparently —
        but the handoff window may have raced a flush, so a live
        enhanced session immediately offers a state-vector digest: the
        anti-entropy loop then repairs any gap with a targeted diff
        instead of waiting out the ``antientropy`` interval."""
        self.routing_epoch = int(epoch)
        if (
            not self._closed
            and not self.plain_mode
            and self.transport is not None
            and self.state in (SYNCING, LIVE, LAGGING)
        ):
            self._send_digest()

    def set_resume_hint(self, peer_sid: int, recv_seq: int) -> None:
        """Arm a recovered session's HELLO with the journaled ack
        floor (see ``TpuProvider.recover``): the surviving peer then
        resumes retransmission past ``recv_seq`` instead of a full
        resync."""
        self._resume_hint = (int(peer_sid), int(recv_seq))

    @property
    def ack_floor(self) -> tuple[int, int]:
        """The receive floor this session would journal: ``(peer sid,
        cumulative seq received)``.  The fleet re-journals it onto a
        doc's NEW owner (migration destination, failover promotion) so
        the shard that answers the next handshake holds the floor and
        the peer resumes instead of full-resyncing."""
        return (self._peer_sid, self._recv_cum)

    def snapshot(self) -> dict:
        """JSON-able per-peer row (the ``sessions_snapshot()`` shape)."""
        return {
            "peer": self.peer,
            "sid": self.sid,
            "peer_sid": self._peer_sid,
            "state": self.state,
            "plain": self.plain_mode,
            "outbox_depth": len(self._outbox),
            "pending_delta": self._pending_delta,
            "send_seq": self._send_seq,
            "recv_cum": self._recv_cum,
            "last_ack_age": self.last_ack_age,
            "sent": self.n_sent,
            "received": self.n_received,
            "retransmits": self.n_retransmits,
            "resumes": self.n_resumes,
            "full_resyncs": self.n_full_resyncs,
            "repairs": self.n_repairs,
            "coalesced": self.n_coalesced,
            "shed": self.n_shed,
            "dead_lettered": self.n_dead_lettered,
            "liveness_timeouts": self.n_liveness_timeouts,
            "busy_backoffs": self.n_busy_backoffs,
            "busy_until": self._busy_until,
            "routing_epoch": self.routing_epoch,
            "tick": self._tick,
        }
