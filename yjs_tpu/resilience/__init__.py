"""yjs_tpu.resilience: failure isolation for the batched engine.

The reference survives a poison update trivially — each ``Y.Doc`` is an
isolated JS object and an exception stops at the doc boundary.  Our
struct-of-arrays batching shares fate across docs (SURVEY.md compound-
item batching), so one malformed byte in a 100k-doc flush used to raise
mid-``flush()`` and wedge the whole engine.  This package restores the
per-doc blast radius (ISSUE 2 tentpole):

- :mod:`.health` — per-doc ``healthy → degraded → quarantined`` state
  machine with exponential (flush-tick) backoff before re-admission;
- :mod:`.deadletter` — bounded dead-letter queue keeping rejected update
  bytes with reason + timestamp, replayable after a fix;
- :mod:`.chaos` — deterministic fault injectors: ``ChaosInjector``
  (corrupt / truncate / duplicate / reorder / drop) for the
  provider/protocol seams, driven by ``YTPU_CHAOS_*`` env knobs and
  used by the chaos test suite, ``DiskFaultInjector``
  (disk_tear / disk_bitflip) for WAL files in the crash-recovery
  harness (ISSUE 3), and ``NetworkFaultInjector``
  (net_drop / net_delay / net_dup / net_reorder / net_partition,
  ``YTPU_CHAOS_NET_*`` knobs) for the session transport seam
  (ISSUE 5).

The engine-side half (transactional per-doc flush isolation, rollback
via the ``_demote`` replay machinery) lives in
:meth:`yjs_tpu.ops.engine.BatchEngine._isolate_failure`; the validation
seam is :func:`yjs_tpu.updates.validate_update`.

Env knobs: ``YTPU_RESILIENCE_DISABLED=1`` (strict mode — failures raise
like the pre-resilience engine), ``YTPU_RESILIENCE_THRESHOLD``
(consecutive failures before quarantine, default 3),
``YTPU_RESILIENCE_BACKOFF`` (base backoff in flushes, default 4),
``YTPU_RESILIENCE_BACKOFF_CAP`` (max backoff in flushes, default 256),
``YTPU_RESILIENCE_RECOVERY`` (successes for degraded → healthy, default
2), ``YTPU_DLQ_MAX`` (dead-letter capacity, default 1024).
"""

from __future__ import annotations

from .chaos import (  # noqa: F401
    ChaosConfig,
    ChaosInjector,
    DiskFaultInjector,
    NetChaosConfig,
    NetworkFaultInjector,
)
from .deadletter import DeadLetter, DeadLetterQueue  # noqa: F401
from .health import (  # noqa: F401
    DEGRADED,
    HEALTHY,
    QUARANTINED,
    DocHealth,
    HealthTracker,
)
