"""Bounded dead-letter queue for rejected update bytes.

Every update the engine refuses to integrate — malformed bytes, CPU-apply
failures, traffic for a quarantined doc — lands here with its reason and
timestamp instead of being dropped, so operators can inspect what was
rejected and :meth:`~yjs_tpu.ops.engine.BatchEngine.replay_dead_letters`
it after a fix.  Capacity is bounded (``YTPU_DLQ_MAX``, default 1024
letters): at capacity the OLDEST letter is dropped and counted, so a
poison storm can never grow host memory without bound.
"""

from __future__ import annotations

import itertools
import os
import time
from collections import deque


class DeadLetter:
    """One rejected update: the exact bytes plus rejection context."""

    __slots__ = ("seq", "doc", "update", "v2", "reason", "ts")

    def __init__(self, seq: int, doc: int, update: bytes, v2: bool,
                 reason: str, ts: float):
        self.seq = seq
        self.doc = doc
        self.update = update
        self.v2 = v2
        self.reason = reason
        self.ts = ts

    def as_dict(self) -> dict:
        """JSON-able view (bytes reported as a length, not inlined)."""
        return {
            "seq": self.seq,
            "doc": self.doc,
            "bytes": len(self.update),
            "v2": self.v2,
            "reason": self.reason,
            "ts": self.ts,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"DeadLetter(seq={self.seq}, doc={self.doc}, "
            f"bytes={len(self.update)}, reason={self.reason!r})"
        )


class DeadLetterQueue:
    """FIFO ring of :class:`DeadLetter` with O(1) bounded append.

    ``total``/``dropped`` counters are kept here (independent of the obs
    registry) so the queue stays fully observable under
    ``YTPU_OBS_DISABLED=1``.
    """

    def __init__(self, maxlen: int | None = None):
        if maxlen is None:
            try:
                maxlen = int(os.environ.get("YTPU_DLQ_MAX", "1024"))
            except ValueError:
                maxlen = 1024
        self.maxlen = max(1, maxlen)
        self._q: deque[DeadLetter] = deque()
        self._seq = itertools.count()
        self.total = 0
        self.dropped = 0

    def append(self, doc: int, update: bytes, v2: bool, reason: str) -> DeadLetter:
        entry = DeadLetter(
            next(self._seq), doc, bytes(update), bool(v2), reason, time.time()
        )
        self._q.append(entry)
        self.total += 1
        if len(self._q) > self.maxlen:
            self._q.popleft()
            self.dropped += 1
        return entry

    def __len__(self) -> int:
        return len(self._q)

    def __iter__(self):
        return iter(list(self._q))

    def list(self, doc: int | None = None) -> list[DeadLetter]:
        """Letters oldest-first, optionally restricted to one doc."""
        if doc is None:
            return list(self._q)
        return [e for e in self._q if e.doc == doc]

    def take(
        self, doc: int | None = None, seqs=None, limit: int | None = None
    ) -> list[DeadLetter]:
        """Remove and return matching letters (oldest-first).

        ``doc`` restricts to one doc; ``seqs`` (an iterable of letter
        seq ids) restricts to specific letters.  Both None = drain all.
        ``limit`` caps how many matches are taken — excess matches stay
        queued (oldest taken first), so one replay invocation cannot
        stall a flush tick on an arbitrarily deep queue.
        """
        seq_set = None if seqs is None else set(seqs)
        taken: list[DeadLetter] = []
        kept: deque[DeadLetter] = deque()
        for e in self._q:
            if (
                (doc is None or e.doc == doc)
                and (seq_set is None or e.seq in seq_set)
                and (limit is None or len(taken) < limit)
            ):
                taken.append(e)
            else:
                kept.append(e)
        self._q = kept
        return taken

    def count_matching(self, doc: int | None = None, seqs=None) -> int:
        """Letters a ``take`` with the same filters would match."""
        seq_set = None if seqs is None else set(seqs)
        return sum(
            1
            for e in self._q
            if (doc is None or e.doc == doc)
            and (seq_set is None or e.seq in seq_set)
        )

    def snapshot(self, letters: bool = False) -> dict:
        """JSON-able summary for exposition/bench artifacts.

        ``letters=True`` additionally inlines every queued letter with
        its update bytes (base64) — the checkpoint-grade dump
        :meth:`restore` rebuilds from, so ``replay_dead_letters`` keeps
        working across a crash (ISSUE 3).  The default stays the small
        summary: exposition must not ship payload bytes."""
        out = {
            "depth": len(self._q),
            "capacity": self.maxlen,
            "total": self.total,
            "dropped": self.dropped,
            "reasons": self._reason_counts(),
        }
        if letters:
            import base64

            out["schema"] = 1
            out["letters"] = [
                {
                    "doc": e.doc,
                    "v2": e.v2,
                    "reason": e.reason,
                    "ts": e.ts,
                    "update": base64.b64encode(e.update).decode("ascii"),
                }
                for e in self._q
            ]
        return out

    def restore(self, state: dict) -> int:
        """Re-enqueue the letters of a :meth:`snapshot(letters=True)`
        dump (crash recovery).  Restored letters keep their original
        doc/bytes/v2/reason/timestamp but get fresh seq ids (seqs are a
        process-local handle, not a durable identity); ``total`` counts
        them again in this process's ledger.  Returns the number of
        letters restored (0 for a summary-only snapshot)."""
        import base64

        restored = 0
        for e in state.get("letters") or []:
            try:
                update = base64.b64decode(e["update"])
                doc = int(e.get("doc", -1))
            except (KeyError, TypeError, ValueError):
                continue
            entry = DeadLetter(
                next(self._seq),
                doc,
                update,
                bool(e.get("v2")),
                str(e.get("reason", "restored")),
                float(e.get("ts") or time.time()),
            )
            self._q.append(entry)
            self.total += 1
            restored += 1
            if len(self._q) > self.maxlen:
                self._q.popleft()
                self.dropped += 1
        return restored

    def _reason_counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for e in self._q:
            # group by the reason's stable prefix (before any exception
            # detail) so the summary stays small under poison storms
            key = e.reason.split(":", 1)[0]
            out[key] = out.get(key, 0) + 1
        return out
