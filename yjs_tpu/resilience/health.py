"""Per-doc health state machine: healthy → degraded → quarantined.

Drives admission control for the engine's update path.  Failures
(validation, integration, CPU-apply) push a doc toward quarantine;
while quarantined its traffic is diverted to the dead-letter queue so
repeated poison cannot re-enter the flush pipeline.  Backoff is counted
in FLUSH TICKS, not wall time, so recovery behavior is deterministic
under test (the engine bumps the tick once per flush).

State transitions:

- ``healthy``: the default; healthy docs carry NO tracker state (the
  hot path pays one empty-dict check per admission).
- ``degraded``: at least one recent failure (below the quarantine
  threshold), or a quarantined doc on re-admission probation.
  ``YTPU_RESILIENCE_RECOVERY`` consecutive successes return it to
  healthy (and free its record).
- ``quarantined``: ``YTPU_RESILIENCE_THRESHOLD`` consecutive failures.
  Inadmissible until ``base * 2**(n_quarantines-1)`` flush ticks pass
  (capped at ``YTPU_RESILIENCE_BACKOFF_CAP``) — each repeat quarantine
  doubles the sentence, the classic exponential-backoff re-admission.
"""

from __future__ import annotations

import os

HEALTHY = "healthy"
DEGRADED = "degraded"
QUARANTINED = "quarantined"


def _env_int(name: str, default: int, lo: int = 1, hi: int = 1 << 30) -> int:
    try:
        return max(lo, min(hi, int(os.environ.get(name, default))))
    except ValueError:
        return default


class DocHealth:
    """Mutable health record of one tracked (non-healthy) doc."""

    __slots__ = (
        "doc",
        "state",
        "consecutive_failures",
        "total_failures",
        "successes",
        "n_quarantines",
        "quarantined_until",
        "last_reason",
    )

    def __init__(self, doc: int):
        self.doc = doc
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.total_failures = 0
        self.successes = 0
        self.n_quarantines = 0
        self.quarantined_until = 0
        self.last_reason: str | None = None

    def as_dict(self) -> dict:
        return {
            "doc": self.doc,
            "state": self.state,
            "consecutive_failures": self.consecutive_failures,
            "total_failures": self.total_failures,
            "n_quarantines": self.n_quarantines,
            "quarantined_until": self.quarantined_until,
            "last_reason": self.last_reason,
        }


class HealthTracker:
    """Admission control + failure accounting over a doc fleet.

    ``obs`` (a :class:`yjs_tpu.obs.EngineObs`, optional) receives gauge
    updates (degraded/quarantined doc counts) and re-admission counts;
    the tracker itself stays import-light and fully functional when obs
    is disabled.
    """

    def __init__(
        self,
        threshold: int | None = None,
        backoff_base: int | None = None,
        backoff_cap: int | None = None,
        recovery: int | None = None,
        obs=None,
    ):
        self.threshold = (
            threshold
            if threshold is not None
            else _env_int("YTPU_RESILIENCE_THRESHOLD", 3)
        )
        self.backoff_base = (
            backoff_base
            if backoff_base is not None
            else _env_int("YTPU_RESILIENCE_BACKOFF", 4)
        )
        self.backoff_cap = (
            backoff_cap
            if backoff_cap is not None
            else _env_int("YTPU_RESILIENCE_BACKOFF_CAP", 256)
        )
        self.recovery = (
            recovery
            if recovery is not None
            else _env_int("YTPU_RESILIENCE_RECOVERY", 2)
        )
        self._obs = obs
        self._tick = 0
        # ONLY non-healthy docs have records: admission for a healthy
        # fleet is one falsy-dict check, no per-doc state
        self._docs: dict[int, DocHealth] = {}

    # -- clock ---------------------------------------------------------------

    @property
    def tick_count(self) -> int:
        return self._tick

    def tick(self) -> None:
        """One engine flush happened (the backoff clock)."""
        self._tick += 1

    # -- queries -------------------------------------------------------------

    @property
    def tracked(self) -> bool:
        """True when ANY doc is non-healthy (hot-path early-out)."""
        return bool(self._docs)

    def state(self, doc: int) -> str:
        h = self._docs.get(doc)
        return HEALTHY if h is None else h.state

    def record(self, doc: int) -> dict:
        h = self._docs.get(doc)
        if h is None:
            return DocHealth(doc).as_dict()
        return h.as_dict()

    def records(self) -> list[dict]:
        """Health records of every tracked (non-healthy) doc."""
        return [h.as_dict() for h in self._docs.values()]

    def reset(self, doc: int | None = None) -> None:
        """Operator override: forget health records (one doc, or all)
        — the doc(s) return to healthy with no backoff memory."""
        if doc is None:
            self._docs.clear()
        else:
            self._docs.pop(doc, None)
        self._push_gauges()

    def summary(self) -> dict:
        states = [h.state for h in self._docs.values()]
        return {
            "degraded": states.count(DEGRADED),
            "quarantined": states.count(QUARANTINED),
            "tick": self._tick,
        }

    # -- transitions ---------------------------------------------------------

    def admissible(self, doc: int) -> bool:
        """May this doc's traffic enter the engine right now?

        Quarantined docs become admissible again once their backoff
        expires — re-admission is lazy (checked here, at the moment
        traffic arrives) and lands the doc in ``degraded`` probation, so
        one more failure re-quarantines it with a doubled sentence.
        """
        h = self._docs.get(doc)
        if h is None or h.state != QUARANTINED:
            return True
        if self._tick < h.quarantined_until:
            return False
        h.state = DEGRADED
        h.consecutive_failures = 0
        h.successes = 0
        if self._obs is not None:
            self._obs.readmitted()
        self._push_gauges()
        return True

    def record_failure(self, doc: int, reason: str) -> str:
        """One failure for ``doc``; returns the resulting state."""
        h = self._docs.get(doc)
        if h is None:
            h = self._docs[doc] = DocHealth(doc)
        h.consecutive_failures += 1
        h.total_failures += 1
        h.successes = 0
        h.last_reason = reason
        if h.consecutive_failures >= self.threshold:
            h.state = QUARANTINED
            h.n_quarantines += 1
            backoff = min(
                self.backoff_cap,
                self.backoff_base * (1 << (h.n_quarantines - 1)),
            )
            h.quarantined_until = self._tick + backoff
            from ..obs.blackbox import flight_recorder
            from ..obs.dist import current_context

            ctx = current_context()
            if ctx is not None:
                ctx = ctx.force("quarantine")
            bb = flight_recorder()
            bb.record(
                "resilience", "quarantine", severity="error",
                trace=ctx.trace_hex if ctx is not None else None,
                doc=doc, reason=reason, backoff_ticks=backoff,
                n_quarantines=h.n_quarantines,
            )
            bb.dump("quarantine", doc=doc, cause=reason)
        else:
            h.state = DEGRADED
        self._push_gauges()
        return h.state

    def record_success(self, doc: int) -> None:
        """One successful apply/flush for a TRACKED doc (no-op for
        healthy docs — call under a ``tracked`` guard on hot paths)."""
        h = self._docs.get(doc)
        if h is None or h.state == QUARANTINED:
            return
        h.consecutive_failures = 0
        h.successes += 1
        if h.successes >= self.recovery:
            del self._docs[doc]  # back to healthy: record freed
        self._push_gauges()

    def _push_gauges(self) -> None:
        if self._obs is not None:
            s = self.summary()
            self._obs.health_gauges(s["degraded"], s["quarantined"])
