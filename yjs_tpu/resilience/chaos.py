"""Deterministic fault injection at the provider/protocol seams.

Models what a hostile transport can do to a CRDT deployment: corrupt,
truncate, duplicate, reorder, and drop — applied to raw update payloads
(``kind="update"``) or framed sync messages (``kind="frame"``).  All
randomness comes from one seeded PRNG, so a chaos test failure replays
byte-for-byte from its seed.

Detectability contract: the injector only produces corruptions that are
REJECTABLE — a corrupted update is verified (and if necessary forced) to
fail :func:`yjs_tpu.updates.validate_update`, and a corrupted frame is
rewritten so the tolerant frame reader rejects or skips it.  A bit flip
that happens to decode as a *different valid update* is a Byzantine
fault no CRDT convergence contract can absorb (garbage-in); real
transports reject it by checksum, so the harness models the
post-checksum world.  Faults applied are counted per kind in the
process-global ``ytpu_chaos_faults_total{fault=...}`` family.

Env knobs (all probabilities in [0, 1], default 0 = fault disabled):
``YTPU_CHAOS_SEED`` (int, default 0), ``YTPU_CHAOS_CORRUPT``,
``YTPU_CHAOS_TRUNCATE``, ``YTPU_CHAOS_DUP``, ``YTPU_CHAOS_REORDER``,
``YTPU_CHAOS_DROP``.
"""

from __future__ import annotations

import os
import random

from ..obs import global_registry
from ..updates import InvalidUpdate, validate_update

_FAULTS = ("corrupt", "truncate", "duplicate", "reorder", "drop")

# 9 continuation bytes splice a ~2**63 count into the leading varint:
# whatever follows, the decoder's struct loop exhausts the buffer and
# raises — the guaranteed-invalid fallback when random flips fail
_POISON_PREFIX = b"\xff" * 9


def _env_float(env, name: str, default: float = 0.0) -> float:
    try:
        return min(1.0, max(0.0, float(env.get(name, default))))
    except (TypeError, ValueError):
        return default


def _env_int(env, name: str, default: int = 0) -> int:
    try:
        return max(0, int(env.get(name, default)))
    except (TypeError, ValueError):
        return default


class ChaosConfig:
    """Per-fault probabilities + PRNG seed."""

    __slots__ = ("seed", "corrupt", "truncate", "duplicate", "reorder", "drop")

    def __init__(
        self,
        seed: int = 0,
        corrupt: float = 0.0,
        truncate: float = 0.0,
        duplicate: float = 0.0,
        reorder: float = 0.0,
        drop: float = 0.0,
    ):
        self.seed = seed
        self.corrupt = corrupt
        self.truncate = truncate
        self.duplicate = duplicate
        self.reorder = reorder
        self.drop = drop

    @classmethod
    def from_env(cls, env=None) -> "ChaosConfig":
        env = os.environ if env is None else env
        try:
            seed = int(env.get("YTPU_CHAOS_SEED", "0"))
        except (TypeError, ValueError):
            seed = 0
        return cls(
            seed=seed,
            corrupt=_env_float(env, "YTPU_CHAOS_CORRUPT"),
            truncate=_env_float(env, "YTPU_CHAOS_TRUNCATE"),
            duplicate=_env_float(env, "YTPU_CHAOS_DUP"),
            reorder=_env_float(env, "YTPU_CHAOS_REORDER"),
            drop=_env_float(env, "YTPU_CHAOS_DROP"),
        )

    def any_faults(self) -> bool:
        return any(
            getattr(self, f) > 0.0
            for f in ("corrupt", "truncate", "duplicate", "reorder", "drop")
        )

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class ChaosInjector:
    """Applies one :class:`ChaosConfig`'s fault mix to message streams.

    ``kind="update"`` treats payloads as raw (V1) update bytes and holds
    corruption to the detectability contract via ``validate_update``;
    ``kind="frame"`` treats them as framed sync messages and corrupts
    the framing itself (unknown message type / inflated length varint),
    which the tolerant ``read_sync_message`` path skips and counts.
    """

    def __init__(self, config: ChaosConfig | None = None, kind: str = "update"):
        if kind not in ("update", "frame"):
            raise ValueError(f"unknown chaos kind {kind!r}")
        self.config = config if config is not None else ChaosConfig.from_env()
        self.kind = kind
        self.rng = random.Random(self.config.seed)
        self.fault_counts: dict[str, int] = {f: 0 for f in _FAULTS}
        fam = global_registry().counter(
            "ytpu_chaos_faults_total",
            "Faults injected by the chaos harness, by fault kind",
            labelnames=("fault",),
        )
        self._children = {f: fam.labels(fault=f) for f in _FAULTS}

    def _hit(self, fault: str) -> None:
        self.fault_counts[fault] += 1
        self._children[fault].inc()

    # -- fault primitives ---------------------------------------------------

    def corrupt(self, payload: bytes) -> bytes:
        """Flip bits until the payload is verifiably rejectable."""
        self._hit("corrupt")
        if self.kind == "frame":
            # rewrite the leading message-type varint to an unknown type
            # (or inflate it): both deterministically un-integratable
            if self.rng.random() < 0.5:
                return b"\x7f" + payload[1:]
            return _POISON_PREFIX + payload
        out = bytearray(payload)
        for _ in range(8):
            if not out:
                break
            i = self.rng.randrange(len(out))
            out[i] ^= 1 << self.rng.randrange(8)
            try:
                validate_update(bytes(out))
            except InvalidUpdate:
                return bytes(out)
        return _POISON_PREFIX + bytes(payload)

    def truncate(self, payload: bytes) -> bytes:
        """Cut the payload short (verified rejectable for updates)."""
        self._hit("truncate")
        if not payload:
            return payload
        for _ in range(8):
            cut = self.rng.randrange(len(payload))
            out = payload[:cut]
            if self.kind == "frame":
                return out
            try:
                validate_update(out)
            except InvalidUpdate:
                return out
        return _POISON_PREFIX + payload

    # -- stream application -------------------------------------------------

    def apply(self, messages: list[bytes]) -> list[bytes]:
        """One fault-mix pass over a message stream.

        Per message: maybe drop, maybe duplicate, maybe corrupt or
        truncate (each delivered copy faulted independently); then maybe
        reorder the whole batch.  Deterministic in (config.seed, input).
        """
        cfg = self.config
        rng = self.rng
        out: list[bytes] = []
        for m in messages:
            if cfg.drop and rng.random() < cfg.drop:
                self._hit("drop")
                continue
            copies = [m]
            if cfg.duplicate and rng.random() < cfg.duplicate:
                self._hit("duplicate")
                copies.append(m)
            for c in copies:
                if cfg.corrupt and rng.random() < cfg.corrupt:
                    c = self.corrupt(c)
                elif cfg.truncate and rng.random() < cfg.truncate:
                    c = self.truncate(c)
                out.append(c)
        if len(out) > 1 and cfg.reorder and rng.random() < cfg.reorder:
            self._hit("reorder")
            rng.shuffle(out)
        return out


class NetChaosConfig:
    """Per-fault probabilities + PRNG seed for the TRANSPORT seam
    (ISSUE 5).  Distinct from :class:`ChaosConfig`: these faults act on
    whole frames in flight (a lossy datagram link), not on payload
    bytes — nothing here corrupts content, so the session layer's
    ack/retransmit + anti-entropy machinery must heal every mix.

    Env knobs (probabilities in [0, 1], default 0 = disabled):
    ``YTPU_CHAOS_SEED`` plus ``YTPU_CHAOS_NET_DROP``,
    ``YTPU_CHAOS_NET_DELAY``, ``YTPU_CHAOS_NET_DUP``,
    ``YTPU_CHAOS_NET_REORDER``, ``YTPU_CHAOS_NET_PARTITION``.

    The WAN profile (ISSUE 17) adds the shapes a LAN mix can't express:
    ``YTPU_CHAOS_NET_PARTITION_ONEWAY`` (probability of an asymmetric
    partition window: one direction goes dark, the reverse still
    flows), ``YTPU_CHAOS_NET_FLAP_TICKS`` (deterministic link flapping:
    up for 3 N-round windows, down for one, straight off the round
    counter), ``YTPU_CHAOS_NET_RTT_TICKS`` +
    ``YTPU_CHAOS_NET_RTT_JITTER_TICKS`` (per-link propagation delay in
    pump rounds, added to every frame — an RTT distribution, not a
    fault), and ``YTPU_CHAOS_NET_BW_FRAMES`` (per-direction bandwidth
    cap in frames per round; excess frames queue to the next round
    rather than being lost)."""

    __slots__ = ("seed", "drop", "delay", "duplicate", "reorder",
                 "partition", "oneway", "flap_ticks", "rtt_ticks",
                 "rtt_jitter_ticks", "bw_frames")

    def __init__(
        self,
        seed: int = 0,
        drop: float = 0.0,
        delay: float = 0.0,
        duplicate: float = 0.0,
        reorder: float = 0.0,
        partition: float = 0.0,
        oneway: float = 0.0,
        flap_ticks: int = 0,
        rtt_ticks: int = 0,
        rtt_jitter_ticks: int = 0,
        bw_frames: int = 0,
    ):
        self.seed = seed
        self.drop = drop
        self.delay = delay
        self.duplicate = duplicate
        self.reorder = reorder
        self.partition = partition
        self.oneway = oneway
        self.flap_ticks = flap_ticks
        self.rtt_ticks = rtt_ticks
        self.rtt_jitter_ticks = rtt_jitter_ticks
        self.bw_frames = bw_frames

    @classmethod
    def from_env(cls, env=None) -> "NetChaosConfig":
        env = os.environ if env is None else env
        try:
            seed = int(env.get("YTPU_CHAOS_SEED", "0"))
        except (TypeError, ValueError):
            seed = 0
        return cls(
            seed=seed,
            drop=_env_float(env, "YTPU_CHAOS_NET_DROP"),
            delay=_env_float(env, "YTPU_CHAOS_NET_DELAY"),
            duplicate=_env_float(env, "YTPU_CHAOS_NET_DUP"),
            reorder=_env_float(env, "YTPU_CHAOS_NET_REORDER"),
            partition=_env_float(env, "YTPU_CHAOS_NET_PARTITION"),
            oneway=_env_float(env, "YTPU_CHAOS_NET_PARTITION_ONEWAY"),
            flap_ticks=_env_int(env, "YTPU_CHAOS_NET_FLAP_TICKS"),
            rtt_ticks=_env_int(env, "YTPU_CHAOS_NET_RTT_TICKS"),
            rtt_jitter_ticks=_env_int(
                env, "YTPU_CHAOS_NET_RTT_JITTER_TICKS"
            ),
            bw_frames=_env_int(env, "YTPU_CHAOS_NET_BW_FRAMES"),
        )

    def any_faults(self) -> bool:
        return any(
            getattr(self, f) > 0.0
            for f in ("drop", "delay", "duplicate", "reorder", "partition",
                      "oneway", "flap_ticks", "rtt_ticks", "bw_frames")
        )

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class NetworkFaultInjector:
    """Frame-level fault injection for :class:`yjs_tpu.sync.transport.
    PipeNetwork` — the transport seam the session layer must survive.

    Three hooks, all driven by one seeded PRNG (same determinism
    contract as :class:`ChaosInjector`):

    - :meth:`fates` — at enqueue, each frame's delivery plan: a list of
      pump-round delays (one per delivered copy; ``None`` = dropped
      copy).  Applies drop, duplicate, and delay.
    - :meth:`partitioned` — per pump round: while a partition window is
      open the link is down and everything due that round is lost (the
      classic net-split; retransmission must heal it).
    - :meth:`maybe_reorder` — per pump round, maybe shuffle the due
      batch.

    Faults are counted in the process-global ``ytpu_chaos_faults_total``
    family (``net_drop``/``net_delay``/``net_dup``/``net_reorder``/
    ``net_partition``).
    """

    _NET_FAULTS = ("net_drop", "net_delay", "net_dup", "net_reorder",
                   "net_partition", "net_oneway", "net_flap", "net_bw")

    def __init__(self, config: NetChaosConfig | None = None):
        self.config = config if config is not None else NetChaosConfig.from_env()
        self.rng = random.Random(self.config.seed)
        self.fault_counts: dict[str, int] = {f: 0 for f in self._NET_FAULTS}
        self._partition_left = 0
        # one-way partition window (ISSUE 17): frames TOWARD _oneway_dst
        # are lost while the window is open; the reverse direction (and
        # every other endpoint) keeps flowing — the asymmetric split a
        # symmetric partition can't model
        self._oneway_left = 0
        self._oneway_dst: str | None = None
        # endpoint names registered by PipeNetwork.pair, so the one-way
        # victim is picked deterministically even on idle rounds
        self._links: list[str] = []
        fam = global_registry().counter(
            "ytpu_chaos_faults_total",
            "Faults injected by the chaos harness, by fault kind",
            labelnames=("fault",),
        )
        self._children = {f: fam.labels(fault=f) for f in self._NET_FAULTS}

    def _hit(self, fault: str) -> None:
        self.fault_counts[fault] += 1
        self._children[fault].inc()

    def fates(self, frame: bytes) -> list:
        """Delivery plan for one enqueued frame: delays in pump rounds
        per copy (``None`` entries are dropped copies)."""
        cfg, rng = self.config, self.rng
        if cfg.drop and rng.random() < cfg.drop:
            self._hit("net_drop")
            return [None]
        n_copies = 1
        if cfg.duplicate and rng.random() < cfg.duplicate:
            self._hit("net_dup")
            n_copies = 2
        out = []
        for _ in range(n_copies):
            delay = 0
            if cfg.delay and rng.random() < cfg.delay:
                self._hit("net_delay")
                delay = 1 + rng.randrange(3)
            # WAN propagation: every copy pays the link RTT floor plus
            # per-frame jitter (a latency profile, not a counted fault)
            if cfg.rtt_ticks:
                delay += cfg.rtt_ticks
            if cfg.rtt_jitter_ticks:
                delay += rng.randrange(cfg.rtt_jitter_ticks + 1)
            out.append(delay)
        return out

    def register_link(self, a_name: str, b_name: str) -> None:
        """Called by :meth:`PipeNetwork.pair`: remember the endpoint
        names so one-way partition windows can pick a victim direction
        deterministically."""
        for n in (a_name, b_name):
            if n not in self._links:
                self._links.append(n)

    def _flap_down(self, rnd: int) -> bool:
        """Deterministic link flapping straight off the pump-round
        counter: with ``flap_ticks=N`` the link is up for three N-round
        windows then down for one (75% duty cycle) — replayable from
        the round number alone, no RNG draw."""
        f = self.config.flap_ticks
        return bool(f) and (rnd % (4 * f)) >= 3 * f

    def _tick_oneway(self, due: list) -> None:
        cfg = self.config
        if self._oneway_left > 0:
            self._oneway_left -= 1
            if self._oneway_left == 0:
                self._oneway_dst = None
            return
        if not cfg.oneway or self.rng.random() >= cfg.oneway:
            return
        names = self._links or sorted({e[1].name for e in due})
        if not names:
            return
        self._oneway_dst = names[self.rng.randrange(len(names))]
        self._oneway_left = 1 + self.rng.randrange(4)

    def filter_due(self, due: list, rnd: int) -> tuple[list, list]:
        """Direction-aware WAN shaping for one pump round's due batch.
        One-way partition windows and flap-down windows LOSE frames
        (retransmission must heal them); the per-direction bandwidth
        cap DEFERS excess frames to the next round (queueing delay, not
        loss).  Returns ``(deliver, defer)``."""
        cfg = self.config
        self._tick_oneway(due)
        flap = self._flap_down(rnd)
        deliver: list = []
        defer: list = []
        sent: dict[str, int] = {}
        for e in due:
            name = e[1].name
            if self._oneway_dst is not None and name == self._oneway_dst:
                self._hit("net_oneway")
                continue
            if flap:
                self._hit("net_flap")
                continue
            n = sent.get(name, 0)
            if cfg.bw_frames and n >= cfg.bw_frames:
                self._hit("net_bw")
                defer.append(e)
                continue
            sent[name] = n + 1
            deliver.append(e)
        return deliver, defer

    def partitioned(self) -> bool:
        """Is the link down this pump round?  Partition windows open
        with probability ``partition`` and last 1-4 rounds."""
        if self._partition_left > 0:
            self._partition_left -= 1
            self._hit("net_partition")
            return True
        cfg = self.config
        if cfg.partition and self.rng.random() < cfg.partition:
            self._partition_left = self.rng.randrange(4)
            self._hit("net_partition")
            return True
        return False

    def maybe_reorder(self, batch: list) -> list:
        if self.config.reorder and self.rng.random() < self.config.reorder:
            self._hit("net_reorder")
            batch = list(batch)
            self.rng.shuffle(batch)
        return batch


class DiskFaultInjector:
    """File-level faults for the WAL crash harness (ISSUE 3).

    Two faults, matching what disks and crashes actually do to a log:
    ``tear`` truncates the final bytes of a file mid-record (the torn
    write a kill leaves on the ACTIVE segment — recovery must truncate
    at the first bad checksum), and ``bitflip`` flips one byte in place
    (at-rest corruption of a SEALED segment — recovery must dead-letter
    the record and resynchronize, never abort).  Same determinism
    contract as :class:`ChaosInjector`: one seeded PRNG, and every
    fault is detectable by construction — any single flipped byte fails
    the record CRC-32.  Counted in the process-global
    ``ytpu_chaos_faults_total`` family (``disk_tear``/``disk_bitflip``).
    """

    _DISK_FAULTS = ("disk_tear", "disk_bitflip")

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.fault_counts: dict[str, int] = {
            f: 0 for f in self._DISK_FAULTS
        }
        fam = global_registry().counter(
            "ytpu_chaos_faults_total",
            "Faults injected by the chaos harness, by fault kind",
            labelnames=("fault",),
        )
        self._children = {f: fam.labels(fault=f) for f in self._DISK_FAULTS}

    def _hit(self, fault: str) -> None:
        self.fault_counts[fault] += 1
        self._children[fault].inc()

    def tear(self, path, max_bytes: int = 64) -> int:
        """Truncate up to ``max_bytes`` off the end of ``path`` (at
        least 1).  Returns the bytes removed (0 if the file is empty)."""
        size = os.path.getsize(path)
        if size <= 1:
            return 0
        cut = self.rng.randrange(1, min(max_bytes, size - 1) + 1)
        os.truncate(path, size - cut)
        self._hit("disk_tear")
        return cut

    def bitflip(self, path, lo: int = 0) -> int:
        """Flip one random bit of one byte at offset >= ``lo`` in
        place.  Returns the flipped offset, or -1 if the file has no
        byte past ``lo``."""
        size = os.path.getsize(path)
        if size <= lo:
            return -1
        off = self.rng.randrange(lo, size)
        with open(path, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ (1 << self.rng.randrange(8))]))
        self._hit("disk_bitflip")
        return off
