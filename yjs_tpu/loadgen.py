"""Seeded, tick-deterministic multi-tenant overload generator (ISSUE 10).

The proof harness for the admission/brownout subsystem: drive a mixed
population of clients — interactive editors, idlers, reconnectors, lossy
links, and an abusive tenant pushing far over its rate — against a
provider or a replicated :class:`~yjs_tpu.fleet.FleetRouter` at a
configurable multiple of sustained admission capacity, then prove the
invariants the paper's robustness story needs:

- **zero acked-update loss** — every update the server accepted (direct
  ``receive_update`` returning True, or a session DATA frame it acked)
  is present in the final server state;
- **byte-identical convergence** — each doc has exactly one writer, so
  the server's final text must equal the writer's local text exactly;
- **interactive protection** — visibility probes (edit tick → tick the
  edit is readable on the server) give an interactive p99 that the
  brownout ladder is meant to protect while background traffic sheds;
- **bounded recovery** — after the load stops, the brownout level walks
  back to ``normal`` within a bounded number of ticks (hysteresis, no
  flapping).

Everything is driven by one integer seed and a tick loop — no wall
clocks, no threads — so a failing run replays exactly from its seed
(printed by the test harness on failure).
"""

from __future__ import annotations

import random

from .admission import AdmissionRejected
from .core import Doc
from .resilience.chaos import NetChaosConfig, NetworkFaultInjector
from .sync.session import DocSessionHost, SessionConfig, SyncSession
from .sync.transport import PipeNetwork

__all__ = [
    "LoadGen", "LoadGenConfig", "Profile", "PROFILES", "INTERACTIVE_MIX",
]

_ALPHABET = "abcdefghijklmnopqrstuvwxyz "


class Profile:
    """One client behavior archetype.

    ``p_edit`` is the per-tick edit probability; ``burst`` the edits per
    editing tick; ``direct`` clients skip the session layer and call
    ``receive_update`` themselves (retrying rejections with a cumulative
    delta); ``faults`` (a NetChaosConfig kwargs dict) puts the client's
    pipe behind the network fault injector; ``reconnect_every`` kills and
    re-attaches the transport on that tick cadence; ``interactive``
    clients carry the visibility probes the SLO assertions read."""

    __slots__ = (
        "name", "p_edit", "burst", "direct", "faults",
        "reconnect_every", "interactive",
    )

    def __init__(
        self, name, p_edit, burst=1, direct=False, faults=None,
        reconnect_every=0, interactive=False,
    ):
        self.name = name
        self.p_edit = float(p_edit)
        self.burst = max(1, int(burst))
        self.direct = bool(direct)
        self.faults = dict(faults) if faults else None
        self.reconnect_every = max(0, int(reconnect_every))
        self.interactive = bool(interactive)


PROFILES = {
    # the interactive population the brownout ladder protects
    "edit": Profile("edit", p_edit=0.4, interactive=True),
    # parked tabs: rare edits, mostly heartbeat/anti-entropy traffic
    "idle": Profile("idle", p_edit=0.02),
    # flappy links: periodic transport loss + reattach (must resume,
    # never full-resync)
    "reconnect": Profile(
        "reconnect", p_edit=0.2, reconnect_every=40, interactive=True
    ),
    # lossy last mile: drops/dups/delays/reorders on the pipe
    "lossy": Profile(
        "lossy", p_edit=0.2,
        faults=dict(drop=0.12, duplicate=0.1, delay=0.2, reorder=0.2),
    ),
    # the overload driver: one tenant hammering direct writes far over
    # its token rate — this is what the fleet must shed
    "abusive": Profile("abusive", p_edit=1.0, burst=4, direct=True),
}

# population mix: (profile, weight)
_DEFAULT_MIX = (
    ("edit", 4), ("idle", 4), ("reconnect", 1), ("lossy", 1),
    ("abusive", 2),
)

# all-interactive population for capacity ramps (obs/capacity.py): every
# session is an editor whose visibility latency the SLO verdict watches
INTERACTIVE_MIX = (("edit", 1),)


class LoadGenConfig:
    """Shape of one load-generation run."""

    __slots__ = (
        "seed", "n_clients", "mix", "flush_every", "root_name",
        "session_config", "drain_max_ticks", "slo_target_ms",
    )

    def __init__(
        self,
        seed: int = 0,
        n_clients: int = 24,
        mix=_DEFAULT_MIX,
        flush_every: int = 2,
        root_name: str = "text",
        session_config: SessionConfig | None = None,
        drain_max_ticks: int = 3000,
        slo_target_ms: float = 5000.0,
    ):
        self.seed = int(seed)
        self.n_clients = max(1, int(n_clients))
        self.mix = tuple(mix)
        self.flush_every = max(1, int(flush_every))
        self.root_name = root_name
        self.session_config = session_config or SessionConfig(
            retry_base=2, retry_cap=16, retry_max=8, retry_jitter=0.25,
            antientropy=16, heartbeat=0, liveness=0, hello_timeout=0,
        )
        self.drain_max_ticks = max(1, int(drain_max_ticks))
        # the convergence SLO target is wall-clock (250 ms production
        # default) but this harness is tick-driven: a pure-Python tick
        # loop legitimately spends hundreds of ms per flush interval, so
        # the production target would page on simulation speed, not on
        # starvation.  Rescale it to the harness (a wedged fleet still
        # pages at 5 s); tick-deterministic interactive latency is
        # measured by the visibility probes instead.
        self.slo_target_ms = float(slo_target_ms)


class _Client:
    """Common writer state: one local Doc, one owned guid."""

    def __init__(self, lg: "LoadGen", idx: int, profile: Profile):
        self.lg = lg
        self.idx = idx
        self.profile = profile
        tenant = "abuser" if profile.direct else f"tenant{idx % 4}"
        self.tenant = tenant
        self.guid = f"{tenant}/{profile.name}-{idx}"
        self.rng = random.Random((lg.config.seed * 1000003) ^ (idx * 7919))
        self.doc = Doc(gc=False)
        self.doc.client_id = idx + 1
        self.n_edits = 0
        # outstanding visibility probe: (sent_tick, local_text_len)
        self.probe: tuple[int, int] | None = None
        self.latencies: list[int] = []

    @property
    def text(self) -> str:
        return str(self.doc.get_text(self.lg.config.root_name))

    def edit(self, tick: int) -> bool:
        if self.rng.random() >= self.profile.p_edit:
            return False
        t = self.doc.get_text(self.lg.config.root_name)
        for _ in range(self.profile.burst):
            t.insert(len(t), self.rng.choice(_ALPHABET))
            self.n_edits += 1
        if self.profile.interactive and self.probe is None:
            self.probe = (tick, len(t))
        return True

    def check_probe(self, tick: int) -> None:
        if self.probe is None:
            return
        sent, want = self.probe
        try:
            visible = len(self.lg.server.text(self.guid))
        except Exception:
            return
        if visible >= want:
            self.latencies.append(tick - sent)
            self.probe = None

    def settle_probe(self, tick: int) -> None:
        """Drain-phase bound: an unanswered probe scores its final age
        so a stalled doc cannot silently vanish from the p99."""
        if self.probe is not None:
            self.latencies.append(tick - self.probe[0])
            self.probe = None


class _DirectClient(_Client):
    """No session: push cumulative deltas straight into the server's
    ``receive_update`` seam and honor typed rejections by retrying the
    (now larger) delta after the advertised retry window.  An accepted
    push is an ACK — the server owns those bytes from that moment."""

    def __init__(self, lg, idx, profile):
        super().__init__(lg, idx, profile)
        from .updates import encode_state_as_update, encode_state_vector

        self._encode_delta = encode_state_as_update
        self._encode_sv = encode_state_vector
        self._acked_sv: bytes | None = None
        self._next_try = 0
        self.n_acked = 0
        self.n_rejected = 0

    def dirty(self) -> bool:
        return self._acked_sv != self._encode_sv(self.doc)

    def push(self, tick: int) -> None:
        if tick < self._next_try or not self.dirty():
            return
        delta = self._encode_delta(self.doc, self._acked_sv)
        try:
            accepted = self.lg.server.receive_update(self.guid, delta)
        except AdmissionRejected as e:
            self.n_rejected += 1
            self._next_try = tick + max(1, e.retry_after)
            return
        except Exception:
            # shard down mid-failover / fleet full: back off and retry
            # the cumulative delta — the CRDT makes the re-push free
            self._next_try = tick + 4
            return
        if accepted:
            self.n_acked += 1
            self._acked_sv = self._encode_sv(self.doc)

    def tick(self, tick: int) -> None:
        self.push(tick)


class _SessionClient(_Client):
    """Real enhanced-envelope session over an in-memory pipe, optionally
    behind the network fault injector, optionally flapping its transport
    on a cadence (reconnect profile)."""

    def __init__(self, lg, idx, profile):
        super().__init__(lg, idx, profile)
        inj = None
        if profile.faults:
            inj = NetworkFaultInjector(NetChaosConfig(
                seed=(lg.config.seed * 31 + idx) & 0x7FFFFFFF,
                **profile.faults,
            ))
        self.net = PipeNetwork(inj)
        self.session = SyncSession(
            DocSessionHost(self.doc), lg.config.session_config,
            peer="server",
        )
        self.doc.on("update", self._relay)
        self.server_session = lg.server.session(
            self.guid, f"client-{idx}", lg.config.session_config
        )
        self._connect(first=True)

    def _relay(self, update, origin, _doc):
        if origin is not self.session.host:
            self.session.send_update(bytes(update))

    def _connect(self, first: bool = False) -> None:
        ta, tb = self.net.pair(f"c{self.idx}", "srv")
        if first:
            self.session.connect(ta)
            self.server_session.connect(tb)
        else:
            self.session.attach(ta)
            self.server_session.attach(tb)

    def maybe_reconnect(self, tick: int) -> None:
        every = self.profile.reconnect_every
        if every and tick and tick % every == 0:
            self.net.kill(self.session.transport,
                          self.server_session.transport)
            self._connect()

    def tick(self, tick: int) -> None:
        self.maybe_reconnect(tick)
        self.net.pump()
        self.session.tick()

    def settled(self) -> bool:
        return (
            self.net.in_flight == 0
            and not self.session._outbox
            and not self.server_session._outbox
        )


class LoadGen:
    """Drive a mixed-profile population against ``server`` (a
    :class:`~yjs_tpu.provider.TpuProvider` or
    :class:`~yjs_tpu.fleet.FleetRouter`) for a seeded, reproducible
    number of ticks, then :meth:`drain` to quiescence and read the
    invariants off :meth:`report`."""

    def __init__(self, server, config: LoadGenConfig | None = None):
        self.server = server
        self.config = config or LoadGenConfig()
        # the harness owns its convergence accounting: rescale the
        # wall-clock SLO target to harness speed AND give the fleet a
        # private origin clock — the process-global one may carry
        # first-sighting stamps for byte-identical updates emitted by
        # earlier (seeded, hence colliding) runs in this process, which
        # would read as minutes-old origins and page the SLO forever
        from .obs.slo import OriginClock

        origins = OriginClock()
        for p in getattr(server, "shards", [server]):
            slo = getattr(p, "slo", None)
            if slo is not None:
                slo._origins = origins
                if self.config.slo_target_ms:
                    slo.target_ms = self.config.slo_target_ms
        self.tick = 0
        self.level_history: list[int] = []
        self.slo_page_ticks = 0
        self.recovery_ticks: int | None = None
        self.clients: list[_Client] = []
        weighted = [
            name for name, w in self.config.mix for _ in range(w)
        ]
        for i in range(self.config.n_clients):
            profile = PROFILES[weighted[i % len(weighted)]]
            cls = _DirectClient if profile.direct else _SessionClient
            self.clients.append(cls(self, i, profile))
        self._interactive = [
            c for c in self.clients if c.profile.interactive
        ]

    # -- capacity arithmetic ------------------------------------------------

    def offered_per_tick(self) -> float:
        return sum(c.profile.p_edit * c.profile.burst
                   for c in self.clients)

    def capacity_per_tick(self) -> float:
        """Sustained admission capacity: per-tenant token rate summed
        over the distinct tenants this population uses."""
        adm = self.server.admission
        tenants = {c.tenant for c in self.clients}
        return adm.config.tenant_rate * max(1, len(tenants))

    def overload_factor(self) -> float:
        cap = self.capacity_per_tick()
        return self.offered_per_tick() / cap if cap else float("inf")

    # -- tick loop ----------------------------------------------------------

    def _tick_server(self) -> None:
        srv = self.server
        tick_fleet = getattr(srv, "tick", None)
        if callable(tick_fleet):
            tick_fleet()
        else:
            srv.tick_sessions()

    def _flush_interval(self) -> int:
        scale = self.server.admission.flush_interval_scale
        return max(1, round(self.config.flush_every * scale))

    def step(self, editing: bool = True, on_tick=None) -> None:
        """One deterministic tick: edits, direct pushes, pumps, session
        ticks, the server tick (admission clock included), and a flush
        on the brownout-scaled cadence."""
        self.tick += 1
        for c in self.clients:
            if editing:
                c.edit(self.tick)
            c.tick(self.tick)
        self._tick_server()
        adm = self.server.admission
        self.level_history.append(adm.level)
        if self._worst_slo() == "page":
            self.slo_page_ticks += 1
        if self.tick % self._flush_interval() == 0:
            self.server.flush()
            for c in self._interactive:
                c.check_probe(self.tick)
        if on_tick is not None:
            on_tick(self)

    def run(self, ticks: int, on_tick=None) -> "LoadGen":
        for _ in range(ticks):
            self.step(editing=True, on_tick=on_tick)
        return self

    def _worst_slo(self) -> str:
        rank = {"ok": 0, "warning": 1, "page": 2}
        worst = "ok"
        for p in getattr(self.server, "shards", [self.server]):
            try:
                st = p.slo.state()
            except Exception:
                continue
            if rank.get(st, 0) > rank.get(worst, 0):
                worst = st
        return worst

    # -- drain / quiescence --------------------------------------------------

    def _converged(self) -> bool:
        adm = self.server.admission
        if adm.queue_depth():
            return False
        for c in self.clients:
            if isinstance(c, _DirectClient):
                if c.dirty():
                    return False
            elif not c.settled():
                return False
        return True

    def drain(self) -> int:
        """Stop editing, keep the machinery ticking until every client's
        traffic is fully integrated AND the brownout level is back to
        ``normal``.  Returns recovery ticks (load-stop → level normal);
        raises if the fleet cannot quiesce inside ``drain_max_ticks``."""
        start = self.tick
        recovered_at = None
        for _ in range(self.config.drain_max_ticks):
            self.step(editing=False)
            if recovered_at is None and self.server.admission.level == 0:
                recovered_at = self.tick
            if self._converged() and recovered_at is not None:
                break
        else:
            raise AssertionError(
                f"loadgen failed to quiesce in "
                f"{self.config.drain_max_ticks} ticks "
                f"(seed {self.config.seed}): "
                f"{self.server.admission.snapshot()}"
            )
        # a few settle laps for in-flight anti-entropy repairs
        for _ in range(8):
            self.step(editing=False)
        self.server.flush()
        for c in self._interactive:
            c.check_probe(self.tick)
            c.settle_probe(self.tick)
        self.recovery_ticks = (recovered_at or self.tick) - start
        return self.recovery_ticks

    # -- invariants ----------------------------------------------------------

    def convergence_failures(self) -> list[dict]:
        """Byte-identical check, one writer per doc: server text must
        equal the writer's local text exactly."""
        out = []
        for c in self.clients:
            server_text = self.server.text(c.guid)
            if server_text != c.text:
                out.append({
                    "guid": c.guid, "profile": c.profile.name,
                    "server_len": len(server_text),
                    "client_len": len(c.text),
                })
        return out

    def interactive_p99(self) -> int:
        lat = sorted(
            x for c in self._interactive for x in c.latencies
        )
        if not lat:
            return 0
        return lat[min(len(lat) - 1, int(0.99 * (len(lat) - 1)))]

    def report(self) -> dict:
        adm = self.server.admission.snapshot()
        offered = max(1, adm["offered"])
        rejected = sum(adm["rejected"].values())
        full_resyncs = sorted({
            c.session.n_full_resyncs for c in self.clients
            if isinstance(c, _SessionClient)
        })
        return {
            "seed": self.config.seed,
            "ticks": self.tick,
            "clients": len(self.clients),
            "profiles": {
                name: sum(
                    1 for c in self.clients if c.profile.name == name
                )
                for name, _w in self.config.mix
            },
            "edits": sum(c.n_edits for c in self.clients),
            "overload_factor": round(self.overload_factor(), 3),
            "shed_fraction": round(
                (adm["queued"] + rejected) / offered, 4
            ),
            "reject_rate": round(rejected / offered, 4),
            "interactive_p99_ticks": self.interactive_p99(),
            "slo_page_ticks": self.slo_page_ticks,
            "max_level": max(self.level_history, default=0),
            "transitions": adm["brownout"]["transitions"],
            "recovery_ticks": self.recovery_ticks,
            "convergence_failures": self.convergence_failures(),
            "session_full_resyncs": full_resyncs,
            "admission": adm,
        }

    def assert_invariants(self, max_interactive_p99: int | None = None):
        """The ISSUE 10 acceptance bundle: zero acked loss / byte
        identity, interactive SLO never paged, bounded recovery."""
        rep = self.report()
        assert not rep["convergence_failures"], (
            f"acked-update loss or divergence (seed {rep['seed']}): "
            f"{rep['convergence_failures']}"
        )
        assert rep["slo_page_ticks"] == 0, (
            f"interactive SLO paged for {rep['slo_page_ticks']} ticks "
            f"(seed {rep['seed']})"
        )
        assert self.server.admission.level == 0, (
            f"brownout never recovered (seed {rep['seed']}): "
            f"{rep['admission']['brownout']}"
        )
        if max_interactive_p99 is not None:
            assert rep["interactive_p99_ticks"] <= max_interactive_p99, (
                f"interactive p99 {rep['interactive_p99_ticks']} > "
                f"{max_interactive_p99} (seed {rep['seed']})"
            )
        return rep
