"""Binary encoders, byte-compatible with lib0/encoding.

Every byte layout here is pinned by the reference wire format:
- varuint / varint framing (used by every codec path)
- the `any` tagged-value codec (reference src/structs/ContentAny.js)
- the Rle / UintOptRle / IntDiffOptRle / String column encoders used by
  UpdateEncoderV2 (reference src/utils/UpdateEncoder.js:264-304)
"""

from __future__ import annotations

import math
import struct

from .binary import BIT7, BIT8, BITS6, BITS7, BITS31
from .u16 import u16_encode_utf8


class Undefined:
    """Singleton mirroring JS `undefined` inside the `any` codec."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "undefined"

    def __bool__(self):
        return False


UNDEFINED = Undefined()


class Encoder:
    """Append-only byte buffer."""

    __slots__ = ("buf",)

    def __init__(self):
        self.buf = bytearray()

    def to_bytes(self) -> bytes:
        return bytes(self.buf)

    def __len__(self):
        return len(self.buf)


def write_uint8(encoder: Encoder, num: int) -> None:
    encoder.buf.append(num & 0xFF)


def write_uint8_array(encoder: Encoder, b: bytes) -> None:
    encoder.buf += b


def write_var_uint(encoder: Encoder, num: int) -> None:
    buf = encoder.buf
    while num > BITS7:
        buf.append(BIT8 | (num & BITS7))
        num >>= 7
    buf.append(num & BITS7)


def write_var_int(encoder: Encoder, num: int, negative_zero: bool = False) -> None:
    """Sign-magnitude varint: first byte holds sign (BIT7) + 6 bits.

    `negative_zero` mirrors JS `-0`, which the UintOptRle encoder relies on to
    signal "a run count follows" even when the run value is 0.
    """
    is_negative = num < 0 or negative_zero
    if is_negative:
        num = -num
    buf = encoder.buf
    buf.append((BIT8 if num > BITS6 else 0) | (BIT7 if is_negative else 0) | (num & BITS6))
    num >>= 6
    while num > 0:
        buf.append((BIT8 if num > BITS7 else 0) | (num & BITS7))
        num >>= 7


def write_var_string(encoder: Encoder, s: str) -> None:
    b = u16_encode_utf8(s)
    write_var_uint(encoder, len(b))
    encoder.buf += b


def write_var_uint8_array(encoder: Encoder, b: bytes) -> None:
    write_var_uint(encoder, len(b))
    encoder.buf += b


def write_float(encoder: Encoder, num: float) -> None:
    encoder.buf += struct.pack(">f", num)


def write_double(encoder: Encoder, num: float) -> None:
    encoder.buf += struct.pack(">d", num)


def write_big_int64(encoder: Encoder, num: int) -> None:
    encoder.buf += struct.pack(">q", num)


def _is_float32(num: float) -> bool:
    try:
        return struct.unpack(">f", struct.pack(">f", num))[0] == num
    except (OverflowError, struct.error):
        return False


def write_any(encoder: Encoder, data) -> None:
    """Tagged-value codec (tags 116-127, matching lib0's `any` encoding)."""
    if data is UNDEFINED:
        write_uint8(encoder, 127)
    elif data is None:
        write_uint8(encoder, 126)
    elif isinstance(data, bool):
        write_uint8(encoder, 120 if data else 121)
    elif isinstance(data, (int, float)) and not isinstance(data, bool):
        is_int = isinstance(data, int) or float(data).is_integer()
        if is_int and abs(data) <= BITS31:
            write_uint8(encoder, 125)
            neg_zero = isinstance(data, float) and data == 0 and math.copysign(1.0, data) < 0
            write_var_int(encoder, int(data), negative_zero=neg_zero)
        elif isinstance(data, float) and _is_float32(data):
            write_uint8(encoder, 124)
            write_float(encoder, data)
        else:
            write_uint8(encoder, 123)
            write_double(encoder, float(data))
    elif isinstance(data, str):
        write_uint8(encoder, 119)
        write_var_string(encoder, data)
    elif isinstance(data, (bytes, bytearray, memoryview)):
        write_uint8(encoder, 116)
        write_var_uint8_array(encoder, bytes(data))
    elif isinstance(data, (list, tuple)):
        write_uint8(encoder, 117)
        write_var_uint(encoder, len(data))
        for item in data:
            write_any(encoder, item)
    elif isinstance(data, dict):
        write_uint8(encoder, 118)
        write_var_uint(encoder, len(data))
        for key, value in data.items():
            write_var_string(encoder, key)
            write_any(encoder, value)
    else:
        raise TypeError(f"cannot encode value of type {type(data)!r} as any")


class RleEncoder(Encoder):
    """Run-length encoder over a basic writer (used for the info/parentInfo
    columns of UpdateEncoderV2)."""

    __slots__ = ("w", "s", "count")

    def __init__(self, writer=write_uint8):
        super().__init__()
        self.w = writer
        self.s = None
        self.count = 0

    def write(self, v) -> None:
        if self.s == v and self.count > 0:
            self.count += 1
        else:
            if self.count > 0:
                write_var_uint(self, self.count - 1)
            self.count = 1
            self.w(self, v)
            self.s = v


class UintOptRleEncoder:
    """Optional run-length encoding of unsigned ints: single values are
    written as positive varints; runs are written as the negated value
    followed by (count - 2)."""

    __slots__ = ("encoder", "s", "count")

    def __init__(self):
        self.encoder = Encoder()
        self.s = 0
        self.count = 0

    def write(self, v: int) -> None:
        if self.s == v:
            self.count += 1
        else:
            self._flush()
            self.count = 1
            self.s = v

    def _flush(self) -> None:
        if self.count > 0:
            if self.count == 1:
                write_var_int(self.encoder, self.s)
            else:
                write_var_int(self.encoder, -self.s, negative_zero=self.s == 0)
                write_var_uint(self.encoder, self.count - 2)

    def to_bytes(self) -> bytes:
        self._flush()
        return self.encoder.to_bytes()


class IntDiffOptRleEncoder:
    """Delta + optional-RLE encoder: diffs are doubled, with the low bit
    signalling that a run count follows."""

    __slots__ = ("encoder", "s", "count", "diff")

    def __init__(self):
        self.encoder = Encoder()
        self.s = 0
        self.count = 0
        self.diff = 0

    def write(self, v: int) -> None:
        if self.diff == v - self.s:
            self.s = v
            self.count += 1
        else:
            self._flush()
            self.count = 1
            self.diff = v - self.s
            self.s = v

    def _flush(self) -> None:
        if self.count > 0:
            encoded_diff = self.diff * 2 + (0 if self.count == 1 else 1)
            write_var_int(self.encoder, encoded_diff)
            if self.count > 1:
                write_var_uint(self.encoder, self.count - 2)

    def to_bytes(self) -> bytes:
        self._flush()
        return self.encoder.to_bytes()


class StringEncoder:
    """All strings concatenated into one var-string + UintOptRle of the
    individual UTF-16 lengths."""

    __slots__ = ("parts", "lens")

    def __init__(self):
        self.parts = []
        self.lens = UintOptRleEncoder()

    def write(self, s: str) -> None:
        self.parts.append(s)
        self.lens.write(len(s))  # s is in u16 form: len == UTF-16 units

    def to_bytes(self) -> bytes:
        encoder = Encoder()
        write_var_string(encoder, "".join(self.parts))
        write_uint8_array(encoder, self.lens.to_bytes())
        return encoder.to_bytes()
