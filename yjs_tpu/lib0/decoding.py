"""Binary decoders mirroring `encoding.py` (lib0/decoding byte formats)."""

from __future__ import annotations

import struct

from .binary import BIT7, BIT8, BITS6, BITS7
from .encoding import UNDEFINED
from .u16 import utf8_decode_u16


class Decoder:
    __slots__ = ("arr", "pos")

    def __init__(self, arr: bytes):
        self.arr = arr
        self.pos = 0

    def has_content(self) -> bool:
        return self.pos < len(self.arr)


def read_uint8(decoder: Decoder) -> int:
    b = decoder.arr[decoder.pos]
    decoder.pos += 1
    return b


def read_var_uint(decoder: Decoder) -> int:
    num = 0
    shift = 0
    arr = decoder.arr
    n = len(arr)
    while decoder.pos < n:
        r = arr[decoder.pos]
        decoder.pos += 1
        num |= (r & BITS7) << shift
        shift += 7
        if r < BIT8:
            return num
    raise ValueError("unexpected end of array")


def read_var_int_signed(decoder: Decoder):
    """Returns (magnitude, sign) where sign is -1 or 1.

    The sign of a zero magnitude is meaningful (JS `-0`): the UintOptRle
    decoder uses it to detect that a run count follows.
    """
    arr = decoder.arr
    r = arr[decoder.pos]
    decoder.pos += 1
    num = r & BITS6
    sign = -1 if (r & BIT7) > 0 else 1
    if (r & BIT8) == 0:
        return num, sign
    shift = 6
    n = len(arr)
    while decoder.pos < n:
        r = arr[decoder.pos]
        decoder.pos += 1
        num |= (r & BITS7) << shift
        shift += 7
        if r < BIT8:
            return num, sign
    raise ValueError("unexpected end of array")


def read_var_int(decoder: Decoder) -> int:
    num, sign = read_var_int_signed(decoder)
    return sign * num


def read_var_string(decoder: Decoder) -> str:
    ln = read_var_uint(decoder)
    s = utf8_decode_u16(bytes(decoder.arr[decoder.pos:decoder.pos + ln]))
    decoder.pos += ln
    return s


def read_var_uint8_array(decoder: Decoder) -> bytes:
    ln = read_var_uint(decoder)
    b = bytes(decoder.arr[decoder.pos:decoder.pos + ln])
    decoder.pos += ln
    return b


def read_float(decoder: Decoder) -> float:
    v = struct.unpack_from(">f", decoder.arr, decoder.pos)[0]
    decoder.pos += 4
    return v


def read_double(decoder: Decoder) -> float:
    v = struct.unpack_from(">d", decoder.arr, decoder.pos)[0]
    decoder.pos += 8
    return v


def read_big_int64(decoder: Decoder) -> int:
    v = struct.unpack_from(">q", decoder.arr, decoder.pos)[0]
    decoder.pos += 8
    return v


def read_any(decoder: Decoder):
    tag = read_uint8(decoder)
    if tag == 127:
        return UNDEFINED
    if tag == 126:
        return None
    if tag == 125:
        return read_var_int(decoder)
    if tag == 124:
        return read_float(decoder)
    if tag == 123:
        return read_double(decoder)
    if tag == 122:
        return read_big_int64(decoder)
    if tag == 121:
        return False
    if tag == 120:
        return True
    if tag == 119:
        return read_var_string(decoder)
    if tag == 118:
        obj = {}
        for _ in range(read_var_uint(decoder)):
            key = read_var_string(decoder)
            obj[key] = read_any(decoder)
        return obj
    if tag == 117:
        return [read_any(decoder) for _ in range(read_var_uint(decoder))]
    if tag == 116:
        return read_var_uint8_array(decoder)
    raise ValueError(f"unknown any tag {tag}")


class RleDecoder(Decoder):
    __slots__ = ("reader", "s", "count")

    def __init__(self, arr: bytes, reader=read_uint8):
        super().__init__(arr)
        self.reader = reader
        self.s = None
        self.count = 0

    def read(self):
        if self.count == 0:
            self.s = self.reader(self)
            if self.has_content():
                self.count = read_var_uint(self) + 1
            else:
                self.count = -1  # the final value repeats forever
        self.count -= 1
        return self.s


class UintOptRleDecoder(Decoder):
    __slots__ = ("s", "count")

    def __init__(self, arr: bytes):
        super().__init__(arr)
        self.s = 0
        self.count = 0

    def read(self) -> int:
        if self.count == 0:
            num, sign = read_var_int_signed(self)
            self.count = 1
            self.s = num
            if sign < 0:
                self.count = read_var_uint(self) + 2
        self.count -= 1
        return self.s


class IntDiffOptRleDecoder(Decoder):
    __slots__ = ("s", "count", "diff")

    def __init__(self, arr: bytes):
        super().__init__(arr)
        self.s = 0
        self.count = 0
        self.diff = 0

    def read(self) -> int:
        if self.count == 0:
            num, sign = read_var_int_signed(self)
            diff = sign * num
            has_count = diff & 1
            self.diff = diff >> 1  # arithmetic shift == floor division by 2
            self.count = read_var_uint(self) + 2 if has_count else 1
        self.s += self.diff
        self.count -= 1
        return self.s


class StringDecoder:
    __slots__ = ("decoder", "string", "spos")

    def __init__(self, arr: bytes):
        self.decoder = UintOptRleDecoder(arr)
        self.string = read_var_string(self.decoder)
        self.spos = 0

    def read(self) -> str:
        ln = self.decoder.read()
        s = self.string[self.spos:self.spos + ln]
        self.spos += ln
        return s
