"""Minimal Observable base class (mirrors lib0/observable semantics used by
the reference Doc, reference src/utils/Doc.js:36)."""

from __future__ import annotations


class Observable:
    def __init__(self):
        self._observers: dict[str, set] = {}

    def on(self, name: str, f) -> None:
        self._observers.setdefault(name, set()).add(f)

    def once(self, name: str, f) -> None:
        def _f(*args):
            self.off(name, _f)
            f(*args)

        self.on(name, _f)

    def off(self, name: str, f) -> None:
        observers = self._observers.get(name)
        if observers is not None:
            observers.discard(f)
            if not observers:
                del self._observers[name]

    def emit(self, name: str, args) -> None:
        # copy so that observers may unregister themselves mid-emit
        for f in list(self._observers.get(name, ())):
            f(*args)

    def destroy(self) -> None:
        self._observers = {}
