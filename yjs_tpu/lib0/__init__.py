"""Host-side binary primitives, wire-compatible with the lib0 JS library.

The reference framework (yjs @ /root/reference) builds its entire wire format
on lib0's varint/RLE/string/any encoders (see e.g. reference
src/utils/UpdateEncoder.js:264-304).  This package reimplements those byte
formats from scratch in Python so that updates produced here are bit-identical
to updates produced by the JS implementation.
"""
