"""UTF-16 code-unit string helpers.

The reference implementation is JavaScript, where `string.length`, slicing and
`split('')` all operate on UTF-16 code units.  Item lengths, YText indices and
the V2 string-column lengths are therefore all UTF-16-unit based (see e.g.
reference src/structs/ContentString.js:51-66, which guards against splitting a
surrogate pair when an item is split).

To match those semantics exactly we represent text *internally* as Python
strings in "u16 form": every astral code point is expanded into its surrogate
pair, so ``len()``/slicing on the Python string equal JS semantics.  The
helpers below convert between u16 form and ordinary Python strings.
"""


def to_u16(s: str) -> str:
    """Expand astral code points into surrogate pairs (JS string model)."""
    for ch in s:
        if ord(ch) > 0xFFFF:
            break
    else:
        return s
    out = []
    for ch in s:
        cp = ord(ch)
        if cp > 0xFFFF:
            cp -= 0x10000
            out.append(chr(0xD800 | (cp >> 10)))
            out.append(chr(0xDC00 | (cp & 0x3FF)))
        else:
            out.append(ch)
    return "".join(out)


def from_u16(s: str) -> str:
    """Recombine surrogate pairs into astral code points.

    Lone surrogates are replaced with U+FFFD, mirroring what a JS engine
    produces when such a string is UTF-8 encoded for the wire.
    """
    for ch in s:
        if 0xD800 <= ord(ch) <= 0xDFFF:
            break
    else:
        return s
    out = []
    i = 0
    n = len(s)
    while i < n:
        c = ord(s[i])
        if 0xD800 <= c <= 0xDBFF and i + 1 < n and 0xDC00 <= ord(s[i + 1]) <= 0xDFFF:
            out.append(chr(0x10000 + ((c - 0xD800) << 10) + (ord(s[i + 1]) - 0xDC00)))
            i += 2
        elif 0xD800 <= c <= 0xDFFF:
            out.append("�")
            i += 1
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def u16_encode_utf8(s: str) -> bytes:
    """UTF-8 encode a u16-form string the way a JS engine would."""
    return from_u16(s).encode("utf-8")


def utf8_decode_u16(b: bytes) -> str:
    """Decode UTF-8 bytes into u16 form."""
    return to_u16(b.decode("utf-8"))
