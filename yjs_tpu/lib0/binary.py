"""Bit constants (mirrors the lib0/binary module used throughout the
reference wire format, e.g. reference src/structs/Item.js:629-632)."""

BIT1 = 1
BIT2 = 2
BIT3 = 4
BIT4 = 8
BIT5 = 16
BIT6 = 32
BIT7 = 64
BIT8 = 128

BITS5 = 0b11111
BITS6 = 0b111111
BITS7 = 0b1111111
BITS31 = 0x7FFFFFFF
BITS32 = 0xFFFFFFFF
