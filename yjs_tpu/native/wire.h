// Shared wire-format scanners/writers for the native transcoder and the
// native plan builder (plancore.cpp).  Header-only; every definition is
// inline-safe inside namespace ytpu_wire.  Extracted verbatim from
// transcode.cpp — see that file's header comment for the format notes
// (reference src/utils/encoding.js:127-198, UpdateDecoder.js:270-293,
// DeleteSet.js:270-285).
#pragma once
#include <cstdint>
#include <cstddef>
#include <vector>

namespace ytpu_wire {

struct Reader {
  const uint8_t* buf;
  uint64_t len;
  uint64_t pos;
  bool fail;

  uint8_t u8() {
    if (pos >= len) { fail = true; return 0; }
    return buf[pos++];
  }

  // lib0 varuint (7 bits per byte, little-endian groups).  Fast paths
  // for the 1- and 2-byte encodings that dominate wire traffic.
  uint64_t varuint() {
    if (pos < len) {
      uint8_t r0 = buf[pos];
      if (r0 < 0x80) {
        pos++;
        return r0;
      }
      if (pos + 1 < len) {
        uint8_t r1 = buf[pos + 1];
        if (r1 < 0x80) {
          pos += 2;
          return (uint64_t)(r0 & 0x7f) | ((uint64_t)r1 << 7);
        }
      }
    }
    uint64_t num = 0;
    int shift = 0;
    while (true) {
      if (pos >= len || shift > 63) { fail = true; return 0; }
      uint8_t r = buf[pos++];
      num |= (uint64_t)(r & 0x7f) << shift;
      shift += 7;
      if (r < 0x80) return num;
    }
  }

  // lib0 varint: first byte holds sign bit 0x40 and 6 bits of payload
  void varint() {
    if (pos >= len) { fail = true; return; }
    uint8_t r = buf[pos++];
    if (r < 0x80) return;
    int shift = 6;
    while (true) {
      if (pos >= len || shift > 63) { fail = true; return; }
      uint8_t c = buf[pos++];
      shift += 7;
      if (c < 0x80) return;
    }
  }

  void skip(uint64_t n) {
    if (n > len - pos) { fail = true; return; }  // overflow-safe bound check
    pos += n;
  }

  // var_string: varuint byte length + utf8; returns (ofs, bytelen)
  void var_string(uint64_t* ofs, uint64_t* blen) {
    uint64_t n = varuint();
    *ofs = pos;
    *blen = n;
    skip(n);
  }

  // UTF-16 code-unit count of a utf8 range (JS string .length
  // semantics).  Malformed sequences — bad lead byte, missing/invalid
  // continuation bytes (must be 0x80-0xBF), truncation — set `fail`, so
  // adversarial bytes take the demote-to-Python path instead of
  // silently miscounting (ADVICE r3: the Python decoder raises here)
  uint64_t utf16_len(uint64_t ofs, uint64_t blen) {
    uint64_t units = 0;
    uint64_t end = ofs + blen;
    if (end > len) { fail = true; return 0; }
    for (uint64_t i = ofs; i < end; ) {
      // ASCII fast path: count 8 valid bytes per iteration
      while (i + 8 <= end) {
        uint64_t w;
        __builtin_memcpy(&w, buf + i, 8);
        if (w & 0x8080808080808080ull) break;
        units += 8;
        i += 8;
      }
      if (i >= end) break;
      uint8_t b = buf[i];
      uint64_t n;
      if (b < 0x80) { n = 1; units += 1; }
      else if (b < 0xC2) { fail = true; return 0; }  // continuation/overlong lead
      else if (b < 0xE0) { n = 2; units += 1; }
      else if (b < 0xF0) { n = 3; units += 1; }
      else if (b < 0xF5) { n = 4; units += 2; }
      else { fail = true; return 0; }                // > U+10FFFF lead
      if (i + n > end) { fail = true; return 0; }    // truncated sequence
      for (uint64_t j = 1; j < n; j++) {
        if ((buf[i + j] & 0xC0) != 0x80) { fail = true; return 0; }
      }
      i += n;
    }
    return units;
  }

  // skip one lib0 "any" value
  void skip_any(int depth = 0) {
    if (depth > 64) { fail = true; return; }
    uint8_t tag = u8();
    if (fail) return;
    switch (tag) {
      case 127: case 126: case 121: case 120: break;  // undefined/null/bools
      case 125: varint(); break;
      case 124: skip(4); break;                        // float32
      case 123: skip(8); break;                        // float64
      case 122: skip(8); break;                        // bigint64
      case 119: { uint64_t o, b; var_string(&o, &b); break; }
      case 118: {                                      // object
        uint64_t n = varuint();
        for (uint64_t i = 0; i < n && !fail; i++) {
          uint64_t o, b; var_string(&o, &b);
          skip_any(depth + 1);
        }
        break;
      }
      case 117: {                                      // array
        uint64_t n = varuint();
        for (uint64_t i = 0; i < n && !fail; i++) skip_any(depth + 1);
        break;
      }
      case 116: { uint64_t n = varuint(); skip(n); break; }  // uint8array
      default: fail = true;
    }
  }
};

constexpr uint8_t kBit6 = 0x20, kBit7 = 0x40, kBit8 = 0x80, kBits5 = 0x1f;

// ---------------------------------------------------------------------------
// V2: lib0 stream decoders over sub-ranges of the update buffer
// (mirrors yjs_tpu/lib0/decoding.py RleDecoder / UintOptRleDecoder /
// IntDiffOptRleDecoder / StringDecoder; reference UpdateDecoder.js:270-293)
// ---------------------------------------------------------------------------

// lib0 signed varint: first byte = sign bit 0x40 + 6 payload bits
inline void varint_signed(Reader* r, int64_t* num, int* sign) {
  if (r->pos >= r->len) { r->fail = true; *num = 0; *sign = 1; return; }
  uint8_t b = r->buf[r->pos++];
  *num = b & 0x3f;
  *sign = (b & kBit7) ? -1 : 1;
  if ((b & kBit8) == 0) return;
  int shift = 6;
  while (true) {
    if (r->pos >= r->len || shift > 63) { r->fail = true; return; }
    uint8_t c = r->buf[r->pos++];
    *num |= (int64_t)(c & 0x7f) << shift;
    shift += 7;
    if (c < 0x80) return;
  }
}

struct RleU8 {  // RleDecoder(read_uint8): u8 value + (varuint count + 1)
  Reader r;
  int64_t s = 0, count = 0;
  int64_t read() {
    if (count == 0) {
      s = r.u8();
      if (r.pos < r.len) count = (int64_t)r.varuint() + 1;
      else count = INT64_MAX;  // final value repeats forever
    }
    count--;
    return s;
  }
};

struct UintOptRle {
  Reader r;
  int64_t s = 0, count = 0;
  int64_t read() {
    if (count == 0) {
      int sign; varint_signed(&r, &s, &sign);
      count = 1;
      if (sign < 0) count = (int64_t)r.varuint() + 2;
    }
    count--;
    return s;
  }
};

struct IntDiffOptRle {
  Reader r;
  int64_t s = 0, count = 0, diff = 0;
  int64_t read() {
    if (count == 0) {
      int64_t num; int sign; varint_signed(&r, &num, &sign);
      int64_t d = sign * num;
      bool has_count = (d & 1) != 0;
      diff = d >> 1;  // arithmetic shift = floor div 2 (also for negatives)
      count = has_count ? (int64_t)r.varuint() + 2 : 1;
    }
    s += diff;
    count--;
    return s;
  }
};

struct StringDec {  // one UTF-8 arena + UintOptRle of UTF-16 lengths
  UintOptRle lens;
  uint64_t arena_ofs = 0, arena_end = 0, cursor = 0;
  const uint8_t* buf = nullptr;

  void init(const uint8_t* b, uint64_t slice_start, uint64_t slice_end) {
    buf = b;
    lens.r = Reader{b, slice_end, slice_start, false};
    uint64_t blen = lens.r.varuint();
    arena_ofs = lens.r.pos;
    lens.r.skip(blen);
    arena_end = lens.r.pos;
    cursor = arena_ofs;
  }

  // consume one string; returns absolute (ofs, end) byte range of its
  // UTF-8.  Continuation bytes are validated (0x80-0xBF) so malformed
  // arenas fail the scan (-> demote-to-Python) instead of miscounting
  void read(int64_t* ofs, int64_t* end) {
    int64_t units = lens.read();
    *ofs = (int64_t)cursor;
    uint64_t i = cursor;
    int64_t got = 0;
    while (got < units && i < arena_end) {
      uint8_t b = buf[i];
      uint64_t n;
      if (b < 0x80) { n = 1; got += 1; }
      else if (b < 0xC2) { lens.r.fail = true; break; }
      else if (b < 0xE0) { n = 2; got += 1; }
      else if (b < 0xF0) { n = 3; got += 1; }
      else if (b < 0xF5) { n = 4; got += 2; }
      else { lens.r.fail = true; break; }
      if (i + n > arena_end) { lens.r.fail = true; break; }
      for (uint64_t j = 1; j < n; j++) {
        if ((buf[i + j] & 0xC0) != 0x80) { lens.r.fail = true; break; }
      }
      if (lens.r.fail) break;
      i += n;
    }
    if (got != units || i > arena_end) lens.r.fail = true;
    cursor = i;
    *end = (int64_t)i;
  }

  bool failed() const { return lens.r.fail; }
};

struct V2Streams {
  IntDiffOptRle key_clock;
  UintOptRle client;
  IntDiffOptRle left_clock;
  IntDiffOptRle right_clock;
  RleU8 info;
  StringDec str;
  RleU8 parent_info;
  UintOptRle type_ref;
  UintOptRle len;
  Reader rest;  // counts, clocks, DS section, rest-stream contents
  // read_key cache: ranges of previously seen keys (parent_sub
  // dictionary) — grows without bound like the reference's JS array
  // (UpdateDecoder.js:370-393); the old 4096-entry cap silently demoted
  // wide-key docs to the CPU core (ADVICE r3)
  std::vector<int64_t> key_ofs, key_end;

  bool init(const uint8_t* buf, uint64_t blen) {
    Reader r{buf, blen, 0, false};
    r.u8();  // feature flag (always 0 in v13.4)
    uint64_t o, n;
    auto slice = [&](auto setup) {
      n = r.varuint(); o = r.pos; r.skip(n);
      if (!r.fail) setup(o, o + n);
    };
    slice([&](uint64_t a, uint64_t b) { key_clock.r = Reader{buf, b, a, false}; });
    slice([&](uint64_t a, uint64_t b) { client.r = Reader{buf, b, a, false}; });
    slice([&](uint64_t a, uint64_t b) { left_clock.r = Reader{buf, b, a, false}; });
    slice([&](uint64_t a, uint64_t b) { right_clock.r = Reader{buf, b, a, false}; });
    slice([&](uint64_t a, uint64_t b) { info.r = Reader{buf, b, a, false}; });
    slice([&](uint64_t a, uint64_t b) { str.init(buf, a, b); });
    slice([&](uint64_t a, uint64_t b) { parent_info.r = Reader{buf, b, a, false}; });
    slice([&](uint64_t a, uint64_t b) { type_ref.r = Reader{buf, b, a, false}; });
    slice([&](uint64_t a, uint64_t b) { len.r = Reader{buf, b, a, false}; });
    if (r.fail) return false;
    rest = Reader{buf, blen, r.pos, false};
    return true;
  }

  void read_key(int64_t* ofs, int64_t* end) {  // UpdateDecoder.js:382-391
    int64_t kc = key_clock.read();
    if (kc >= 0 && (size_t)kc < key_ofs.size()) {
      *ofs = key_ofs[(size_t)kc];
      *end = key_end[(size_t)kc];
      return;
    }
    str.read(ofs, end);
    key_ofs.push_back(*ofs);
    key_end.push_back(*end);
  }

  bool any_fail() {
    return key_clock.r.fail || client.r.fail || left_clock.r.fail ||
           right_clock.r.fail || info.r.fail || str.failed() ||
           parent_info.r.fail || type_ref.r.fail || len.r.fail || rest.fail;
  }
};

struct StructOut2 {
  int64_t *client, *clock, *length;
  int64_t *origin_client, *origin_clock;
  int64_t *right_client, *right_clock;
  int64_t *info;
  int64_t *parent_name_ofs, *parent_name_len;
  int64_t *parent_id_client, *parent_id_clock;
  int64_t *parent_sub_ofs, *parent_sub_len;
  int64_t *content_ofs, *content_end;     // kind-specific primary range
  int64_t *content_ofs2, *content_end2;   // secondary range (Format value …)
  int64_t *content_count;                 // element count / type_ref
};

// Parse the V2 struct section.  When out == nullptr, only counts.
inline uint64_t parse_structs_v2(V2Streams* v, StructOut2* out, int* err) {
  uint64_t idx = 0;
  Reader* rest = &v->rest;
  uint64_t n_updates = rest->varuint();
  for (uint64_t u = 0; u < n_updates && !rest->fail; u++) {
    uint64_t n_structs = rest->varuint();
    int64_t client = v->client.read();
    uint64_t clock = rest->varuint();
    for (uint64_t s = 0; s < n_structs; s++) {
      if (v->any_fail()) { *err = -1; return idx; }
      uint8_t info = (uint8_t)v->info.read();
      uint8_t ref = info & kBits5;
      int64_t oc = -1, ok = 0, rc = -1, rk = 0;
      int64_t pno = -1, pne = -1, pic = -1, pik = -1, pso = -1, pse = -1;
      int64_t c_ofs = -1, c_end = -1, c_ofs2 = -1, c_end2 = -1, c_cnt = -1;
      int64_t length = 0;
      if (ref != 0) {
        if (info & kBit8) { oc = v->client.read(); ok = v->left_clock.read(); }
        if (info & kBit7) { rc = v->client.read(); rk = v->right_clock.read(); }
        if (!(info & (kBit7 | kBit8))) {
          if (v->parent_info.read() == 1) {
            v->str.read(&pno, &pne);
          } else {
            pic = v->client.read(); pik = v->left_clock.read();
          }
          if (info & kBit6) v->str.read(&pso, &pse);
        }
        switch (ref) {
          case 1: length = v->len.read(); break;            // ContentDeleted
          case 3: {                                         // ContentBinary
            c_ofs = (int64_t)rest->pos;
            uint64_t n = rest->varuint(); rest->skip(n);
            c_end = (int64_t)rest->pos;
            length = 1;
            break;
          }
          case 4: {                                         // ContentString
            v->str.read(&c_ofs, &c_end);
            // UTF-16 unit length = what the arena scan consumed
            length = v->str.lens.s;
            break;
          }
          case 5: {                                         // ContentEmbed
            c_ofs = (int64_t)rest->pos;
            rest->skip_any();
            c_end = (int64_t)rest->pos;
            length = 1;
            break;
          }
          case 6: {                                         // ContentFormat
            v->str.read(&c_ofs, &c_end);                    // key string
            c_ofs2 = (int64_t)rest->pos;
            rest->skip_any();                               // json value
            c_end2 = (int64_t)rest->pos;
            length = 1;
            break;
          }
          case 7: {                                         // ContentType
            c_cnt = v->type_ref.read();
            // XmlElement / XmlHook names go through the key dictionary
            // (readYXmlElement: decoder.readKey(), YXmlElement.js:225)
            if (c_cnt == 3 || c_cnt == 5) v->read_key(&c_ofs, &c_end);
            length = 1;
            break;
          }
          case 8: {                                         // ContentAny
            c_cnt = v->len.read();
            c_ofs = (int64_t)rest->pos;
            for (int64_t i = 0; i < c_cnt && !rest->fail; i++) rest->skip_any();
            c_end = (int64_t)rest->pos;
            length = c_cnt;
            break;
          }
          case 2:                                           // ContentJSON
          case 9:                                           // ContentDoc
          default:
            // legacy / subdoc payloads: punt the whole update to the
            // Python decoder (they demote the doc off the device path
            // anyway)
            *err = -4;
            return idx;
        }
      } else {
        length = v->len.read();                             // GC
      }
      if (v->any_fail()) { *err = -1; return idx; }
      if (length == 0 && ref != 0) { *err = -1; return idx; }
      if (out != nullptr) {
        out->client[idx] = client;
        out->clock[idx] = (int64_t)clock;
        out->length[idx] = length;
        out->origin_client[idx] = oc; out->origin_clock[idx] = ok;
        out->right_client[idx] = rc; out->right_clock[idx] = rk;
        out->info[idx] = info;
        out->parent_name_ofs[idx] = pno;
        out->parent_name_len[idx] = pno < 0 ? -1 : pne - pno;
        out->parent_id_client[idx] = pic; out->parent_id_clock[idx] = pik;
        out->parent_sub_ofs[idx] = pso;
        out->parent_sub_len[idx] = pso < 0 ? -1 : pse - pso;
        out->content_ofs[idx] = c_ofs; out->content_end[idx] = c_end;
        out->content_ofs2[idx] = c_ofs2; out->content_end2[idx] = c_end2;
        out->content_count[idx] = c_cnt;
      }
      idx++;
      clock += (uint64_t)length;
    }
  }
  if (rest->fail) *err = -1;
  return idx;
}

// V2 DS section (coding.py DSDecoderV2: delta-varint clocks, len-1 wire)
inline uint64_t parse_ds_v2(Reader* r, int64_t* ds_client, int64_t* ds_clock,
                     int64_t* ds_len) {
  uint64_t idx = 0;
  uint64_t n_clients = r->varuint();
  for (uint64_t c = 0; c < n_clients && !r->fail; c++) {
    int64_t cur = 0;
    uint64_t client = r->varuint();
    uint64_t n = r->varuint();
    for (uint64_t i = 0; i < n && !r->fail; i++) {
      cur += (int64_t)r->varuint();
      int64_t clock = cur;
      int64_t len = (int64_t)r->varuint() + 1;
      cur += len;
      if (ds_client != nullptr) {
        ds_client[idx] = (int64_t)client;
        ds_clock[idx] = clock;
        ds_len[idx] = len;
      }
      idx++;
    }
  }
  return idx;
}

struct StructOut {
  int64_t *client, *clock, *length;
  int64_t *origin_client, *origin_clock;
  int64_t *right_client, *right_clock;
  int64_t *info;
  int64_t *parent_name_ofs, *parent_name_len;
  int64_t *parent_id_client, *parent_id_clock;
  int64_t *parent_sub_ofs, *parent_sub_len;
  int64_t *content_ofs, *content_end;
};

// Parse the struct section.  When out == nullptr, only counts.
// Returns the number of structs, or sets r->fail.
inline uint64_t parse_structs(Reader* r, StructOut* out) {
  uint64_t idx = 0;
  uint64_t n_updates = r->varuint();
  for (uint64_t u = 0; u < n_updates && !r->fail; u++) {
    uint64_t n_structs = r->varuint();
    uint64_t client = r->varuint();
    uint64_t clock = r->varuint();
    for (uint64_t s = 0; s < n_structs && !r->fail; s++) {
      uint8_t info = r->u8();
      uint8_t ref = info & kBits5;
      int64_t oc = -1, ok = 0, rc = -1, rk = 0;
      int64_t pno = -1, pnl = -1, pic = -1, pik = -1, pso = -1, psl = -1;
      uint64_t length = 0, c_ofs = 0, c_end = 0;
      if (ref != 0) {
        if (info & kBit8) { oc = (int64_t)r->varuint(); ok = (int64_t)r->varuint(); }
        if (info & kBit7) { rc = (int64_t)r->varuint(); rk = (int64_t)r->varuint(); }
        if (!(info & (kBit7 | kBit8))) {
          if (r->varuint() == 1) {                       // parent is root name
            uint64_t o, b; r->var_string(&o, &b);
            pno = (int64_t)o; pnl = (int64_t)b;
          } else {                                       // parent is an id
            pic = (int64_t)r->varuint(); pik = (int64_t)r->varuint();
          }
          if (info & kBit6) {
            uint64_t o, b; r->var_string(&o, &b);
            pso = (int64_t)o; psl = (int64_t)b;
          }
        }
        c_ofs = r->pos;
        switch (ref) {
          case 1: length = r->varuint(); break;          // ContentDeleted
          case 2: {                                      // ContentJSON
            uint64_t n = r->varuint();
            for (uint64_t i = 0; i < n && !r->fail; i++) {
              uint64_t o, b; r->var_string(&o, &b);
            }
            length = n;
            break;
          }
          case 3: { uint64_t n = r->varuint(); r->skip(n); length = 1; break; }
          case 4: {                                      // ContentString
            uint64_t o, b; r->var_string(&o, &b);
            length = r->utf16_len(o, b);
            break;
          }
          case 5: {                                      // ContentEmbed (json string)
            uint64_t o, b; r->var_string(&o, &b);
            length = 1;
            break;
          }
          case 6: {                                      // ContentFormat
            uint64_t o, b;
            r->var_string(&o, &b);                       // key
            r->var_string(&o, &b);                       // json value
            length = 1;
            break;
          }
          case 7: {                                      // ContentType
            uint64_t tref = r->varuint();
            if (tref == 3 || tref == 5) {                // XmlElement / XmlHook
              uint64_t o, b; r->var_string(&o, &b);
            }
            length = 1;
            break;
          }
          case 8: {                                      // ContentAny
            uint64_t n = r->varuint();
            for (uint64_t i = 0; i < n && !r->fail; i++) r->skip_any();
            length = n;
            break;
          }
          case 9: {                                      // ContentDoc
            uint64_t o, b; r->var_string(&o, &b);        // guid
            r->skip_any();                               // opts
            length = 1;
            break;
          }
          default: r->fail = true;
        }
        c_end = r->pos;
      } else {
        length = r->varuint();                           // GC
      }
      if (r->fail) break;
      if (length == 0 && ref != 0) { r->fail = true; break; }
      if (out != nullptr) {
        out->client[idx] = (int64_t)client;
        out->clock[idx] = (int64_t)clock;
        out->length[idx] = (int64_t)length;
        out->origin_client[idx] = oc; out->origin_clock[idx] = ok;
        out->right_client[idx] = rc; out->right_clock[idx] = rk;
        out->info[idx] = info;
        out->parent_name_ofs[idx] = pno; out->parent_name_len[idx] = pnl;
        out->parent_id_client[idx] = pic; out->parent_id_clock[idx] = pik;
        out->parent_sub_ofs[idx] = pso; out->parent_sub_len[idx] = psl;
        out->content_ofs[idx] = (int64_t)c_ofs; out->content_end[idx] = (int64_t)c_end;
      }
      idx++;
      clock += length;
    }
  }
  return idx;
}

inline uint64_t parse_ds(Reader* r, int64_t* ds_client, int64_t* ds_clock, int64_t* ds_len) {
  uint64_t idx = 0;
  uint64_t n_clients = r->varuint();
  for (uint64_t c = 0; c < n_clients && !r->fail; c++) {
    uint64_t client = r->varuint();
    uint64_t n = r->varuint();
    for (uint64_t i = 0; i < n && !r->fail; i++) {
      uint64_t clock = r->varuint();
      uint64_t len = r->varuint();
      if (ds_client != nullptr) {
        ds_client[idx] = (int64_t)client;
        ds_clock[idx] = (int64_t)clock;
        ds_len[idx] = (int64_t)len;
      }
      idx++;
    }
  }
  return idx;
}

// ---------------------------------------------------------------------------
// V1 wire encoder: mirror columns -> update bytes (the writer half of sync
// step 2 / update emission; reference encoding.js:71-116, Item.js:625-658,
// GC.js:45-48, DeleteSet.js:219-232).  Content bytes are memcpy'd from the
// source update buffers the rows were decoded from (payloads never transit
// Python), except spill rows the caller pre-encoded.
// ---------------------------------------------------------------------------

struct Writer {
  uint8_t* out;
  uint64_t cap, pos;
  bool fail;

  void u8(uint8_t b) {
    if (pos >= cap) { fail = true; return; }
    out[pos++] = b;
  }

  void varuint(uint64_t num) {
    while (num > 0x7f) {
      u8(0x80 | (num & 0x7f));
      num >>= 7;
    }
    u8((uint8_t)num);
  }

  void bytes(const uint8_t* src, uint64_t n) {
    if (n > cap - pos) { fail = true; return; }
    for (uint64_t i = 0; i < n; i++) out[pos + i] = src[i];
    pos += n;
  }
};

// content-source kinds (matches yjs_tpu/native/__init__.py encode wrapper)
constexpr int64_t kSrcNone = 0;      // GC row: no content bytes
constexpr int64_t kSrcDeleted = 1;   // ContentDeleted: varuint(len - offset)
constexpr int64_t kSrcFramed = 2;    // V1-framed bytes, memcpy (offset == 0)
constexpr int64_t kSrcUtf8 = 3;      // raw UTF-8 string bytes -> var_string
constexpr int64_t kSrcSpill = 4;     // caller-framed bytes, offset applied
// element ranges from the native plan builder (plancore.cpp): `length`
// elements at [ofs,end); encode emits varuint(length-offset) + the elements
// from `offset` on (ContentAny lib0 any values / ContentJSON var_strings)
constexpr int64_t kSrcAnys = 5;
constexpr int64_t kSrcJsons = 6;

// write a var_string from raw UTF-8, skipping `offset` UTF-16 units; a cut
// landing inside a surrogate pair (4-byte char) emits U+FFFD for the lone
// low surrogate, exactly like the Python u16 wire encode (lib0/u16.py)
inline void write_cut_string(Writer* w, const uint8_t* s, uint64_t blen,
                      int64_t offset) {
  uint64_t i = 0;
  bool mid_pair = false;
  int64_t skipped = 0;
  while (skipped < offset && i < blen) {
    uint8_t b = s[i];
    if (b < 0x80) { skipped += 1; i += 1; }
    else if (b < 0xE0) { skipped += 1; i += 2; }
    else if (b < 0xF0) { skipped += 1; i += 3; }
    else {
      if (skipped + 2 <= offset) { skipped += 2; i += 4; }
      else {  // cut lands between the pair's units
        skipped += 2;  // consume the char; emit replacement low half
        i += 4;
        mid_pair = true;
      }
    }
  }
  uint64_t body = blen - i;
  w->varuint(body + (mid_pair ? 3 : 0));
  if (mid_pair) { w->u8(0xEF); w->u8(0xBF); w->u8(0xBD); }
  w->bytes(s + i, body);
}

}  // namespace ytpu_wire
