// Native plan builder: the persistent host mirror of one document's struct
// columns, with the full flush pipeline (wire scan -> causal schedule ->
// pre-split -> row assignment -> level-parallel schedule) implemented in
// C++.  This is the C++ twin of yjs_tpu/ops/columns.py DocMirror
// (reference pipeline: src/utils/encoding.js:127-198,225-321 decode +
// dependency-stack integration, src/structs/Item.js:84-120 splitItem,
// :354-397 getMissing, :403-517 integrate; recast as the columnar plan of
// SURVEY.md §7).  Python keeps a semantically identical pure-Python
// implementation as the conformance oracle; the differential fuzz tests
// assert plan-for-plan equality between the two.
//
// Ownership/ABI: one `Mirror` per doc behind an opaque handle.  Update
// buffers are borrowed (Python keeps the bytes objects alive and passes
// stable pointers); synthesized content (surrogate-straddling splits,
// compaction merges) lives in mirror-owned arena buffers registered in the
// same buffer table.  All plan/state getters fill caller-allocated numpy
// arrays.  Row content is described by (src_kind, buf, ofs, end, ...)
// descriptor columns; Python realizes payload objects lazily from these.
//
// Threading contract: a Mirror handle must NOT be used from two threads
// concurrently — even read-only getters may touch mutable lookup hints
// (frag_hint).  The ymx_prepare_many worker pool honors this by
// parallelizing ACROSS doc handles, never within one; Python callers that
// share a doc across threads must serialize per doc (BatchEngine does —
// all native calls for a doc happen on the flush thread).

#include "wire.h"

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <thread>

#include <algorithm>
#include <array>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <tuple>
#include <unordered_map>
#include <vector>

using namespace ytpu_wire;

namespace {

constexpr int64_t kNull = -1;
// sched8 sentinels (shared with yjs_tpu/ops/kernels.py)
constexpr int64_t kNoLeftWrite = -3;
constexpr int64_t kGatherSucc = -2;

// content-source kinds (superset of yjs_tpu/native/__init__.py SRC_*)
constexpr int64_t kKindNone = 0;     // GC row
constexpr int64_t kKindDeleted = 1;  // ContentDeleted: length only
constexpr int64_t kKindFramed = 2;   // V1-framed bytes, verbatim range
constexpr int64_t kKindUtf8 = 3;     // raw UTF-8 of a ContentString
constexpr int64_t kKindSpill = 4;    // Python-realized (never produced here)
constexpr int64_t kKindAnys = 5;     // `count` lib0 any values at [ofs,end)
constexpr int64_t kKindJsons = 6;    // `count` ContentJSON var_strings
constexpr int64_t kKindV2Lazy = 7;   // V2 embed/format/type byte ranges

// error codes returned by ymx_prepare / ymx_ingest helpers
constexpr int kErrMalformed = -1;    // bad bytes: caller retries via Python
constexpr int kErrUnsupported = -9;  // subdocument: demote doc to CPU core
constexpr int kErrLegacy = -4;       // payload kind the scanner won't carry
constexpr int kErrInternal = -8;

// chain-run anchor adoption (the native twin of the segment planner's
// fast set, ISSUE 15): when a scheduled ref's origin/rightOrigin sits
// inside the row emit_row just produced — typing and prepend chains —
// the anchor is adopted in O(1) instead of re-running the per-slot
// fragment binary search.  Gated by YTPU_PLAN_SEGMENT=off through
// ymx_set_plan_segment; hit/lookup totals feed the flush metrics.
std::atomic<int> g_plan_segment{1};
std::atomic<long long> g_seg_fast{0};
std::atomic<long long> g_seg_lookup{0};

struct ContentDesc {
  int64_t kind = kKindNone;
  int64_t buf = kNull;
  int64_t ofs = kNull, end = kNull;
  int64_t ofs2 = kNull, end2 = kNull;
  int64_t count = kNull;  // elements (ANYS/JSONS) or v2 type_ref (V2Lazy k7)
  int64_t v2 = 0;         // source wire version (realize dispatch)
};

struct PendRef {
  int64_t client = 0, clock = 0, length = 0;
  int64_t oc = kNull, ok = 0;    // origin (client, clock); oc<0 = none
  int64_t rc = kNull, rk = 0;    // rightOrigin
  int64_t pic = kNull, pik = 0;  // parent type-item id
  int64_t name_id = kNull;       // interned root-type name
  int64_t sub_id = kNull;        // interned parentSub
  int64_t ref = 0;               // wire content ref (0 = GC)
  bool is_gc = false;
  ContentDesc c;
};

struct Plan {
  int64_t n_rows = 0;
  std::vector<std::array<int64_t, 2>> splits;
  std::vector<std::array<int64_t, 4>> sched;
  std::vector<int64_t> delete_rows;
  std::vector<std::array<int64_t, 3>> applied_ds;
  std::vector<std::array<int64_t, 8>> sched8;
  std::vector<int64_t> levels;
  int64_t n_levels = 0;
  int64_t max_width = 0;
  // bulk-apply form: FINAL link/head values of everything this step
  // changed (host-resolved YATA; see Mirror::list_insert).  Dedup rides
  // epoch marks in the Mirror (mark_link/mark_head); the finalize pass
  // sorts, matching the Python twin's `sorted(plan._dl)`.
  std::vector<int64_t> dirty_links, dirty_heads;
  std::vector<int64_t> link_rows, link_vals, head_segs, head_vals;

  void clear() {
    n_rows = 0;
    splits.clear();
    sched.clear();
    delete_rows.clear();
    applied_ds.clear();
    sched8.clear();
    levels.clear();
    n_levels = 0;
    max_width = 0;
    dirty_links.clear();
    dirty_heads.clear();
    link_rows.clear();
    link_vals.clear();
    head_segs.clear();
    head_vals.clear();
  }
};

struct Mirror {
  // client <-> dense slot mapping (creation order = Python slot())
  std::vector<int64_t> client_of_slot;
  std::unordered_map<int64_t, int64_t> slot_of_client;
  // per-slot fragment index sorted by clock, and next expected clock
  std::vector<std::vector<int64_t>> frag_clock, frag_row;
  // per-slot last frag_containing hit: lookups chain forward one fragment
  // at a time (origin cuts / delete walks), so checking hint and hint+1
  // before the binary search hits most of the time.  Purely an index
  // guess — every use re-verifies bounds against the live frag lists, so
  // stale values (splits/compaction reindexing) cost a miss, never a
  // wrong answer.
  mutable std::vector<int64_t> frag_hint;
  std::vector<int64_t> state;

  // per-row columns
  std::vector<int64_t> r_slot, r_clock, r_len;
  std::vector<int64_t> r_oslot, r_oclock, r_rslot, r_rclock;
  std::vector<int64_t> r_ref, r_seg;
  std::vector<uint8_t> r_is_gc, r_countable;
  std::vector<ContentDesc> r_c;
  std::vector<uint8_t> r_host_deleted, r_lww_deleted;

  // segment registry: (name_id, sub_id, parent_row) -> seg, creation order
  std::map<std::tuple<int64_t, int64_t, int64_t>, int64_t> seg_lookup;
  std::vector<int64_t> seg_name_id, seg_sub_id, seg_parent;
  std::unordered_map<int64_t, std::vector<int64_t>> segs_of_parent;
  std::unordered_map<int64_t, std::vector<int64_t>> rows_of_seg;  // nested only
  std::unordered_map<int64_t, std::vector<int64_t>> map_chain;
  // host linked lists: the mirror of the device right_link/starts state
  // (the planner resolves YATA placement against these, so each flush
  // ships final link values)
  std::vector<int64_t> list_next;
  std::vector<int64_t> head_of_seg;

  // interned strings (UTF-8 blob + ranges); key = raw bytes
  std::vector<uint8_t> strings;
  std::unordered_map<std::string, int64_t> interned;
  std::vector<int64_t> intern_ofs, intern_len;

  // delete-set bookkeeping: per-slot ranges (slot-indexed — slots are
  // dense small ints, so indexing beats hashing per deleted row) + slot
  // first-note order; a slot is "present" iff its range list is
  // non-empty (note_deleted is the only writer and never leaves one
  // empty)
  std::vector<std::vector<std::array<int64_t, 2>>> ds;
  std::vector<int64_t> ds_slot_order;

  // pending causally-early refs per client + pending delete ranges
  std::map<int64_t, std::vector<PendRef>> pending;
  std::vector<std::array<int64_t, 3>> pending_ds;

  // buffer registry: borrowed update bytes + owned arena blocks
  std::vector<std::pair<const uint8_t*, uint64_t>> bufs;
  std::vector<std::unique_ptr<std::vector<uint8_t>>> owned;

  Plan plan;
  uint64_t gen = 0;

  // dedup epochs for Plan.dirty_links/dirty_heads (one bump per prepare);
  // tm_mark dedups the touched-map-segs list in the rows loop
  std::vector<uint64_t> dl_mark, dh_mark, tm_mark;
  uint64_t dirty_epoch = 0;
  // list_insert conflict-scan marks: visited-walk id + visit order per row
  // (replaces two std::set<int64_t> per insert with O(1) membership)
  std::vector<uint64_t> walk_mark, walk_order;
  uint64_t walk_id = 0;
  // bump-allocated arena chunk for small synthesized buffers (surrogate
  // repairs); chunks live in `owned`, so their bytes never move
  int64_t cur_chunk = kNull;
  size_t chunk_used = 0;

  // ---- interning / slots / segments -------------------------------------

  int64_t intern(const uint8_t* p, int64_t n) {
    std::string key(reinterpret_cast<const char*>(p), (size_t)n);
    auto it = interned.find(key);
    if (it != interned.end()) return it->second;
    int64_t id = (int64_t)intern_ofs.size();
    intern_ofs.push_back((int64_t)strings.size());
    intern_len.push_back(n);
    strings.insert(strings.end(), p, p + n);
    interned.emplace(std::move(key), id);
    return id;
  }

  // tiny round-robin cache: each ref touches up to three clients (self,
  // origin, right-origin), so one entry thrashes; four cover the working
  // set.  Slots are never removed, so entries can only go stale on
  // nothing — a cached (client, slot) pair stays true forever.
  static constexpr int kSlotCache = 4;
  int64_t slot_cache_cl[kSlotCache] = {INT64_MIN, INT64_MIN, INT64_MIN,
                                       INT64_MIN};
  int64_t slot_cache_v[kSlotCache] = {kNull, kNull, kNull, kNull};
  int slot_cache_pos = 0;

  int64_t slot(int64_t client) {
    for (int i = 0; i < kSlotCache; i++)
      if (slot_cache_cl[i] == client) return slot_cache_v[i];
    int64_t s;
    auto it = slot_of_client.find(client);
    if (it != slot_of_client.end()) {
      s = it->second;
    } else {
      s = (int64_t)client_of_slot.size();
      slot_of_client.emplace(client, s);
      client_of_slot.push_back(client);
      frag_clock.emplace_back();
      frag_row.emplace_back();
      frag_hint.push_back(0);
      state.push_back(0);
    }
    slot_cache_cl[slot_cache_pos] = client;
    slot_cache_v[slot_cache_pos] = s;
    slot_cache_pos = (slot_cache_pos + 1) & (kSlotCache - 1);
    return s;
  }

  int64_t get_state(int64_t client) const {
    auto it = slot_of_client.find(client);
    return it == slot_of_client.end() ? 0 : state[it->second];
  }

  int64_t n_rows() const { return (int64_t)r_slot.size(); }
  int64_t n_segs() const { return (int64_t)seg_name_id.size(); }
  bool seg_is_map(int64_t s) const { return seg_sub_id[s] != kNull; }

  int64_t seg(int64_t name_id, int64_t sub_id, int64_t parent_row) {
    auto key = std::make_tuple(name_id, sub_id, parent_row);
    auto it = seg_lookup.find(key);
    if (it != seg_lookup.end()) return it->second;
    int64_t s = n_segs();
    seg_lookup.emplace(key, s);
    seg_name_id.push_back(name_id);
    seg_sub_id.push_back(sub_id);
    seg_parent.push_back(parent_row);
    head_of_seg.push_back(kNull);
    if (parent_row != kNull) segs_of_parent[parent_row].push_back(s);
    return s;
  }

  // ---- buffers / arena ---------------------------------------------------

  int64_t add_buf(const uint8_t* p, uint64_t n) {
    bufs.emplace_back(p, n);
    return (int64_t)bufs.size() - 1;
  }

  // synthesize an owned buffer (surrogate repairs, compaction merges)
  int64_t arena(std::vector<uint8_t>&& data) {
    owned.push_back(std::make_unique<std::vector<uint8_t>>(std::move(data)));
    auto& v = *owned.back();
    bufs.emplace_back(v.data(), (uint64_t)v.size());
    return (int64_t)bufs.size() - 1;
  }

  const uint8_t* buf_ptr(int64_t b) const { return bufs[(size_t)b].first; }
  uint64_t buf_len(int64_t b) const { return bufs[(size_t)b].second; }

  // two-part copy into the bump arena (surrogate repair buffers); avoids
  // a malloc'd std::vector per synthesized fragment
  static constexpr size_t kChunk = 1 << 16;
  int64_t arena2(const uint8_t* a, size_t na, const uint8_t* b, size_t nb) {
    size_t need = na + nb;
    if (need > kChunk) {
      std::vector<uint8_t> big;
      big.reserve(need);
      big.insert(big.end(), a, a + na);
      big.insert(big.end(), b, b + nb);
      return arena(std::move(big));
    }
    if (cur_chunk == kNull || chunk_used + need > kChunk) {
      owned.push_back(std::make_unique<std::vector<uint8_t>>(kChunk));
      cur_chunk = (int64_t)owned.size() - 1;
      chunk_used = 0;
    }
    uint8_t* dst = owned[(size_t)cur_chunk]->data() + chunk_used;
    std::memcpy(dst, a, na);
    if (nb) std::memcpy(dst + na, b, nb);
    chunk_used += need;
    bufs.emplace_back(dst, (uint64_t)need);
    return (int64_t)bufs.size() - 1;
  }

  // LSD radix sort for clock lists (non-negative, usually < 2^16): the
  // same ascending result std::sort produces, with branch-free counting
  // passes.  Scratch persists across prepares to avoid re-allocation.
  std::vector<int64_t> radix_tmp;

  void radix_sort_clocks(std::vector<int64_t>& v) {
    size_t n = v.size();
    if (n < 96) {  // small lists: introsort's constant wins
      std::sort(v.begin(), v.end());
      return;
    }
    int64_t mx = 0;
    bool neg = false;
    for (int64_t x : v) {
      mx = x > mx ? x : mx;
      neg |= x < 0;
    }
    if (neg) {  // outside the clock domain (hostile bytes): total order
      std::sort(v.begin(), v.end());
      return;
    }
    if (radix_tmp.size() < n) radix_tmp.resize(n);
    int64_t* src = v.data();
    int64_t* dst = radix_tmp.data();
    // shift < 64 bounds the pass loop even for mx >= 2^56 (a shift of 64
    // would be UB; byte 7 of a non-negative int64 is covered at shift 56)
    for (int shift = 0; shift < 64 && (mx >> shift) > 0; shift += 8) {
      size_t cnt[256] = {0};
      for (size_t i = 0; i < n; i++) cnt[(src[i] >> shift) & 0xFF]++;
      size_t sum = 0;
      for (int b = 0; b < 256; b++) {
        size_t c = cnt[b];
        cnt[b] = sum;
        sum += c;
      }
      for (size_t i = 0; i < n; i++)
        dst[cnt[(src[i] >> shift) & 0xFF]++] = src[i];
      std::swap(src, dst);
    }
    if (src != v.data()) std::memcpy(v.data(), src, n * sizeof(int64_t));
  }

  // dedup'd dirty-row / dirty-head notes (sorted once at plan finalize)
  void mark_link(int64_t row) {
    if ((size_t)row >= dl_mark.size()) dl_mark.resize((size_t)row + 64, 0);
    if (dl_mark[(size_t)row] != dirty_epoch) {
      dl_mark[(size_t)row] = dirty_epoch;
      plan.dirty_links.push_back(row);
    }
  }
  void mark_head(int64_t sg) {
    if ((size_t)sg >= dh_mark.size()) dh_mark.resize((size_t)sg + 64, 0);
    if (dh_mark[(size_t)sg] != dirty_epoch) {
      dh_mark[(size_t)sg] = dirty_epoch;
      plan.dirty_heads.push_back(sg);
    }
  }

  // ---- content descriptor splitting -------------------------------------

  // byte index of UTF-16 unit `units` within the UTF-8 range; *mid_pair set
  // when the cut lands between the two units of a 4-byte char (the char is
  // consumed; reference ContentString.js:51-66 replaces both halves)
  static uint64_t utf8_at_u16(const uint8_t* b, uint64_t ofs, uint64_t end,
                              int64_t units, bool* mid_pair) {
    uint64_t i = ofs;
    int64_t got = 0;
    *mid_pair = false;
    while (got < units && i < end) {
      uint8_t c = b[i];
      if (c < 0x80) { got += 1; i += 1; }
      else if (c < 0xE0) { got += 1; i += 2; }
      else if (c < 0xF0) { got += 1; i += 3; }
      else {
        if (got + 2 <= units) { got += 2; i += 4; }
        else { got += 2; i += 4; *mid_pair = true; }
      }
    }
    return i;
  }

  // advance an element-range descriptor past `k` elements; returns new ofs
  int64_t elem_skip(const ContentDesc& c, int64_t k) const {
    Reader r{buf_ptr(c.buf), (uint64_t)c.end, (uint64_t)c.ofs, false};
    for (int64_t i = 0; i < k && !r.fail; i++) {
      if (c.kind == kKindAnys) r.skip_any();
      else { uint64_t o, b; r.var_string(&o, &b); }
    }
    return r.fail ? kNull : (int64_t)r.pos;
  }

  // split `c` (a row/ref of total length `total`) at element offset `off`:
  // `c` keeps the left part, the returned descriptor is the right part.
  // ok=false on malformed content (caller degrades to Python).
  ContentDesc desc_split(ContentDesc& c, int64_t total, int64_t off, bool* ok) {
    *ok = true;
    ContentDesc right = c;
    switch (c.kind) {
      case kKindDeleted:
        return right;  // length-only content: columns carry the lengths
      case kKindAnys:
      case kKindJsons: {
        int64_t cut = elem_skip(c, off);
        if (cut == kNull) { *ok = false; return right; }
        right = c;
        right.ofs = cut;
        right.count = c.count - off;
        c.end = cut;
        c.count = off;
        return right;
      }
      case kKindUtf8: {
        bool mid = false;
        uint64_t cut = utf8_at_u16(buf_ptr(c.buf), (uint64_t)c.ofs,
                                   (uint64_t)c.end, off, &mid);
        if (cut > (uint64_t)c.end) {  // truncated trailing sequence
          *ok = false;
          return right;
        }
        if (!mid) {
          right = c;
          right.ofs = (int64_t)cut;
          c.end = (int64_t)cut;
          return right;
        }
        // the cut consumed a surrogate pair: left = prefix + U+FFFD,
        // right = U+FFFD + suffix (both synthesized into arena buffers)
        static const uint8_t kFFFD[3] = {0xEF, 0xBF, 0xBD};
        const uint8_t* base = buf_ptr(c.buf);
        int64_t lb = arena2(base + c.ofs, (size_t)(cut - 4 - (uint64_t)c.ofs),
                            kFFFD, 3);
        int64_t rb = arena2(kFFFD, 3, base + cut,
                            (size_t)((uint64_t)c.end - cut));
        c.buf = lb; c.ofs = 0; c.end = (int64_t)buf_len(lb);
        right.kind = kKindUtf8;
        right.buf = rb; right.ofs = 0; right.end = (int64_t)buf_len(rb);
        right.v2 = c.v2;
        return right;
      }
      default:
        *ok = false;  // V2Lazy/Spill/None are length-1 or unsplittable
        return right;
    }
  }

  bool desc_trim_left(ContentDesc* c, int64_t total, int64_t off) {
    bool ok = true;
    ContentDesc right = desc_split(*c, total, off, &ok);
    if (ok) *c = right;
    return ok;
  }

  // ---- row / fragment bookkeeping (DocMirror._add_row etc.) -------------

  void note_deleted(int64_t slot_, int64_t clock, int64_t len) {
    if ((size_t)slot_ >= ds.size()) ds.resize((size_t)slot_ + 1);
    auto& v = ds[(size_t)slot_];
    if (v.empty()) ds_slot_order.push_back(slot_);
    v.push_back({{clock, len}});
  }

  void reserve_rows(size_t extra) {
    size_t want = r_slot.size() + extra;
    if (r_slot.capacity() >= want) return;
    r_slot.reserve(want); r_clock.reserve(want); r_len.reserve(want);
    r_oslot.reserve(want); r_oclock.reserve(want);
    r_rslot.reserve(want); r_rclock.reserve(want);
    r_ref.reserve(want); r_seg.reserve(want);
    r_is_gc.reserve(want); r_countable.reserve(want);
    r_c.reserve(want); r_host_deleted.reserve(want);
    r_lww_deleted.reserve(want); list_next.reserve(want);
  }

  // oslot_/rslot_ are PRE-RESOLVED slots (kNull = no origin): every caller
  // has already paid the client->slot lookup, so add_row must not repeat it
  int64_t add_row(int64_t slot_, int64_t clock, int64_t length,
                  int64_t oslot_, int64_t ok_, int64_t rslot_, int64_t rk,
                  bool is_gc, const ContentDesc& c, int64_t ref,
                  int64_t seg_) {
    int64_t row = n_rows();
    r_slot.push_back(slot_);
    r_clock.push_back(clock);
    r_len.push_back(length);
    if (oslot_ == kNull) { r_oslot.push_back(kNull); r_oclock.push_back(0); }
    else { r_oslot.push_back(oslot_); r_oclock.push_back(ok_); }
    if (rslot_ == kNull) { r_rslot.push_back(kNull); r_rclock.push_back(0); }
    else { r_rslot.push_back(rslot_); r_rclock.push_back(rk); }
    r_is_gc.push_back(is_gc ? 1 : 0);
    r_countable.push_back((!is_gc && ref != 0 && ref != 1 && ref != 6) ? 1 : 0);
    r_c.push_back(c);
    r_ref.push_back(ref);
    r_seg.push_back(is_gc ? kNull : seg_);
    list_next.push_back(kNull);
    r_host_deleted.push_back(0);
    r_lww_deleted.push_back(0);
    if (!is_gc && seg_ != kNull && seg_parent[seg_] != kNull)
      rows_of_seg[seg_].push_back(row);
    gen++;
    if (is_gc) note_deleted(slot_, clock, length);
    auto& fc = frag_clock[slot_];
    auto& fr = frag_row[slot_];
    if (fc.empty() || clock > fc.back()) {
      fc.push_back(clock);
      fr.push_back(row);
    } else {
      auto it = std::lower_bound(fc.begin(), fc.end(), clock);
      size_t i = (size_t)(it - fc.begin());
      fc.insert(fc.begin() + i, clock);
      fr.insert(fr.begin() + i, row);
    }
    int64_t end = clock + length;
    if (end > state[slot_]) state[slot_] = end;
    return row;
  }

  // index into the frag lists of the fragment covering `clock`, or -1
  int64_t frag_containing(int64_t slot_, int64_t clock) const {
    const auto& fc = frag_clock[slot_];
    int64_t n = (int64_t)fc.size();
    if (n == 0) return kNull;
    // fast path: appends dominate, so most lookups hit the last fragment
    if (clock >= fc.back()) {
      int64_t i = n - 1;
      int64_t row = frag_row[slot_][(size_t)i];
      return clock < r_clock[row] + r_len[row] ? i : kNull;
    }
    // hint path: chained lookups land on the same or the next fragment
    int64_t i;
    int64_t h = frag_hint[slot_];
    if (h >= 0 && h + 1 < n && fc[(size_t)h] <= clock) {
      if (clock < fc[(size_t)h + 1]) i = h;
      else if (h + 2 < n ? clock < fc[(size_t)h + 2]
                         : clock < fc.back())
        i = h + 1;
      else
        i = std::upper_bound(fc.begin() + h + 2, fc.end(), clock) -
            fc.begin() - 1;
    } else {
      i = std::upper_bound(fc.begin(), fc.end(), clock) - fc.begin() - 1;
    }
    if (i < 0) return kNull;
    frag_hint[slot_] = i;
    int64_t row = frag_row[slot_][(size_t)i];
    if (clock < r_clock[row] + r_len[row]) return i;
    return kNull;
  }

  int64_t split_existing(int64_t slot_, int64_t frag_idx, int64_t at_clock,
                         bool* ok) {
    int64_t row = frag_row[slot_][(size_t)frag_idx];
    int64_t offset = at_clock - r_clock[row];
    ContentDesc right = desc_split(r_c[row], r_len[row], offset, ok);
    if (!*ok) return kNull;
    gen++;
    int64_t sg = r_seg[row];
    int64_t new_row = add_row(
        slot_, at_clock, r_len[row] - offset,
        slot_, at_clock - 1, r_rslot[row], r_rclock[row],
        false, right, r_ref[row], sg);
    r_len[row] = offset;
    plan.splits.push_back({{row, new_row}});
    list_next[new_row] = list_next[row];
    list_next[row] = new_row;
    mark_link(row);
    mark_link(new_row);
    if (r_host_deleted[row]) {
      r_host_deleted[new_row] = 1;
      // ship the fragment's deleted bit: the bulk-apply path has no
      // on-device split surgery to copy it from the original
      plan.delete_rows.push_back(new_row);
    }
    if (sg != kNull && seg_is_map(sg)) {
      auto& chain = map_chain[sg];
      auto it = std::find(chain.begin(), chain.end(), row);
      chain.insert(it + 1, new_row);
      if (r_lww_deleted[row]) r_lww_deleted[new_row] = 1;
    }
    return new_row;
  }

  // ---- map-chain YATA insert (DocMirror._chain_insert) ------------------

  int64_t origin_row_of(int64_t row) const {
    int64_t s = r_oslot[row];
    if (s == kNull) return kNull;
    int64_t fi = frag_containing(s, r_oclock[row]);
    return fi == kNull ? kNull : frag_row[s][(size_t)fi];
  }

  bool row_origin_eq(int64_t a, int64_t b) const {
    int64_t sa = r_oslot[a], sb = r_oslot[b];
    return sa == sb && (sa == kNull || r_oclock[a] == r_oclock[b]);
  }

  bool row_right_eq(int64_t a, int64_t b) const {
    int64_t sa = r_rslot[a], sb = r_rslot[b];
    return sa == sb && (sa == kNull || r_rclock[a] == r_rclock[b]);
  }

  int64_t row_client(int64_t row) const {
    return client_of_slot[r_slot[row]];
  }

  // resolve the row's YATA placement against the host list and splice —
  // the host twin of the device conflict scan (reference Item.js:403-517,
  // the same itemsBeforeOrigin/conflictingItems walk).  Returns the
  // resolved left row (kNull = new head).
  int64_t list_insert(int64_t sg, int64_t row, int64_t left_row,
                      int64_t right_row) {
    int64_t left = left_row;
    int64_t o = left_row != kNull ? list_next[left_row] : head_of_seg[sg];
    if (o != kNull && o != right_row) {
      // conflict scan with O(1) membership: `items_before` = rows stamped
      // with this walk id; `conflicting` = those with visit order >=
      // conf_start (clear() == bump conf_start past the current row).
      // Stale stamps (older walks, pre-compaction ids) are always < the
      // freshly bumped walk id, so lazy sizing is safe.
      if (walk_mark.size() < r_slot.size()) {
        walk_mark.resize(r_slot.size(), 0);
        walk_order.resize(r_slot.size(), 0);
      }
      uint64_t wid = ++walk_id;
      uint64_t idx = 0, conf_start = 0;
      while (o != kNull && o != right_row) {
        walk_mark[(size_t)o] = wid;
        walk_order[(size_t)o] = idx++;
        if (row_origin_eq(row, o)) {
          if (row_client(o) < row_client(row)) {
            left = o;
            conf_start = idx;
          } else if (row_right_eq(row, o)) {
            break;
          }
        } else {
          int64_t oor = origin_row_of(o);
          if (oor != kNull && walk_mark[(size_t)oor] == wid) {
            if (walk_order[(size_t)oor] < conf_start) {
              left = o;
              conf_start = idx;
            }
          } else {
            break;
          }
        }
        o = list_next[o];
      }
    }
    if (left != kNull) {
      list_next[row] = list_next[left];
      list_next[left] = row;
      mark_link(left);
      mark_link(row);
    } else {
      list_next[row] = head_of_seg[sg];
      head_of_seg[sg] = row;
      mark_link(row);
      mark_head(sg);
    }
    return left;
  }

  // ---- deletes (DocMirror._delete_row / _lww_pass) ----------------------

  void delete_row(int64_t row) {
    if (r_host_deleted[row] || r_is_gc[row]) return;
    r_host_deleted[row] = 1;
    plan.delete_rows.push_back(row);
    note_deleted(r_slot[row], r_clock[row], r_len[row]);
    plan.applied_ds.push_back({{row_client(row), r_clock[row], r_len[row]}});
    int64_t sg = r_seg[row];
    if (sg != kNull && seg_is_map(sg)) r_lww_deleted[row] = 1;
    if (r_ref[row] == 7) {
      auto it = segs_of_parent.find(row);
      if (it != segs_of_parent.end()) {
        for (int64_t cs : it->second) {
          auto rit = rows_of_seg.find(cs);
          if (rit == rows_of_seg.end()) continue;
          std::vector<int64_t> children = rit->second;  // copy: recursion mutates
          for (int64_t child : children) delete_row(child);
        }
      }
    }
  }

  void lww_pass(const std::vector<int64_t>& segs) {
    for (int64_t sg : segs) {
      auto it = map_chain.find(sg);
      if (it == map_chain.end() || it->second.empty()) continue;
      int64_t tail = it->second.back();
      for (int64_t r : it->second)
        if (r != tail && !r_lww_deleted[r]) delete_row(r);
    }
  }

  // ---- wire scan (decode_update_refs twin) ------------------------------

  // scan one update into `out`; returns 0 or an error code
  int scan_update(int64_t buf_id, bool v2, std::vector<PendRef>* out,
                  std::vector<std::array<int64_t, 3>>* ds_out) {
    const uint8_t* buf = buf_ptr(buf_id);
    uint64_t blen = buf_len(buf_id);
    if (!v2) return scan_v1(buf, blen, buf_id, out, ds_out);
    return scan_v2(buf, blen, buf_id, out, ds_out);
  }

  int scan_v1(const uint8_t* buf, uint64_t blen, int64_t buf_id,
              std::vector<PendRef>* out,
              std::vector<std::array<int64_t, 3>>* ds_out) {
    Reader r{buf, blen, 0, false};
    uint64_t n_updates = r.varuint();
    for (uint64_t u = 0; u < n_updates && !r.fail; u++) {
      uint64_t n_structs = r.varuint();
      uint64_t client = r.varuint();
      uint64_t clock = r.varuint();
      for (uint64_t s = 0; s < n_structs && !r.fail; s++) {
        // build in place: a 176-byte PendRef copy per struct is real
        // memcpy traffic at millions of refs per flush
        out->emplace_back();
        PendRef& p = out->back();
        p.client = (int64_t)client;
        p.clock = (int64_t)clock;
        uint8_t info = r.u8();
        uint8_t ref = info & kBits5;
        p.ref = ref;
        if (ref == 0) {
          p.is_gc = true;
          p.length = (int64_t)r.varuint();
          p.c.kind = kKindNone;
        } else {
          if (ref == 9) return kErrUnsupported;  // ContentDoc: subdocument
          if (info & kBit8) {
            p.oc = (int64_t)r.varuint();
            p.ok = (int64_t)r.varuint();
          }
          if (info & kBit7) {
            p.rc = (int64_t)r.varuint();
            p.rk = (int64_t)r.varuint();
          }
          if (!(info & (kBit7 | kBit8))) {
            if (r.varuint() == 1) {
              uint64_t o, b;
              r.var_string(&o, &b);
              if (r.fail) return kErrMalformed;
              p.name_id = intern(buf + o, (int64_t)b);
            } else {
              p.pic = (int64_t)r.varuint();
              p.pik = (int64_t)r.varuint();
            }
            if (info & kBit6) {
              uint64_t o, b;
              r.var_string(&o, &b);
              if (r.fail) return kErrMalformed;
              p.sub_id = intern(buf + o, (int64_t)b);
            }
          }
          uint64_t c_ofs = r.pos;
          switch (ref) {
            case 1:
              p.length = (int64_t)r.varuint();
              p.c.kind = kKindDeleted;
              break;
            case 2: {  // ContentJSON: element range directly
              uint64_t n = r.varuint();
              uint64_t e_ofs = r.pos;
              for (uint64_t i = 0; i < n && !r.fail; i++) {
                uint64_t o, b;
                r.var_string(&o, &b);
              }
              p.length = (int64_t)n;
              p.c.kind = kKindJsons;
              p.c.buf = buf_id;
              p.c.ofs = (int64_t)e_ofs;
              p.c.end = (int64_t)r.pos;
              p.c.count = (int64_t)n;
              break;
            }
            case 3: {
              uint64_t n = r.varuint();
              r.skip(n);
              p.length = 1;
              p.c.kind = kKindFramed;
              p.c.buf = buf_id;
              p.c.ofs = (int64_t)c_ofs;
              p.c.end = (int64_t)r.pos;
              break;
            }
            case 4: {  // ContentString: raw UTF-8 range
              uint64_t o, b;
              r.var_string(&o, &b);
              p.length = (int64_t)r.utf16_len(o, b);
              p.c.kind = kKindUtf8;
              p.c.buf = buf_id;
              p.c.ofs = (int64_t)o;
              p.c.end = (int64_t)(o + b);
              break;
            }
            case 5: case 6: {
              uint64_t o, b;
              r.var_string(&o, &b);
              if (ref == 6) r.var_string(&o, &b);
              p.length = 1;
              p.c.kind = kKindFramed;
              p.c.buf = buf_id;
              p.c.ofs = (int64_t)c_ofs;
              p.c.end = (int64_t)r.pos;
              break;
            }
            case 7: {
              uint64_t tref = r.varuint();
              if (tref == 3 || tref == 5) {
                uint64_t o, b;
                r.var_string(&o, &b);
              }
              p.length = 1;
              p.c.kind = kKindFramed;
              p.c.buf = buf_id;
              p.c.ofs = (int64_t)c_ofs;
              p.c.end = (int64_t)r.pos;
              break;
            }
            case 8: {  // ContentAny: element range directly
              uint64_t n = r.varuint();
              uint64_t e_ofs = r.pos;
              for (uint64_t i = 0; i < n && !r.fail; i++) r.skip_any();
              p.length = (int64_t)n;
              p.c.kind = kKindAnys;
              p.c.buf = buf_id;
              p.c.ofs = (int64_t)e_ofs;
              p.c.end = (int64_t)r.pos;
              p.c.count = (int64_t)n;
              break;
            }
            default:
              return kErrMalformed;
          }
        }
        if (r.fail) return kErrMalformed;
        if (p.length == 0 && ref != 0) return kErrMalformed;
        clock += (uint64_t)p.length;
      }
    }
    if (r.fail) return kErrMalformed;
    uint64_t n_clients = r.varuint();
    for (uint64_t c = 0; c < n_clients && !r.fail; c++) {
      uint64_t client = r.varuint();
      uint64_t n = r.varuint();
      for (uint64_t i = 0; i < n && !r.fail; i++) {
        uint64_t clock = r.varuint();
        uint64_t len = r.varuint();
        ds_out->push_back({{(int64_t)client, (int64_t)clock, (int64_t)len}});
      }
    }
    if (r.fail || r.pos != blen) return kErrMalformed;
    return 0;
  }

  int scan_v2(const uint8_t* buf, uint64_t blen, int64_t buf_id,
              std::vector<PendRef>* out,
              std::vector<std::array<int64_t, 3>>* ds_out) {
    V2Streams v;
    if (!v.init(buf, blen)) return kErrMalformed;
    Reader* rest = &v.rest;
    uint64_t n_updates = rest->varuint();
    for (uint64_t u = 0; u < n_updates && !rest->fail; u++) {
      uint64_t n_structs = rest->varuint();
      int64_t client = v.client.read();
      uint64_t clock = rest->varuint();
      for (uint64_t s = 0; s < n_structs; s++) {
        if (v.any_fail()) return kErrMalformed;
        // build in place (mirrors scan_v1): no 176-byte copy per struct
        out->emplace_back();
        PendRef& p = out->back();
        p.client = client;
        p.clock = (int64_t)clock;
        p.c.v2 = 1;
        uint8_t info = (uint8_t)v.info.read();
        uint8_t ref = info & kBits5;
        p.ref = ref;
        if (ref == 0) {
          p.is_gc = true;
          p.length = v.len.read();
          p.c.kind = kKindNone;
        } else {
          if (ref == 9) return kErrUnsupported;
          if (ref == 2) return kErrLegacy;  // legacy ContentJSON in V2
          if (info & kBit8) { p.oc = v.client.read(); p.ok = v.left_clock.read(); }
          if (info & kBit7) { p.rc = v.client.read(); p.rk = v.right_clock.read(); }
          if (!(info & (kBit7 | kBit8))) {
            int64_t o = kNull, e = kNull;
            if (v.parent_info.read() == 1) {
              v.str.read(&o, &e);
              if (v.any_fail()) return kErrMalformed;
              p.name_id = intern(buf + o, e - o);
            } else {
              p.pic = v.client.read();
              p.pik = v.left_clock.read();
            }
            if (info & kBit6) {
              v.str.read(&o, &e);
              if (v.any_fail()) return kErrMalformed;
              p.sub_id = intern(buf + o, e - o);
            }
          }
          switch (ref) {
            case 1:
              p.length = v.len.read();
              p.c.kind = kKindDeleted;
              break;
            case 3: {
              int64_t c_ofs = (int64_t)rest->pos;
              uint64_t n = rest->varuint();
              rest->skip(n);
              p.length = 1;
              p.c.kind = kKindFramed;  // varuint+bytes: V1-compatible framing
              p.c.buf = buf_id;
              p.c.ofs = c_ofs;
              p.c.end = (int64_t)rest->pos;
              break;
            }
            case 4: {
              int64_t o, e;
              v.str.read(&o, &e);
              p.length = v.str.lens.s;
              p.c.kind = kKindUtf8;
              p.c.buf = buf_id;
              p.c.ofs = o;
              p.c.end = e;
              break;
            }
            case 5: {  // embed: lib0 any (V2-only framing)
              p.c.kind = kKindV2Lazy;
              p.c.buf = buf_id;
              p.c.ofs = (int64_t)rest->pos;
              rest->skip_any();
              p.c.end = (int64_t)rest->pos;
              p.c.count = 5;
              p.length = 1;
              break;
            }
            case 6: {  // format: key string + any value
              int64_t o, e;
              v.str.read(&o, &e);
              p.c.kind = kKindV2Lazy;
              p.c.buf = buf_id;
              p.c.ofs = o;
              p.c.end = e;
              p.c.ofs2 = (int64_t)rest->pos;
              rest->skip_any();
              p.c.end2 = (int64_t)rest->pos;
              p.c.count = 6;
              p.length = 1;
              break;
            }
            case 7: {
              int64_t tref = v.type_ref.read();
              int64_t o = kNull, e = kNull;
              if (tref == 3 || tref == 5) v.read_key(&o, &e);
              p.c.kind = kKindV2Lazy;
              p.c.buf = buf_id;
              p.c.ofs = o;
              p.c.end = e;
              p.c.count = tref;  // type ref rides in count
              p.length = 1;
              break;
            }
            case 8: {
              int64_t n = v.len.read();
              p.c.kind = kKindAnys;
              p.c.buf = buf_id;
              p.c.ofs = (int64_t)rest->pos;
              for (int64_t i = 0; i < n && !rest->fail; i++) rest->skip_any();
              p.c.end = (int64_t)rest->pos;
              p.c.count = n;
              p.length = n;
              break;
            }
            default:
              return kErrMalformed;
          }
        }
        if (v.any_fail()) return kErrMalformed;
        if (p.length == 0 && ref != 0) return kErrMalformed;
        clock += (uint64_t)p.length;
      }
    }
    if (rest->fail) return kErrMalformed;
    // DS section: delta-varint clocks, len-1 on the wire
    uint64_t n_clients = rest->varuint();
    for (uint64_t c = 0; c < n_clients && !rest->fail; c++) {
      int64_t cur = 0;
      uint64_t client = rest->varuint();
      uint64_t n = rest->varuint();
      for (uint64_t i = 0; i < n && !rest->fail; i++) {
        cur += (int64_t)rest->varuint();
        int64_t clock = cur;
        int64_t len = (int64_t)rest->varuint() + 1;
        cur += len;
        ds_out->push_back({{(int64_t)client, clock, len}});
      }
    }
    if (rest->fail || rest->pos != blen) return kErrMalformed;
    return 0;
  }

  // ---- the flush pipeline (DocMirror.prepare_step twin) -----------------

  int prepare(const int64_t* buf_ids, const int64_t* v2_flags,
              int64_t n_updates, bool want_levels, bool want_sched = true) {
    // the bulk-apply path never reads the sched section unless events are
    // observed; skipping it saves a 32-byte append per integrated row
    want_sched = want_sched || want_levels;
    const bool timing = std::getenv("YMX_TIMING") != nullptr;
    auto t0 = std::chrono::steady_clock::now();
    auto lap = [&](const char* what) {
      if (!timing) return;
      auto t1 = std::chrono::steady_clock::now();
      std::fprintf(stderr, "[ymx] %-12s %8.1f us\n", what,
                   std::chrono::duration<double, std::micro>(t1 - t0).count());
      t0 = t1;
    };
    plan.clear();
    dirty_epoch++;

    // decode every staged update first (nothing merges on error; the doc
    // demotes wholesale, matching the Python flow).  Refs scan into ONE
    // flat buffer and move into the per-client queues afterwards — a
    // single fat-struct copy instead of the old scan/group/insert three.
    std::vector<PendRef> all_refs;
    {
      // structs are >= ~4 wire bytes each; over-reserving transiently is
      // far cheaper than re-copying 176-byte PendRefs on vector growth
      uint64_t total_bytes = 0;
      for (int64_t i = 0; i < n_updates; i++) total_bytes += buf_len(buf_ids[i]);
      all_refs.reserve(total_bytes / 4 + 64);
    }
    std::vector<std::array<int64_t, 3>> ds_ranges(pending_ds);
    {
      std::vector<std::array<int64_t, 3>> ds_new;
      for (int64_t i = 0; i < n_updates; i++) {
        std::vector<std::array<int64_t, 3>> ds_one;
        int rc = scan_update(buf_ids[i], v2_flags[i] != 0, &all_refs, &ds_one);
        if (rc != 0) return rc;
        for (auto& d : ds_one) ds_new.push_back(d);
      }
      for (auto& d : ds_new) ds_ranges.push_back(d);
    }
    lap("scan");
    pending_ds.clear();

    // merge into per-client WORKING SETS of pointers (old pending refs
    // first, then this call's scan output, stable-sorted by clock) — the
    // same order the old fat-struct queues had, without moving a single
    // 176-byte PendRef.  `pending` stays untouched until the end of the
    // call, when only the UNCONSUMED tail is copied back (common case:
    // empty).  all_refs is function-scoped, so the pointers outlive every
    // consumer (fixpoint, cuts-collect, rows).
    // Clients interleave ref-by-ref in merged updates, so the working-set
    // lookup rides a small linear cache (few clients), spilling to a map
    // past kLinearClients.
    constexpr size_t kLinearClients = 32;
    std::vector<std::pair<int64_t, std::vector<PendRef*>>> qwork_lin;
    std::unordered_map<int64_t, std::vector<PendRef*>> qwork_wide;
    {
      auto qwork_of = [&](int64_t cl) -> std::vector<PendRef*>& {
        if (!qwork_wide.empty()) return qwork_wide[cl];
        for (auto& [c, w] : qwork_lin)
          if (c == cl) return w;
        if (qwork_lin.size() >= kLinearClients) {
          for (auto& [c, w] : qwork_lin)
            qwork_wide.emplace(c, std::move(w));
          qwork_lin.clear();
          return qwork_wide[cl];
        }
        qwork_lin.emplace_back(cl, std::vector<PendRef*>());
        return qwork_lin.back().second;
      };
      for (auto& [cl, q] : pending) {
        auto& w = qwork_of(cl);
        w.reserve(q.size() + 16);
        for (auto& r : q) w.push_back(&r);
      }
      int64_t cache_cl = INT64_MIN;
      std::vector<PendRef*>* cache_w = nullptr;
      for (auto& p : all_refs) {
        if (p.client != cache_cl) {
          cache_w = &qwork_of(p.client);
          cache_cl = p.client;
        }
        cache_w->push_back(&p);
      }
      auto by_clock = [](const PendRef* a, const PendRef* b) {
        return a->clock < b->clock;
      };
      for (auto& [cl, w] : qwork_lin)
        if (!std::is_sorted(w.begin(), w.end(), by_clock))
          std::stable_sort(w.begin(), w.end(), by_clock);
      for (auto& [cl, w] : qwork_wide)
        if (!std::is_sorted(w.begin(), w.end(), by_clock))
          std::stable_sort(w.begin(), w.end(), by_clock);
    }
    // descending-client iteration order for the fixpoint (the old
    // pending.rbegin() order), with consumed-prefix heads alongside
    std::vector<std::pair<int64_t, std::vector<PendRef*>*>> clients_desc;
    clients_desc.reserve(qwork_lin.size() + qwork_wide.size());
    for (auto& [cl, w] : qwork_lin) clients_desc.emplace_back(cl, &w);
    for (auto& [cl, w] : qwork_wide) clients_desc.emplace_back(cl, &w);
    std::sort(clients_desc.begin(), clients_desc.end(),
              [](const auto& a, const auto& b) { return a.first > b.first; });
    std::vector<size_t> q_head(clients_desc.size(), 0);

    lap("merge");
    // causal scheduling: per-client queue fixpoint, descending client order
    std::vector<PendRef*> sched;
    {
      size_t tot = 0;
      for (auto& [c, w] : clients_desc) tot += w->size();
      sched.reserve(tot);
    }
    // effective-state cache: the fixpoint probes state_of 3-4x per ref
    // (dep checks + clock gate); the old overlay map cost two hash
    // lookups per probe.  Live state[] never changes during the fixpoint
    // (rows are added later), so caching get_state is safe.  Linear for
    // the common few-client case, spilling to a map when wide.
    constexpr size_t kLinearStClients = 32;
    std::vector<std::pair<int64_t, int64_t>> st_lin;
    std::unordered_map<int64_t, int64_t> st_wide;
    auto state_of = [&](int64_t client) -> int64_t {
      if (!st_wide.empty()) {
        auto it = st_wide.find(client);
        if (it != st_wide.end()) return it->second;
        int64_t v = get_state(client);
        st_wide.emplace(client, v);
        return v;
      }
      for (auto& e : st_lin)
        if (e.first == client) return e.second;
      int64_t v = get_state(client);
      if (st_lin.size() >= kLinearStClients) {
        st_wide.insert(st_lin.begin(), st_lin.end());
        st_lin.clear();  // same spill discipline as qwork_of above
        st_wide.emplace(client, v);
      } else {
        st_lin.emplace_back(client, v);
      }
      return v;
    };
    auto bump_state = [&](int64_t client, int64_t v) {
      if (!st_wide.empty()) {
        st_wide[client] = v;
        return;
      }
      for (auto& e : st_lin)
        if (e.first == client) { e.second = v; return; }
      if (st_lin.size() >= kLinearStClients) {
        st_wide.insert(st_lin.begin(), st_lin.end());
        st_lin.clear();
        st_wide[client] = v;
      } else {
        st_lin.emplace_back(client, v);
      }
    };
    auto dep_ok = [&](int64_t dc, int64_t dk, bool has, int64_t client) {
      return !has || dc == client || state_of(dc) > dk;
    };
    bool progress = true;
    while (progress) {
      progress = false;
      for (size_t ci = 0; ci < clients_desc.size(); ci++) {
        int64_t client = clients_desc[ci].first;
        auto& q = *clients_desc[ci].second;
        size_t& head = q_head[ci];
        while (head < q.size()) {
          PendRef& ref = *q[head];
          int64_t st = state_of(client);
          if (ref.clock > st) break;
          if (ref.clock + ref.length <= st) {
            head++;
            progress = true;
            continue;
          }
          if (!(dep_ok(ref.oc, ref.ok, ref.oc >= 0, client) &&
                dep_ok(ref.rc, ref.rk, ref.rc >= 0, client) &&
                dep_ok(ref.pic, ref.pik, ref.pic >= 0, client)))
            break;
          if (ref.clock < st) {
            int64_t off = st - ref.clock;
            if (!ref.is_gc) {
              if (ref.c.kind != kKindNone &&
                  !desc_trim_left(&ref.c, ref.length, off))
                return kErrMalformed;
            }
            ref.clock += off;
            ref.length -= off;
            if (!ref.is_gc) {
              ref.oc = ref.client;
              ref.ok = ref.clock - 1;
            }
          }
          sched.push_back(&ref);
          bump_state(client, ref.clock + ref.length);
          head++;
          progress = true;
        }
      }
    }

    lap("fixpoint");
    // delete-set clamping against post-step state
    std::vector<std::array<int64_t, 3>> applicable;
    for (auto& [client, clock, ln] : ds_ranges) {
      int64_t st = state_of(client);
      if (clock < st)
        applicable.push_back({{client, clock, std::min(ln, st - clock)}});
      if (clock + ln > st) {
        int64_t lo = std::max(clock, st);
        pending_ds.push_back({{client, lo, clock + ln - lo}});
      }
    }

    lap("ds-clamp");
    // pre-split pass: every boundary this step needs (collected raw,
    // then sorted+deduped per client — matches Python's set semantics
    // without per-insert node allocation)
    std::vector<int64_t> cut_clients;  // first-need order (Python dict order)
    std::unordered_map<int64_t, std::vector<int64_t>> cuts;
    cuts.reserve(16);
    // one-entry cache (consecutive refs share clients) + consecutive-dup
    // elision: the sort+unique below makes dropped dups unobservable
    int64_t cut_cl_cache = INT64_MIN;
    std::vector<int64_t>* cut_ks_cache = nullptr;
    auto need_start = [&](int64_t client, int64_t clock) {
      if (client != cut_cl_cache) {
        auto it = cuts.find(client);
        if (it == cuts.end()) {
          cut_clients.push_back(client);
          it = cuts.emplace(client, std::vector<int64_t>()).first;
        }
        cut_cl_cache = client;
        cut_ks_cache = &it->second;
      }
      if (cut_ks_cache->empty() || cut_ks_cache->back() != clock)
        cut_ks_cache->push_back(clock);
    };
    // per-stream repeat elision: origin cuts chain forward one at a time
    // and right-origin cuts repeat across a typing burst, so most points
    // equal that client-stream's previous one; sort+unique makes drops
    // invisible.  Keyed per client (refs interleave clients ref-by-ref,
    // so a single-entry cache would thrash), linear scan over few clients.
    std::vector<std::array<int64_t, 3>> last_cut;  // client, last_o, last_r
    std::unordered_map<int64_t, std::array<int64_t, 2>> last_cut_wide;
    constexpr size_t kLinearCutClients = 32;
    auto cut_slot = [&](int64_t cl) -> int64_t* {
      // linear for the common few-client case; spill to a map when refs
      // span many historical clients (initial sync / bulk history load)
      if (last_cut.size() >= kLinearCutClients) {
        if (last_cut_wide.empty())
          for (auto& e : last_cut)
            last_cut_wide.emplace(e[0], std::array<int64_t, 2>{e[1], e[2]});
        return last_cut_wide
            .emplace(cl, std::array<int64_t, 2>{INT64_MIN, INT64_MIN})
            .first->second.data();
      }
      for (auto& e : last_cut)
        if (e[0] == cl) return &e[1];
      last_cut.push_back({cl, INT64_MIN, INT64_MIN});
      return &last_cut.back()[1];
    };
    for (const PendRef* rp : sched) {
      const PendRef& ref = *rp;
      if (ref.oc >= 0) {
        int64_t* e = cut_slot(ref.oc);
        if (e[0] != ref.ok + 1) {
          e[0] = ref.ok + 1;
          need_start(ref.oc, e[0]);
        }
      }
      if (ref.rc >= 0) {
        int64_t* e = cut_slot(ref.rc);
        if (e[1] != ref.rk) {
          e[1] = ref.rk;
          need_start(ref.rc, ref.rk);
        }
      }
    }
    for (auto& [client, clock, ln] : applicable) {
      need_start(client, clock);
      need_start(client, clock + ln);
    }
    lap("cuts-collect");
    for (auto& [client, ks] : cuts) {
      // mostly-ascending in practice (origins chain forward); skip the
      // sort when the scan produced them in order.  Clocks are small
      // non-negative ints, so the unsorted case takes an LSD radix sort
      // (branch-free counting passes beat introsort's compares on these
      // ~1k-element lists).
      if (!std::is_sorted(ks.begin(), ks.end()))
        radix_sort_clocks(ks);
      ks.erase(std::unique(ks.begin(), ks.end()), ks.end());
    }

    lap("cuts");
    // cuts inside existing rows: split + device link surgery
    size_t pre_split_marker = plan.splits.size();
    for (int64_t client : cut_clients) {
      auto sit = slot_of_client.find(client);
      if (sit == slot_of_client.end()) continue;
      int64_t slot_ = sit->second;
      for (int64_t k : cuts[client]) {
        int64_t fi = frag_containing(slot_, k);
        if (fi == kNull) continue;
        int64_t row = frag_row[slot_][(size_t)fi];
        if (r_is_gc[row] || r_clock[row] == k) continue;
        bool ok = true;
        split_existing(slot_, fi, k, &ok);
        if (!ok) return kErrMalformed;
      }
    }
    std::sort(plan.splits.begin() + pre_split_marker, plan.splits.end(),
              [](const std::array<int64_t, 2>& a,
                 const std::array<int64_t, 2>& b) {
                if (a[0] != b[0]) return a[0] < b[0];
                return a[1] > b[1];
              });

    lap("pre-split");
    // row assignment + pointer resolution, fragmenting each scheduled ref
    // by its client's cut set inline (same fragment order as the old
    // two-pass frag_sched build, without the fat-struct copy pass)
    reserve_rows(sched.size());
    std::vector<int64_t> touched_map_segs;  // ascending on use (set below)
    if (tm_mark.size() < dh_mark.size()) tm_mark.resize(dh_mark.size(), 0);
    // last row emit_row produced: rows emitted this pass are never split
    // again within the pass (all cuts were applied in pre-split or
    // inline), so containment against it is exact — chained refs adopt
    // their anchor without the fragment binary search
    const bool seg_on = g_plan_segment.load(std::memory_order_relaxed) != 0;
    bool em_last_valid = false;
    int64_t em_last_row = kNull, em_last_slot = kNull;
    int64_t em_last_clock = 0, em_last_len = 0;
    int64_t seg_fast_n = 0, seg_lookup_n = 0;
    auto emit_row = [&](const PendRef& ref) -> int {
      int64_t slot_ = slot(ref.client);
      if (ref.is_gc) {
        int64_t row = add_row(slot_, ref.clock, ref.length, kNull, 0, kNull,
                              0, true, ContentDesc{}, 0, kNull);
        em_last_valid = true;
        em_last_row = row;
        em_last_slot = slot_;
        em_last_clock = ref.clock;
        em_last_len = ref.length;
        return 0;
      }
      int64_t left_row = kNull, right_row = kNull;
      int64_t oslot = kNull, rslot = kNull;
      bool degrade = false;
      if (ref.oc >= 0) {
        oslot = slot(ref.oc);
        if (seg_on && em_last_valid && oslot == em_last_slot &&
            ref.ok >= em_last_clock &&
            ref.ok < em_last_clock + em_last_len) {
          left_row = em_last_row;
          seg_fast_n++;
        } else {
          int64_t fi = frag_containing(oslot, ref.ok);
          if (fi == kNull) return kErrInternal;
          left_row = frag_row[oslot][(size_t)fi];
          if (seg_on) seg_lookup_n++;
        }
        if (r_is_gc[left_row]) degrade = true;
      }
      if (ref.rc >= 0) {
        rslot = slot(ref.rc);
        if (seg_on && em_last_valid && rslot == em_last_slot &&
            ref.rk >= em_last_clock &&
            ref.rk < em_last_clock + em_last_len) {
          right_row = em_last_row;
          seg_fast_n++;
        } else {
          int64_t fi = frag_containing(rslot, ref.rk);
          if (fi == kNull) return kErrInternal;
          right_row = frag_row[rslot][(size_t)fi];
          if (seg_on) seg_lookup_n++;
        }
        if (r_is_gc[right_row]) degrade = true;
      }
      int64_t parent_row = kNull;
      if (!degrade && ref.pic >= 0) {
        int64_t pslot = slot(ref.pic);
        int64_t fi = frag_containing(pslot, ref.pik);
        if (fi == kNull) return kErrInternal;
        parent_row = frag_row[pslot][(size_t)fi];
        if (r_is_gc[parent_row] || r_ref[parent_row] != 7) degrade = true;
      }
      if (degrade) {
        int64_t row = add_row(slot_, ref.clock, ref.length, kNull, 0, kNull,
                              0, true, ContentDesc{}, 0, kNull);
        em_last_valid = true;
        em_last_row = row;
        em_last_slot = slot_;
        em_last_clock = ref.clock;
        em_last_len = ref.length;
        return 0;
      }
      int64_t sg;
      if (parent_row != kNull) {
        sg = seg(kNull, ref.sub_id, parent_row);
      } else if (ref.name_id != kNull) {
        sg = seg(ref.name_id, ref.sub_id, kNull);
      } else if (left_row != kNull) {
        sg = r_seg[left_row];
      } else if (right_row != kNull) {
        sg = r_seg[right_row];
      } else {
        return kErrUnsupported;  // item with no derivable parent
      }
      int64_t row = add_row(slot_, ref.clock, ref.length, oslot, ref.ok,
                            rslot, ref.rk, false, ref.c, ref.ref, sg);
      em_last_valid = true;
      em_last_row = row;
      em_last_slot = slot_;
      em_last_clock = ref.clock;
      em_last_len = ref.length;
      if (want_sched) plan.sched.push_back({{row, left_row, right_row, sg}});
      int64_t actual_left = list_insert(sg, row, left_row, right_row);
      if (seg_is_map(sg)) {
        auto& chain = map_chain[sg];
        if (actual_left == kNull) {
          chain.insert(chain.begin(), row);
        } else {
          auto it = std::find(chain.begin(), chain.end(), actual_left);
          chain.insert(it + 1, row);
        }
        if ((size_t)sg >= tm_mark.size()) tm_mark.resize((size_t)sg + 64, 0);
        if (tm_mark[(size_t)sg] != dirty_epoch) {
          tm_mark[(size_t)sg] = dirty_epoch;
          touched_map_segs.push_back(sg);
        }
      }
      int64_t pr = seg_parent[sg];
      if (pr != kNull && r_host_deleted[pr]) delete_row(row);
      if (ref.ref == 1)
        applicable.push_back({{ref.client, ref.clock, ref.length}});
      return 0;
    };
    // per-ref cuts lookup cache + rolling cut cursor: sched's clocks
    // ascend per client, so within a client run the cut cursor only moves
    // forward (amortized O(1)); a client switch re-seeks once.  The hash
    // find per ref is gone with it.
    int64_t cuts_cl_cache = INT64_MIN;
    std::vector<int64_t>* cuts_ks_cache = nullptr;
    size_t cuts_idx_cache = 0;
    for (const PendRef* rp0 : sched) {
      const PendRef& ref0 = *rp0;
      // length-1 refs can never be fragmented (no strictly-interior cut)
      std::vector<int64_t>* ks_p = nullptr;
      if (!ref0.is_gc && ref0.length > 1) {
        if (ref0.client == cuts_cl_cache) {
          ks_p = cuts_ks_cache;
        } else {
          auto cit = cuts.find(ref0.client);
          ks_p = cit == cuts.end() ? nullptr : &cit->second;
          cuts_cl_cache = ref0.client;
          cuts_ks_cache = ks_p;
          if (ks_p)
            cuts_idx_cache =
                std::upper_bound(ks_p->begin(), ks_p->end(), ref0.clock) -
                ks_p->begin();
        }
      }
      if (ks_p == nullptr) {
        int rc = emit_row(ref0);
        if (rc != 0) return rc;
        continue;
      }
      PendRef cur = ref0;
      auto& ks = *ks_p;
      while (cuts_idx_cache < ks.size() && ks[cuts_idx_cache] <= cur.clock)
        cuts_idx_cache++;
      for (size_t ki = cuts_idx_cache;
           ki < ks.size() && ks[ki] < ref0.clock + ref0.length; ++ki) {
        int64_t k = ks[ki];
        if (k <= cur.clock) continue;
        PendRef right = cur;
        int64_t off = k - cur.clock;
        bool ok = true;
        if (cur.c.kind != kKindNone) {
          right.c = desc_split(cur.c, cur.length, off, &ok);
          if (!ok) return kErrMalformed;
        }
        right.clock = cur.clock + off;
        right.length = cur.length - off;
        right.oc = cur.client;
        right.ok = right.clock - 1;
        cur.length = off;
        int rc = emit_row(cur);
        if (rc != 0) return rc;
        cur = right;
      }
      int rc = emit_row(cur);
      if (rc != 0) return rc;
    }

    if (seg_fast_n)
      g_seg_fast.fetch_add(seg_fast_n, std::memory_order_relaxed);
    if (seg_lookup_n)
      g_seg_lookup.fetch_add(seg_lookup_n, std::memory_order_relaxed);

    lap("rows");
    // resolve delete ranges to row ids.  Ranges arrive grouped per
    // client (update DS sections are per-client), so a 1-entry slot memo
    // avoids a hash find per range; the memo must NOT create slots
    // (unknown clients in a DS are skipped, not integrated).
    int64_t del_cl_memo = INT64_MIN, del_slot_memo = kNull;
    for (size_t ai = 0; ai < applicable.size(); ai++) {
      auto [client, clock, ln] = applicable[ai];
      if (client != del_cl_memo) {
        auto sit = slot_of_client.find(client);
        del_cl_memo = client;
        del_slot_memo = sit == slot_of_client.end() ? kNull : sit->second;
      }
      if (del_slot_memo == kNull) continue;
      int64_t slot_ = del_slot_memo;
      auto& fc = frag_clock[slot_];
      auto& fr = frag_row[slot_];
      auto it = std::upper_bound(fc.begin(), fc.end(), clock);
      int64_t i = (int64_t)(it - fc.begin()) - 1;
      if (i < 0) i = 0;
      int64_t end = clock + ln;
      while (i < (int64_t)fc.size() && fc[(size_t)i] < end) {
        if (fc[(size_t)i] >= clock) delete_row(fr[(size_t)i]);
        i++;
      }
    }

    lap("deletes");
    // LWW: sorted seg order (delete order is consumer-order-independent)
    std::sort(touched_map_segs.begin(), touched_map_segs.end());
    lww_pass(touched_map_segs);
    lap("lww");
    plan.n_rows = n_rows();
    // the level-parallel schedule serves only the YATA device kernels
    // (YTPU_KERNEL=levels/seq and the sharded step); the default bulk
    // path ships final links and skips the level assignment entirely
    if (want_levels) assign_levels();
    lap("levels");
    // ascending row/seg order = the Python twin's `sorted(plan._dl)`.
    // When the dirty set is DENSE in the row range (bulk first flush),
    // recollect it ascending by scanning the dl_mark epoch array — O(range)
    // sequential loads beat an O(n log n) sort.  Sparse incremental
    // flushes on big mirrors keep the sort.
    {
      size_t nd = plan.dirty_links.size();
      if (nd > 16 && (size_t)n_rows() / 16 < nd) {
        plan.dirty_links.clear();
        size_t hi = std::min(dl_mark.size(), (size_t)n_rows());
        for (size_t r = 0; r < hi; r++)
          if (dl_mark[r] == dirty_epoch) plan.dirty_links.push_back((int64_t)r);
      } else {
        std::sort(plan.dirty_links.begin(), plan.dirty_links.end());
      }
    }
    std::sort(plan.dirty_heads.begin(), plan.dirty_heads.end());
    plan.link_rows.reserve(plan.dirty_links.size());
    plan.link_vals.reserve(plan.dirty_links.size());
    for (int64_t r : plan.dirty_links) {
      plan.link_rows.push_back(r);
      plan.link_vals.push_back(list_next[(size_t)r]);
    }
    for (int64_t s : plan.dirty_heads) {
      plan.head_segs.push_back(s);
      plan.head_vals.push_back(head_of_seg[(size_t)s]);
    }
    // rebuild `pending` from the unconsumed working-set tails: only refs
    // that failed the causal gate get a fat copy (common case: none).
    // Deferred to here because sched/qwork hold pointers into the OLD
    // pending vectors until the rows pass is done.
    {
      std::map<int64_t, std::vector<PendRef>> new_pending;
      for (size_t ci = 0; ci < clients_desc.size(); ci++) {
        auto& w = *clients_desc[ci].second;
        size_t head = q_head[ci];
        if (head >= w.size()) continue;
        auto& q = new_pending[clients_desc[ci].first];
        q.reserve(w.size() - head);
        for (size_t j = head; j < w.size(); j++) q.push_back(*w[j]);
      }
      pending.swap(new_pending);
    }
    lap("finalize");
    gen++;
    return 0;
  }

  // ---- level assignment (StepPlan.assign_levels twin) -------------------

  void assign_levels() {
    const bool timing = std::getenv("YMX_TIMING") != nullptr;
    auto t0 = std::chrono::steady_clock::now();
    auto lap = [&](const char* what) {
      if (!timing) return;
      auto t1 = std::chrono::steady_clock::now();
      std::fprintf(stderr, "[ymx-lv] %-12s %8.1f us\n", what,
                   std::chrono::duration<double, std::micro>(t1 - t0).count());
      t0 = t1;
    };
    auto& sched = plan.sched;
    size_t n = sched.size();
    // group by (left, right, seg) preserving first-appearance order
    struct Group {
      int64_t left, right, seg;
      std::vector<int64_t> members;  // row ids, sched order
    };
    std::vector<Group> groups;
    groups.reserve(n);
    std::unordered_map<uint64_t, std::vector<uint32_t>> gmap;  // hash -> idxs
    gmap.reserve(n * 2);
    auto ghash = [](int64_t l, int64_t r, int64_t s) -> uint64_t {
      uint64_t h = 1469598103934665603ull;
      for (uint64_t v : {(uint64_t)l, (uint64_t)r, (uint64_t)s}) {
        h ^= v + 0x9e3779b97f4a7c15ull;
        h *= 1099511628211ull;
      }
      return h;
    };
    for (size_t i = 0; i < n; i++) {
      int64_t left = sched[i][1], right = sched[i][2], sg = sched[i][3];
      auto& cands = gmap[ghash(left, right, sg)];
      int32_t found = -1;
      for (uint32_t gi : cands) {
        Group& g = groups[gi];
        if (g.left == left && g.right == right && g.seg == sg) {
          found = (int32_t)gi;
          break;
        }
      }
      if (found < 0) {
        cands.push_back((uint32_t)groups.size());
        groups.push_back({left, right, sg, {sched[i][0]}});
      } else {
        groups[(size_t)found].members.push_back(sched[i][0]);
      }
    }
    lap("grouping");
    plan.sched8.clear();
    plan.levels.clear();
    plan.sched8.reserve(n);
    plan.levels.reserve(n);
    // row -> level scratch (0 = unassigned this pass)
    std::vector<int64_t> lev_of_row((size_t)n_rows(), 0);
    auto lev_of = [&](int64_t row) {
      return (row >= 0 && row < (int64_t)lev_of_row.size())
                 ? lev_of_row[(size_t)row]
                 : 0;
    };
    // per-gap used levels (tiny sorted vectors; usually length 1)
    std::unordered_map<int64_t, std::vector<int64_t>> used;
    used.reserve(groups.size() * 2);
    // open chain tails: tail row -> (entry idx, head check, head right, lev)
    std::unordered_map<int64_t, std::array<int64_t, 4>> tails;
    tails.reserve(groups.size() * 2);
    int64_t n_levels = 0;
    for (auto& g : groups) {
      int64_t left = g.left, right = g.right, sg = g.seg;
      auto& members = g.members;
      if (members.size() > 1)
        std::stable_sort(members.begin(), members.end(),
                         [&](int64_t a, int64_t b) {
                           return row_client(a) < row_client(b);
                         });
      auto tit = left != kNull ? tails.find(left) : tails.end();
      if (tit != tails.end() && tit->second[2] == right &&
          plan.sched8[(size_t)tit->second[0]][5] == sg) {
        // stitch: continue the chain ending at `left` in place
        auto [idx0, hchk, hr0, lev] = tit->second;
        plan.sched8[(size_t)idx0][4] = members[0];
        for (size_t j = 0; j < members.size(); j++) {
          int64_t row = members[j];
          int64_t succ = j + 1 < members.size() ? members[j + 1] : kGatherSucc;
          plan.sched8.push_back(
              {{row, kNoLeftWrite, hr0, hchk, succ, sg, left, right}});
          plan.levels.push_back(lev);
          lev_of_row[(size_t)row] = lev;
        }
        tails.erase(left);
        tails[members.back()] = {(int64_t)plan.sched8.size() - 1, hchk, hr0,
                                 lev};
        continue;
      }
      int64_t base = 1 + std::max(lev_of(left), lev_of(right));
      int64_t gap = left != kNull ? left : ~sg;  // head writes keyed per seg
      int64_t lev = base;
      {
        auto& lvls = used[gap];
        auto it = std::lower_bound(lvls.begin(), lvls.end(), lev);
        while (it != lvls.end() && *it == lev) {
          ++lev;
          ++it;
        }
        lvls.insert(it, lev);
      }
      for (size_t j = 0; j < members.size(); j++) {
        int64_t row = members[j];
        int64_t entry_left = j == 0 ? left : kNoLeftWrite;
        int64_t succ = j + 1 < members.size() ? members[j + 1] : kGatherSucc;
        plan.sched8.push_back(
            {{row, entry_left, right, left, succ, sg, left, right}});
        plan.levels.push_back(lev);
        lev_of_row[(size_t)row] = lev;
      }
      tails[members.back()] = {(int64_t)plan.sched8.size() - 1, left, right,
                               lev};
      n_levels = std::max(n_levels, lev);
    }
    lap("main-loop");
    plan.n_levels = n_levels;
    // width of the widest level (for the engine's padded pack)
    std::vector<int64_t> width((size_t)n_levels, 0);
    for (int64_t lv : plan.levels) width[(size_t)(lv - 1)]++;
    plan.max_width = 0;
    for (int64_t w : width) plan.max_width = std::max(plan.max_width, w);
  }

  // ---- compaction (DocMirror.rebuild_compacted twin) --------------------

  // merge content descriptors of rows a,b; returns false when not mergeable
  bool desc_merge(int64_t a, int64_t b) {
    ContentDesc& ca = r_c[(size_t)a];
    ContentDesc& cb = r_c[(size_t)b];
    if (ca.kind != cb.kind) return false;
    switch (ca.kind) {
      case kKindDeleted:
        return true;
      case kKindUtf8:
      case kKindAnys:
      case kKindJsons: {
        if (ca.kind != kKindUtf8 && ca.v2 != cb.v2) return false;
        if (ca.buf == cb.buf && ca.end == cb.ofs) {
          ca.end = cb.end;  // naturally adjacent: extend in place
        } else {
          std::vector<uint8_t> merged(buf_ptr(ca.buf) + ca.ofs,
                                      buf_ptr(ca.buf) + ca.end);
          merged.insert(merged.end(), buf_ptr(cb.buf) + cb.ofs,
                        buf_ptr(cb.buf) + cb.end);
          int64_t nb = arena(std::move(merged));
          ca.buf = nb;
          ca.ofs = 0;
          ca.end = (int64_t)buf_len(nb);
        }
        if (ca.kind != kKindUtf8) ca.count += cb.count;
        return true;
      }
      default:
        return false;  // Framed/V2Lazy: length-1 kinds never merge
    }
  }

  bool try_merge(int64_t a, int64_t b, const uint8_t* deleted) {
    if (r_slot[a] != r_slot[b]) return false;
    if (r_clock[a] + r_len[a] != r_clock[b]) return false;
    if ((deleted[a] != 0) != (deleted[b] != 0)) return false;
    if (r_is_gc[a] != r_is_gc[b]) return false;
    if (segs_of_parent.count(a) || segs_of_parent.count(b)) return false;
    if (r_is_gc[a]) return true;
    if (r_oslot[b] != r_slot[a] ||
        r_oclock[b] != r_clock[a] + r_len[a] - 1)
      return false;
    if (!row_right_eq(a, b)) return false;
    if (r_ref[a] != r_ref[b]) return false;
    return desc_merge(a, b);
  }

  // renumber every host structure after compaction decided `keep`
  void renumber(const std::vector<int64_t>& keep,
                const std::vector<int64_t>& new_of_old) {
    auto take_i = [&](std::vector<int64_t>& col) {
      std::vector<int64_t> out;
      out.reserve(keep.size());
      for (int64_t r : keep) out.push_back(col[(size_t)r]);
      col = std::move(out);
    };
    auto take_b = [&](std::vector<uint8_t>& col) {
      std::vector<uint8_t> out;
      out.reserve(keep.size());
      for (int64_t r : keep) out.push_back(col[(size_t)r]);
      col = std::move(out);
    };
    take_i(r_slot); take_i(r_clock); take_i(r_len);
    take_i(r_oslot); take_i(r_oclock); take_i(r_rslot); take_i(r_rclock);
    take_i(r_ref); take_i(r_seg);
    take_b(r_is_gc); take_b(r_countable);
    take_b(r_host_deleted); take_b(r_lww_deleted);
    {
      std::vector<ContentDesc> out;
      out.reserve(keep.size());
      for (int64_t r : keep) out.push_back(r_c[(size_t)r]);
      r_c = std::move(out);
    }
    gen++;
    // fragment index: rebuild clock-sorted per slot
    size_t n_slots = client_of_slot.size();
    for (size_t s = 0; s < n_slots; s++) {
      frag_clock[s].clear();
      frag_row[s].clear();
    }
    std::vector<std::vector<int64_t>> by_slot(n_slots);
    for (size_t row = 0; row < r_slot.size(); row++)
      by_slot[(size_t)r_slot[row]].push_back((int64_t)row);
    for (size_t s = 0; s < n_slots; s++) {
      auto& rows = by_slot[s];
      std::sort(rows.begin(), rows.end(), [&](int64_t a, int64_t b) {
        return r_clock[a] < r_clock[b];
      });
      for (int64_t r : rows) {
        frag_clock[s].push_back(r_clock[r]);
        frag_row[s].push_back(r);
      }
    }
    // map chains / nested bookkeeping
    for (auto& [sg, chain] : map_chain) {
      std::vector<int64_t> out;
      for (int64_t r : chain)
        if (new_of_old[(size_t)r] != kNull)
          out.push_back(new_of_old[(size_t)r]);
      chain = std::move(out);
    }
    {
      std::unordered_map<int64_t, std::vector<int64_t>> out;
      for (auto& [sg, rows] : rows_of_seg) {
        std::vector<int64_t> nr;
        for (int64_t r : rows)
          if (new_of_old[(size_t)r] != kNull)
            nr.push_back(new_of_old[(size_t)r]);
        out[sg] = std::move(nr);
      }
      rows_of_seg = std::move(out);
    }
    {
      // seg parents renumber (type rows never merge, so they survive)
      std::map<std::tuple<int64_t, int64_t, int64_t>, int64_t> lookup;
      std::unordered_map<int64_t, std::vector<int64_t>> parents;
      for (int64_t s = 0; s < n_segs(); s++) {
        if (seg_parent[s] != kNull)
          seg_parent[s] = new_of_old[(size_t)seg_parent[s]];
        lookup[std::make_tuple(seg_name_id[s], seg_sub_id[s],
                               seg_parent[s])] = s;
        if (seg_parent[s] != kNull) parents[seg_parent[s]].push_back(s);
      }
      seg_lookup = std::move(lookup);
      segs_of_parent = std::move(parents);
    }
    // compact DS ranges (sorted union per slot)
    for (auto& ranges : ds) {
      if (ranges.empty()) continue;
      std::sort(ranges.begin(), ranges.end());
      std::vector<std::array<int64_t, 2>> out;
      for (auto& [clock, ln] : ranges) {
        if (!out.empty() && clock <= out.back()[0] + out.back()[1]) {
          out.back()[1] =
              std::max(out.back()[1], clock + ln - out.back()[0]);
        } else {
          out.push_back({{clock, ln}});
        }
      }
      ranges = std::move(out);
    }
  }

  // full compaction entry: device read-back in, renumbered device state out
  int64_t compact(const int32_t* right_link, const uint8_t* deleted,
                  const int32_t* heads, int64_t n_heads, int gc,
                  int32_t* new_right, uint8_t* new_deleted,
                  int32_t* new_heads, int64_t new_heads_cap) {
    int64_t n = n_rows();
    // per-seg order from the read-back links
    std::vector<std::vector<int64_t>> order_of_seg((size_t)n_segs());
    for (int64_t sg = 0; sg < n_segs(); sg++) {
      int64_t head = sg < n_heads ? heads[sg] : kNull;
      int64_t r = head;
      while (r != kNull) {
        order_of_seg[(size_t)sg].push_back(r);
        r = right_link[r];
      }
    }
    if (gc) {
      for (int64_t row = 0; row < n; row++) {
        if (!r_is_gc[row] && deleted[row] && r_ref[row] != 1) {
          r_c[(size_t)row] = ContentDesc{};
          r_c[(size_t)row].kind = kKindDeleted;
          r_ref[row] = 1;
          r_countable[row] = 0;
        }
      }
    }
    std::unordered_map<int64_t, int64_t> absorbed;
    for (int64_t sg = 0; sg < n_segs(); sg++) {
      if (seg_is_map(sg)) continue;
      auto& order = order_of_seg[(size_t)sg];
      size_t i = 0;
      while (i + 1 < order.size()) {
        int64_t a = order[i], b = order[i + 1];
        if (try_merge(a, b, deleted)) {
          r_len[a] += r_len[b];
          absorbed[b] = a;
          order.erase(order.begin() + (ptrdiff_t)(i + 1));
        } else {
          i++;
        }
      }
    }
    // GC structs: merge contiguous runs per client (not in any list)
    for (size_t s = 0; s < client_of_slot.size(); s++) {
      int64_t prev = kNull;
      for (int64_t row : frag_row[s]) {
        if (!r_is_gc[row] || absorbed.count(row)) {
          prev = r_is_gc[row] ? row : kNull;
          continue;
        }
        if (prev != kNull && try_merge(prev, row, deleted)) {
          r_len[prev] += r_len[row];
          absorbed[row] = prev;
        } else {
          prev = row;
        }
      }
    }
    std::vector<int64_t> new_of_old((size_t)n, kNull);
    std::vector<int64_t> keep;
    keep.reserve((size_t)n);
    for (int64_t r = 0; r < n; r++) {
      if (!absorbed.count(r)) {
        new_of_old[(size_t)r] = (int64_t)keep.size();
        keep.push_back(r);
      }
    }
    renumber(keep, new_of_old);
    int64_t n_new = (int64_t)keep.size();
    for (int64_t r = 0; r < n_new; r++) {
      new_right[r] = (int32_t)kNull;
      new_deleted[r] = deleted[keep[(size_t)r]];
    }
    for (int64_t sg = 0; sg < std::min(new_heads_cap, n_segs()); sg++)
      new_heads[sg] = (int32_t)kNull;
    list_next.assign((size_t)n_new, kNull);
    head_of_seg.assign((size_t)n_segs(), kNull);
    for (int64_t sg = 0; sg < n_segs(); sg++) {
      int64_t prev = kNull;
      for (int64_t old : order_of_seg[(size_t)sg]) {
        int64_t nr = new_of_old[(size_t)old];
        if (prev == kNull) {
          if (sg < new_heads_cap) new_heads[sg] = (int32_t)nr;
          head_of_seg[(size_t)sg] = nr;
        } else {
          new_right[prev] = (int32_t)nr;
          list_next[(size_t)prev] = nr;
        }
        prev = nr;
      }
    }
    return n_new;
  }
};

// shared by the V1/V2 diff writers: remote state per slot, slot order
// (descending client), and the DS section groups
struct DiffPrep {
  std::vector<int64_t> remote;
  std::vector<size_t> slot_order;
  std::vector<int64_t> dg_client, dg_start, dg_len, d_clock, d_len;
};

inline void build_diff_prep(Mirror* m, const int64_t* sv_clients,
                            const int64_t* sv_clocks, int64_t n_sv,
                            const int64_t* ds_ranges, int64_t n_ds_override,
                            int ds_override, DiffPrep* p) {
  size_t n_slots = m->client_of_slot.size();
  p->remote.assign(n_slots, 0);
  for (int64_t i = 0; i < n_sv; i++) {
    auto it = m->slot_of_client.find(sv_clients[i]);
    if (it != m->slot_of_client.end())
      p->remote[(size_t)it->second] = sv_clocks[i];
  }
  p->slot_order.resize(n_slots);
  for (size_t s = 0; s < n_slots; s++) p->slot_order[s] = s;
  std::sort(p->slot_order.begin(), p->slot_order.end(),
            [&](size_t a, size_t b) {
              return m->client_of_slot[a] > m->client_of_slot[b];
            });
  auto push_union = [&](int64_t client,
                        std::vector<std::array<int64_t, 2>>& ranges) {
    std::sort(ranges.begin(), ranges.end());
    size_t start = p->d_clock.size();
    for (auto& [ck, ln] : ranges) {
      if (p->d_clock.size() > start &&
          ck <= p->d_clock.back() + p->d_len.back()) {
        p->d_len.back() =
            std::max(p->d_len.back(), ck + ln - p->d_clock.back());
      } else {
        p->d_clock.push_back(ck);
        p->d_len.push_back(ln);
      }
    }
    if (p->d_clock.size() > start) {
      p->dg_client.push_back(client);
      p->dg_start.push_back((int64_t)start);
      p->dg_len.push_back((int64_t)(p->d_clock.size() - start));
    }
  };
  if (ds_override) {
    std::vector<int64_t> order;
    std::unordered_map<int64_t, std::vector<std::array<int64_t, 2>>> by;
    for (int64_t i = 0; i < n_ds_override; i++) {
      int64_t cl = ds_ranges[i * 3];
      if (!by.count(cl)) order.push_back(cl);
      by[cl].push_back({{ds_ranges[i * 3 + 1], ds_ranges[i * 3 + 2]}});
    }
    for (int64_t cl : order) push_union(cl, by[cl]);
  } else {
    for (int64_t slot : m->ds_slot_order) {
      auto ranges = m->ds[slot];  // copy: union sorts
      push_union(m->client_of_slot[(size_t)slot], ranges);
    }
  }
}

// ---------------------------------------------------------------------------
// native V2 wire writer: the 9-stream columnar container (reference
// UpdateEncoder.js:264-408; byte-identical to yjs_tpu/coding.py
// UpdateEncoderV2, including the never-populated key_map quirk)
// ---------------------------------------------------------------------------

struct VecW {
  std::vector<uint8_t> b;
  void u8(uint8_t x) { b.push_back(x); }
  void varuint(uint64_t n) {
    while (n > 0x7f) { b.push_back(0x80 | (n & 0x7f)); n >>= 7; }
    b.push_back((uint8_t)n);
  }
  // lib0 signed varint (sign-magnitude, 6 bits in the first byte)
  void varint(int64_t num, bool neg_zero = false) {
    bool neg = num < 0 || neg_zero;
    uint64_t n = neg ? (uint64_t)(-num) : (uint64_t)num;
    b.push_back((n > 0x3f ? 0x80 : 0) | (neg ? 0x40 : 0) | (n & 0x3f));
    n >>= 6;
    while (n > 0) { b.push_back((n > 0x7f ? 0x80 : 0) | (n & 0x7f)); n >>= 7; }
  }
  void bytes(const uint8_t* p, size_t n) { b.insert(b.end(), p, p + n); }
};

struct RleW {  // lib0 RleEncoder over write_uint8 (no trailing count)
  VecW o;
  int64_t s = 0, count = 0;
  void write(int64_t v) {
    if (s == v && count > 0) { count++; return; }
    if (count > 0) o.varuint((uint64_t)(count - 1));
    count = 1;
    o.u8((uint8_t)v);
    s = v;
  }
};

struct UintOptW {  // lib0 UintOptRleEncoder
  VecW o;
  int64_t s = 0, count = 0;
  void write(int64_t v) {
    if (s == v) { count++; return; }
    flush();
    count = 1;
    s = v;
  }
  void flush() {
    if (count > 0) {
      if (count == 1) o.varint(s);
      else { o.varint(-s, s == 0); o.varuint((uint64_t)(count - 2)); }
    }
  }
};

struct IntDiffOptW {  // lib0 IntDiffOptRleEncoder
  VecW o;
  int64_t s = 0, count = 0, diff = 0;
  void write(int64_t v) {
    if (diff == v - s) { s = v; count++; return; }
    flush();
    count = 1;
    diff = v - s;
    s = v;
  }
  void flush() {
    if (count > 0) {
      o.varint(diff * 2 + (count == 1 ? 0 : 1));
      if (count > 1) o.varuint((uint64_t)(count - 2));
    }
  }
};

inline int64_t utf16_len_of(const uint8_t* p, uint64_t n);

struct StringW {  // lib0 StringEncoder: one UTF-8 arena + u16-length runs
  std::vector<uint8_t> arena;
  UintOptW lens;
  // append a raw UTF-8 range; u16len = its UTF-16 unit count
  void write(const uint8_t* p, size_t n, int64_t u16len) {
    arena.insert(arena.end(), p, p + n);
    lens.write(u16len);
  }
  // cut `off` UTF-16 units off the front (the partial-first-struct rule),
  // with the surrogate-pair U+FFFD repair of write_cut_string
  // false on a truncated trailing multi-byte sequence (the skip loop
  // would overshoot — same guard as desc_split's surrogate branch)
  bool write_cut(const uint8_t* s, uint64_t blen, int64_t off) {
    uint64_t i = 0;
    bool mid = false;
    int64_t skipped = 0;
    int64_t total = utf16_len_of(s, blen);
    while (skipped < off && i < blen) {
      uint8_t c = s[i];
      if (c < 0x80) { skipped += 1; i += 1; }
      else if (c < 0xE0) { skipped += 1; i += 2; }
      else if (c < 0xF0) { skipped += 1; i += 3; }
      else {
        skipped += 2; i += 4;
        if (skipped > off) mid = true;
      }
    }
    if (i > blen) return false;  // malformed UTF-8 tail
    if (mid) {  // the cut consumed a pair: emit the U+FFFD low half
      static const uint8_t rep[3] = {0xEF, 0xBF, 0xBD};
      arena.insert(arena.end(), rep, rep + 3);
    }
    arena.insert(arena.end(), s + i, s + blen);
    lens.write(total - off);
    return true;
  }
  void emit(VecW* out) {
    // StringEncoder.to_bytes = var_string(arena) + RAW lens bytes, the
    // whole thing wrapped in the container's var_uint8_array
    UintOptW tmp = lens;  // copy: flush is destructive
    tmp.flush();
    VecW inner;
    inner.varuint(arena.size());
    inner.bytes(arena.data(), arena.size());
    inner.bytes(tmp.o.b.data(), tmp.o.b.size());
    out->varuint(inner.b.size());
    out->bytes(inner.b.data(), inner.b.size());
  }
};

struct V2W {
  IntDiffOptW key_clock;
  UintOptW client;
  IntDiffOptW left_clock;
  IntDiffOptW right_clock;
  RleW info;
  StringW str;
  RleW parent_info;
  UintOptW type_ref;
  UintOptW len;
  VecW rest;
  int64_t key_counter = 0;

  void write_left_id(int64_t c, int64_t k) { client.write(c); left_clock.write(k); }
  void write_right_id(int64_t c, int64_t k) { client.write(c); right_clock.write(k); }
  // the v13.4.9 write_key quirk: the dictionary is never populated, so
  // every key emits a fresh clock AND the string (UpdateEncoder.js:399-407)
  void write_key(const uint8_t* p, size_t n, int64_t u16len) {
    key_clock.write(key_counter++);
    str.write(p, n, u16len);
  }

  std::vector<uint8_t> finish() {
    VecW out;
    out.u8(0);  // feature flag
    auto stream = [&](VecW& v) {
      out.varuint(v.b.size());
      out.bytes(v.b.data(), v.b.size());
    };
    auto opt = [&](UintOptW& e) { UintOptW t = e; t.flush(); stream(t.o); };
    auto idf = [&](IntDiffOptW& e) { IntDiffOptW t = e; t.flush(); stream(t.o); };
    idf(key_clock);
    opt(client);
    idf(left_clock);
    idf(right_clock);
    stream(info.o);
    str.emit(&out);
    stream(parent_info.o);
    opt(type_ref);
    opt(len);
    out.bytes(rest.b.data(), rest.b.size());
    return std::move(out.b);
  }
};

inline int64_t utf16_len_of(const uint8_t* p, uint64_t n) {
  int64_t u = 0;
  for (uint64_t i = 0; i < n;) {
    uint8_t c = p[i];
    if (c < 0x80) { u += 1; i += 1; }
    else if (c < 0xE0) { u += 1; i += 2; }
    else if (c < 0xF0) { u += 1; i += 3; }
    else { u += 2; i += 4; }
  }
  return u;
}

// full-native V2 sync encode (the V2 twin of mirror_encode_diff).
// Returns the update bytes via `out` vector; -7 when the selection needs
// the Python writer (V1-framed embed/format/type or spilled content).
int64_t mirror_encode_diff_v2(Mirror* m, const int64_t* sv_clients,
                              const int64_t* sv_clocks, int64_t n_sv,
                              const int64_t* ds_ranges, int64_t n_ds_override,
                              int ds_override,
                              std::vector<uint8_t>* out_bytes) {
  DiffPrep prep;
  build_diff_prep(m, sv_clients, sv_clocks, n_sv, ds_ranges, n_ds_override,
                  ds_override, &prep);
  auto& remote = prep.remote;
  auto& slot_order = prep.slot_order;
  // selection per slot (rows in clock order via the frag index)
  std::vector<std::pair<size_t, std::vector<int64_t>>> groups;
  for (size_t si : slot_order) {
    std::vector<int64_t> rows;
    int64_t rem = remote[si];
    for (int64_t r : m->frag_row[si])
      if (m->r_clock[r] + m->r_len[r] > rem) rows.push_back(r);
    if (!rows.empty()) groups.push_back({si, std::move(rows)});
  }
  // scope check first: fall back before writing anything
  for (auto& [si, rows] : groups) {
    for (int64_t r : rows) {
      const ContentDesc& c = m->r_c[(size_t)r];
      if (c.kind == kKindSpill) return -7;
      if (c.kind == kKindFramed && m->r_ref[r] != 3) return -7;
    }
  }
  V2W w;
  w.rest.varuint(groups.size());
  for (auto& [si, rows] : groups) {
    int64_t rem = remote[si];
    w.rest.varuint(rows.size());
    w.client.write(m->client_of_slot[si]);
    int64_t first_ofs = std::max<int64_t>(0, rem - m->r_clock[rows[0]]);
    w.rest.varuint((uint64_t)(m->r_clock[rows[0]] + first_ofs));
    bool first = true;
    for (int64_t r : rows) {
      int64_t ofs = first ? first_ofs : 0;
      first = false;
      const ContentDesc& c = m->r_c[(size_t)r];
      int64_t ref = m->r_ref[r];
      if (m->r_is_gc[r]) {
        w.info.write(0);
        w.len.write(m->r_len[r] - ofs);
        continue;
      }
      int64_t oc = m->r_oslot[r] == kNull
                       ? kNull
                       : m->client_of_slot[(size_t)m->r_oslot[r]];
      int64_t ok = m->r_oclock[r];
      if (ofs > 0) { oc = m->client_of_slot[si]; ok = m->r_clock[r] + ofs - 1; }
      int64_t rc = m->r_rslot[r] == kNull
                       ? kNull
                       : m->client_of_slot[(size_t)m->r_rslot[r]];
      int64_t rk = m->r_rclock[r];
      int64_t sg = m->r_seg[r];
      int64_t ni = sg == kNull ? kNull : m->seg_name_id[sg];
      int64_t sui = sg == kNull ? kNull : m->seg_sub_id[sg];
      int64_t pr = sg == kNull ? kNull : m->seg_parent[sg];
      uint8_t inf = (uint8_t)(ref & kBits5);
      if (oc >= 0) inf |= kBit8;
      if (rc >= 0) inf |= kBit7;
      if (sui != kNull) inf |= kBit6;
      w.info.write(inf);
      if (oc >= 0) w.write_left_id(oc, ok);
      if (rc >= 0) w.write_right_id(rc, rk);
      if (oc < 0 && rc < 0) {
        if (pr != kNull) {
          w.parent_info.write(0);
          w.write_left_id(
              m->client_of_slot[(size_t)m->r_slot[(size_t)pr]],
              m->r_clock[(size_t)pr]);
        } else if (ni != kNull) {
          w.parent_info.write(1);
          const uint8_t* np = m->strings.data() + m->intern_ofs[(size_t)ni];
          size_t nl = (size_t)m->intern_len[(size_t)ni];
          w.str.write(np, nl, utf16_len_of(np, nl));
        } else {
          return -3;
        }
        if (sui != kNull) {
          const uint8_t* sp = m->strings.data() + m->intern_ofs[(size_t)sui];
          size_t sl = (size_t)m->intern_len[(size_t)sui];
          w.str.write(sp, sl, utf16_len_of(sp, sl));
        }
      }
      // content (write order matches the Python Content*.write methods)
      switch (c.kind) {
        case kKindDeleted:
          w.len.write(m->r_len[r] - ofs);
          break;
        case kKindUtf8:
          if (!w.str.write_cut(m->buf_ptr(c.buf) + c.ofs,
                               (uint64_t)(c.end - c.ofs), ofs))
            return -4;
          break;
        case kKindAnys: {  // write_len + element any bytes into rest
          w.len.write(c.count - ofs);
          Reader er{m->buf_ptr(c.buf), (uint64_t)c.end, (uint64_t)c.ofs,
                    false};
          for (int64_t i = 0; i < ofs && !er.fail; i++) er.skip_any();
          if (er.fail) return -4;
          w.rest.bytes(m->buf_ptr(c.buf) + er.pos,
                       (size_t)(c.end - (int64_t)er.pos));
          break;
        }
        case kKindJsons: {  // write_len + each element into the str stream
          w.len.write(c.count - ofs);
          Reader er{m->buf_ptr(c.buf), (uint64_t)c.end, (uint64_t)c.ofs,
                    false};
          for (int64_t i = 0; i < c.count && !er.fail; i++) {
            uint64_t o, bl;
            er.var_string(&o, &bl);
            if (i >= ofs)
              w.str.write(m->buf_ptr(c.buf) + o, (size_t)bl,
                          utf16_len_of(m->buf_ptr(c.buf) + o, bl));
          }
          if (er.fail) return -4;
          break;
        }
        case kKindFramed:  // ref 3 only (checked above): varuint+bytes
          w.rest.bytes(m->buf_ptr(c.buf) + c.ofs, (size_t)(c.end - c.ofs));
          break;
        case kKindV2Lazy: {
          if (ref == 5) {  // embed: any into rest
            w.rest.bytes(m->buf_ptr(c.buf) + c.ofs,
                         (size_t)(c.end - c.ofs));
          } else if (ref == 6) {  // format: key via write_key, value any
            const uint8_t* kp = m->buf_ptr(c.buf) + c.ofs;
            size_t kl = (size_t)(c.end - c.ofs);
            w.write_key(kp, kl, utf16_len_of(kp, kl));
            w.rest.bytes(m->buf_ptr(c.buf) + c.ofs2,
                         (size_t)(c.end2 - c.ofs2));
          } else if (ref == 7) {  // type: type_ref (+ name via write_key)
            w.type_ref.write(c.count);
            if (c.count == 3 || c.count == 5) {
              if (c.ofs < 0) return -7;
              const uint8_t* np2 = m->buf_ptr(c.buf) + c.ofs;
              size_t nl2 = (size_t)(c.end - c.ofs);
              w.write_key(np2, nl2, utf16_len_of(np2, nl2));
            }
          } else {
            return -7;
          }
          break;
        }
        default:
          return -7;
      }
    }
  }
  // DS section (DSEncoderV2: delta clocks, len-1; groups from
  // build_diff_prep)
  auto& dg_client = prep.dg_client;
  auto& dg_start = prep.dg_start;
  auto& dg_len = prep.dg_len;
  auto& d_clock = prep.d_clock;
  auto& d_len = prep.d_len;
  w.rest.varuint(dg_client.size());
  for (size_t g = 0; g < dg_client.size(); g++) {
    int64_t cur = 0;
    w.rest.varuint((uint64_t)dg_client[g]);
    w.rest.varuint((uint64_t)dg_len[g]);
    for (int64_t i = dg_start[g]; i < dg_start[g] + dg_len[g]; i++) {
      w.rest.varuint((uint64_t)(d_clock[(size_t)i] - cur));
      cur = d_clock[(size_t)i];
      if (d_len[(size_t)i] <= 0) return -4;
      w.rest.varuint((uint64_t)(d_len[(size_t)i] - 1));
      cur += d_len[(size_t)i];
    }
  }
  *out_bytes = w.finish();
  return (int64_t)out_bytes->size();
}

}  // namespace

// the V1 wire writer (transcode.cpp, same shared object)
extern "C" int64_t ytpu_encode_v1(
    const uint8_t** bufs, const uint64_t* buf_lens, uint64_t n_bufs,
    const int64_t* group_client, const int64_t* group_start,
    const int64_t* group_len, uint64_t n_groups,
    const int64_t* clock, const int64_t* length, const int64_t* offset,
    const int64_t* origin_client, const int64_t* origin_clock,
    const int64_t* right_client, const int64_t* right_clock,
    const int64_t* content_ref,
    const int64_t* name_ofs, const int64_t* name_len,
    const int64_t* sub_ofs, const int64_t* sub_len,
    const int64_t* parent_client, const int64_t* parent_clock,
    const int64_t* src_kind, const int64_t* src_buf,
    const int64_t* src_ofs, const int64_t* src_end,
    const uint8_t* strings, uint64_t strings_len,
    const int64_t* ds_group_client, const int64_t* ds_group_start,
    const int64_t* ds_group_len, uint64_t n_ds_groups,
    const int64_t* ds_clock, const int64_t* ds_len,
    uint8_t* out, uint64_t out_cap);

namespace {


// full-native sync encode: rows beyond a remote state vector, written
// straight from the mirror state (reference encodeStateAsUpdate,
// encoding.js:490-526 + writeClientsStructs :94-116).  Returns bytes
// written, -7 when a selected row needs the Python spill path (V2-framed
// embed/format/type payloads), <0 on writer errors.
int64_t mirror_encode_diff(Mirror* m, const int64_t* sv_clients,
                           const int64_t* sv_clocks, int64_t n_sv,
                           const int64_t* ds_ranges, int64_t n_ds_override,
                           int ds_override, uint8_t* out, uint64_t cap) {
  DiffPrep prep;
  build_diff_prep(m, sv_clients, sv_clocks, n_sv, ds_ranges, n_ds_override,
                  ds_override, &prep);
  auto& remote = prep.remote;
  // slots in descending client order ("heavily improves the conflict
  // algorithm", encoding.js:112)
  auto& slot_order = prep.slot_order;
  // selected rows, flat in group order
  std::vector<int64_t> g_client, g_start, g_len;
  std::vector<int64_t> c_clock, c_len, c_ofs, c_oc, c_ok, c_rc, c_rk, c_ref;
  std::vector<int64_t> c_no, c_nl, c_so, c_sl, c_pc, c_pk;
  std::vector<int64_t> c_sk, c_sb, c_sofs, c_send;
  for (size_t si : slot_order) {
    int64_t rem = remote[si];
    size_t start = c_clock.size();
    for (int64_t r : m->frag_row[si]) {
      int64_t end = m->r_clock[r] + m->r_len[r];
      if (end <= rem) continue;
      const ContentDesc& c = m->r_c[(size_t)r];
      if (c.kind == kKindV2Lazy || c.kind == kKindSpill) return -7;
      int64_t off = std::max<int64_t>(0, rem - m->r_clock[r]);
      c_clock.push_back(m->r_clock[r]);
      c_len.push_back(m->r_len[r]);
      c_ofs.push_back(off);
      c_oc.push_back(m->r_oslot[r] == kNull
                         ? kNull
                         : m->client_of_slot[(size_t)m->r_oslot[r]]);
      c_ok.push_back(m->r_oclock[r]);
      c_rc.push_back(m->r_rslot[r] == kNull
                         ? kNull
                         : m->client_of_slot[(size_t)m->r_rslot[r]]);
      c_rk.push_back(m->r_rclock[r]);
      c_ref.push_back(m->r_ref[r]);
      int64_t sg = m->r_seg[r];
      int64_t ni = sg == kNull ? kNull : m->seg_name_id[sg];
      int64_t sui = sg == kNull ? kNull : m->seg_sub_id[sg];
      int64_t pr = sg == kNull ? kNull : m->seg_parent[sg];
      c_no.push_back(ni == kNull ? kNull : m->intern_ofs[(size_t)ni]);
      c_nl.push_back(ni == kNull ? 0 : m->intern_len[(size_t)ni]);
      c_so.push_back(sui == kNull ? kNull : m->intern_ofs[(size_t)sui]);
      c_sl.push_back(sui == kNull ? 0 : m->intern_len[(size_t)sui]);
      c_pc.push_back(
          pr == kNull ? kNull
                      : m->client_of_slot[(size_t)m->r_slot[(size_t)pr]]);
      c_pk.push_back(pr == kNull ? 0 : m->r_clock[(size_t)pr]);
      c_sk.push_back(m->r_is_gc[r] ? kSrcNone : c.kind);
      c_sb.push_back(c.buf);
      c_sofs.push_back(c.ofs);
      c_send.push_back(c.end);
    }
    if (c_clock.size() > start) {
      g_client.push_back(m->client_of_slot[si]);
      g_start.push_back((int64_t)start);
      g_len.push_back((int64_t)(c_clock.size() - start));
    }
  }
  // DS section (built by build_diff_prep)
  auto& dg_client = prep.dg_client;
  auto& dg_start = prep.dg_start;
  auto& dg_len = prep.dg_len;
  auto& d_clock = prep.d_clock;
  auto& d_len = prep.d_len;
  std::vector<const uint8_t*> bptrs;
  std::vector<uint64_t> blens;
  for (auto& [p, ln] : m->bufs) {
    bptrs.push_back(p);
    blens.push_back(ln);
  }
  static const uint8_t kNoBuf = 0;
  if (bptrs.empty()) {
    bptrs.push_back(&kNoBuf);
    blens.push_back(0);
  }
  static const int64_t kZero = 0;
  auto dat = [](std::vector<int64_t>& v) {
    return v.empty() ? &kZero : v.data();
  };
  return ytpu_encode_v1(
      bptrs.data(), blens.data(), bptrs.size(),
      dat(g_client), dat(g_start), dat(g_len), g_client.size(),
      dat(c_clock), dat(c_len), dat(c_ofs),
      dat(c_oc), dat(c_ok), dat(c_rc), dat(c_rk), dat(c_ref),
      dat(c_no), dat(c_nl), dat(c_so), dat(c_sl), dat(c_pc), dat(c_pk),
      dat(c_sk), dat(c_sb), dat(c_sofs), dat(c_send),
      m->strings.empty() ? &kNoBuf : m->strings.data(), m->strings.size(),
      dat(dg_client), dat(dg_start), dat(dg_len), dg_client.size(),
      dat(d_clock), dat(d_len), out, cap);
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void* ymx_new() { return new Mirror(); }
void ymx_free(void* h) { delete static_cast<Mirror*>(h); }

// batched buffer registration across docs: one ctypes crossing for the
// whole flush's staged updates (hs[i] may repeat for docs staging more
// than one update; ids come back in input order)
void ymx_add_bufs_many(void** hs, const uint8_t* const* ptrs,
                       const uint64_t* lens, int64_t n, int64_t* out_ids) {
  for (int64_t i = 0; i < n; i++)
    out_ids[i] =
        static_cast<Mirror*>(hs[i])->add_buf(ptrs[i], lens[i]);
}

int64_t ymx_add_buf(void* h, const uint8_t* p, uint64_t n) {
  return static_cast<Mirror*>(h)->add_buf(p, n);
}

int64_t ymx_n_bufs(void* h) {
  return (int64_t)static_cast<Mirror*>(h)->bufs.size();
}

// roll back buffer registrations from a failed scan (nothing referenced
// them: scan failures happen before any ref merges; arena chunks are only
// created by later phases, so the tail is exactly the staged updates)
void ymx_drop_bufs_from(void* h, int64_t first) {
  Mirror* m = static_cast<Mirror*>(h);
  if (first >= 0 && (size_t)first < m->bufs.size())
    m->bufs.resize((size_t)first);
}

int64_t ymx_buf_len(void* h, int64_t idx) {
  Mirror* m = static_cast<Mirror*>(h);
  if (idx < 0 || (size_t)idx >= m->bufs.size()) return -1;
  return (int64_t)m->buf_len(idx);
}

// run the flush pipeline over the staged updates (buf ids + v2 flags).
// out_counts (int64[12]): n_rows, n_splits, n_sched, n_sched8, n_levels,
// max_width, n_delete_rows, n_applied_ds, has_pending, pending_depth,
// n_slots, n_segs.  Returns 0 or an error code (<0).
int ymx_prepare(void* h, const int64_t* buf_ids, const int64_t* v2_flags,
                int64_t n_updates, int want_levels, int64_t* out_counts) {
  Mirror* m = static_cast<Mirror*>(h);
  int rc = m->prepare(buf_ids, v2_flags, n_updates, want_levels != 0);
  if (rc != 0) return rc;
  int64_t depth = (int64_t)m->pending_ds.size();
  for (auto& [c, q] : m->pending) depth += (int64_t)q.size();
  out_counts[0] = m->plan.n_rows;
  out_counts[1] = (int64_t)m->plan.splits.size();
  out_counts[2] = (int64_t)m->plan.sched.size();
  out_counts[3] = (int64_t)m->plan.sched8.size();
  out_counts[4] = m->plan.n_levels;
  out_counts[5] = m->plan.max_width;
  out_counts[6] = (int64_t)m->plan.delete_rows.size();
  out_counts[7] = (int64_t)m->plan.applied_ds.size();
  out_counts[8] = (m->pending.empty() && m->pending_ds.empty()) ? 0 : 1;
  out_counts[9] = depth;
  out_counts[10] = (int64_t)m->client_of_slot.size();
  out_counts[11] = m->n_segs();
  out_counts[12] = (int64_t)m->plan.link_rows.size();
  out_counts[13] = (int64_t)m->plan.head_segs.size();
  return 0;
}

// planner worker-pool width: YTPU_PLAN_THREADS wins, else the hardware
// concurrency of the host (1 on this build image — the pool then takes
// the serial path with zero thread overhead; real multi-core hosts fan
// the per-doc plans out)
static int plan_pool_width() {
  const char* e = std::getenv("YTPU_PLAN_THREADS");
  if (e && *e) {
    int v = std::atoi(e);
    return v > 0 ? v : 1;
  }
  unsigned hc = std::thread::hardware_concurrency();
  return hc > 0 ? (int)hc : 1;
}

int ymx_plan_threads() { return plan_pool_width(); }

// YTPU_PLAN_SEGMENT gate for the emit_row chain-run anchor adoption —
// Python sets it from the env knob so the A/B `off` lane disables every
// segment-planning shortcut, host and native alike
void ymx_set_plan_segment(int on) { g_plan_segment.store(on != 0); }

// cumulative [fast adoptions, fragment-search lookups] across every
// prepare in the process; callers diff around a flush
void ymx_plan_segment_stats(int64_t* out) {
  out[0] = g_seg_fast.load(std::memory_order_relaxed);
  out[1] = g_seg_lookup.load(std::memory_order_relaxed);
}

// batched twin of ymx_prepare: one call plans EVERY staged doc, writing a
// 16-wide counts row per doc ([0..13] = ymx_prepare's layout, [14] =
// dense-link flag: link_rows == [0..n_rows)) and a per-doc rc.  Kills the
// per-doc Python/ctypes round trip that dominated distinct-doc flushes.
// Per-doc plans are independent (each touches only its own Mirror; the
// only shared data are the const update bytes), so the loop fans out over
// a worker pool on multi-core hosts — results are bit-identical at any
// width because no doc reads another doc's state.  Callers must not pass
// the same handle twice in one call.
void ymx_prepare_many(void** hs, int64_t n_docs, const int64_t* buf_ofs,
                      const int64_t* ids_flat, const int64_t* v2_flat,
                      int want_levels, int want_sched, int64_t* out_counts,
                      int64_t* out_rc) {
  auto plan_one = [&](int64_t i) {
    Mirror* m = static_cast<Mirror*>(hs[i]);
    int64_t lo = buf_ofs[i], hi = buf_ofs[i + 1];
    int rc = m->prepare(ids_flat + lo, v2_flat + lo, hi - lo,
                        want_levels != 0, want_sched != 0);
    out_rc[i] = rc;
    int64_t* c = out_counts + i * 16;
    if (rc != 0) {
      for (int j = 0; j < 16; j++) c[j] = 0;
      return;
    }
    int64_t depth = (int64_t)m->pending_ds.size();
    for (auto& [cl, q] : m->pending) depth += (int64_t)q.size();
    c[0] = m->plan.n_rows;
    c[1] = (int64_t)m->plan.splits.size();
    c[2] = (int64_t)m->plan.sched.size();
    c[3] = (int64_t)m->plan.sched8.size();
    c[4] = m->plan.n_levels;
    c[5] = m->plan.max_width;
    c[6] = (int64_t)m->plan.delete_rows.size();
    c[7] = (int64_t)m->plan.applied_ds.size();
    c[8] = (m->pending.empty() && m->pending_ds.empty()) ? 0 : 1;
    c[9] = depth;
    c[10] = (int64_t)m->client_of_slot.size();
    c[11] = m->n_segs();
    c[12] = (int64_t)m->plan.link_rows.size();
    c[13] = (int64_t)m->plan.head_segs.size();
    int64_t k = c[12];
    c[14] = (k > 0 && k == m->plan.n_rows &&
             m->plan.link_rows.back() == k - 1)
                ? 1
                : 0;
    c[15] = 0;
  };
  int nt = plan_pool_width();
  if (nt > (int)n_docs) nt = (int)n_docs;
  if (nt <= 1) {
    for (int64_t i = 0; i < n_docs; i++) plan_one(i);
    return;
  }
  std::atomic<int64_t> next{0};
  std::vector<std::thread> pool;
  pool.reserve((size_t)nt);
  for (int t = 0; t < nt; t++)
    pool.emplace_back([&] {
      for (int64_t i; (i = next.fetch_add(1, std::memory_order_relaxed)) <
                      n_docs;)
        plan_one(i);
    });
  for (auto& th : pool) th.join();
}

// deep state clone: dst becomes a bit-identical twin of src — same rows,
// segments, pending queues, delete sets, AND the same last-prepare plan,
// so pack_apply / plan readback / encode work on the clone unchanged.
// Owned arena blocks are deep-copied and every bufs pointer into a
// src-owned block is remapped to the dst copy; borrowed pointers (the
// Python-pinned update bytes) are shared, so the caller must keep those
// buffers alive for the clone's lifetime (the plan cache pins them).
// Returns an approximate host byte size of the cloned state (cache
// accounting); dst's previous state is discarded.
int64_t ymx_clone_state(void* dst_h, void* src_h) {
  Mirror* d = static_cast<Mirror*>(dst_h);
  const Mirror* s = static_cast<const Mirror*>(src_h);
  if (d == s) return 0;

  d->client_of_slot = s->client_of_slot;
  d->slot_of_client = s->slot_of_client;
  d->frag_clock = s->frag_clock;
  d->frag_row = s->frag_row;
  d->frag_hint = s->frag_hint;
  d->state = s->state;

  d->r_slot = s->r_slot;
  d->r_clock = s->r_clock;
  d->r_len = s->r_len;
  d->r_oslot = s->r_oslot;
  d->r_oclock = s->r_oclock;
  d->r_rslot = s->r_rslot;
  d->r_rclock = s->r_rclock;
  d->r_ref = s->r_ref;
  d->r_seg = s->r_seg;
  d->r_is_gc = s->r_is_gc;
  d->r_countable = s->r_countable;
  d->r_c = s->r_c;
  d->r_host_deleted = s->r_host_deleted;
  d->r_lww_deleted = s->r_lww_deleted;

  d->seg_lookup = s->seg_lookup;
  d->seg_name_id = s->seg_name_id;
  d->seg_sub_id = s->seg_sub_id;
  d->seg_parent = s->seg_parent;
  d->segs_of_parent = s->segs_of_parent;
  d->rows_of_seg = s->rows_of_seg;
  d->map_chain = s->map_chain;
  d->list_next = s->list_next;
  d->head_of_seg = s->head_of_seg;

  d->strings = s->strings;
  d->interned = s->interned;
  d->intern_ofs = s->intern_ofs;
  d->intern_len = s->intern_len;

  d->ds = s->ds;
  d->ds_slot_order = s->ds_slot_order;
  d->pending = s->pending;
  d->pending_ds = s->pending_ds;

  d->plan = s->plan;
  d->gen = s->gen;
  d->dl_mark = s->dl_mark;
  d->dh_mark = s->dh_mark;
  d->tm_mark = s->tm_mark;
  d->dirty_epoch = s->dirty_epoch;
  d->walk_mark = s->walk_mark;
  d->walk_order = s->walk_order;
  d->walk_id = s->walk_id;
  d->cur_chunk = s->cur_chunk;
  d->chunk_used = s->chunk_used;
  for (int i = 0; i < Mirror::kSlotCache; i++) {
    d->slot_cache_cl[i] = s->slot_cache_cl[i];
    d->slot_cache_v[i] = s->slot_cache_v[i];
  }
  d->slot_cache_pos = s->slot_cache_pos;
  d->radix_tmp.clear();  // pure scratch: never read before resize

  // owned arena blocks: deep copy, then remap the bufs pointers that
  // point INTO a src block (arena/arena2 hand out interior pointers for
  // bump-allocated fragments) onto the dst copy at the same offset
  d->owned.clear();
  d->owned.reserve(s->owned.size());
  struct Range {
    const uint8_t* lo;
    const uint8_t* hi;
    size_t idx;
  };
  std::vector<Range> ranges;
  ranges.reserve(s->owned.size());
  int64_t owned_bytes = 0;
  for (size_t i = 0; i < s->owned.size(); i++) {
    const auto& blk = *s->owned[i];
    d->owned.push_back(std::make_unique<std::vector<uint8_t>>(blk));
    owned_bytes += (int64_t)blk.size();
    if (!blk.empty())
      ranges.push_back({blk.data(), blk.data() + blk.size(), i});
  }
  std::sort(ranges.begin(), ranges.end(),
            [](const Range& a, const Range& b) { return a.lo < b.lo; });
  d->bufs = s->bufs;
  for (auto& [p, n] : d->bufs) {
    if (p == nullptr || ranges.empty()) continue;
    // rightmost block starting at or before p (blocks never overlap)
    auto it = std::upper_bound(
        ranges.begin(), ranges.end(), p,
        [](const uint8_t* q, const Range& r) { return q < r.lo; });
    if (it == ranges.begin()) continue;
    --it;
    if (p >= it->lo && p < it->hi)
      p = d->owned[it->idx]->data() + (p - it->lo);
  }

  // approximate host footprint (cache eviction accounting): the int64
  // row/fragment columns dominate real mirrors
  int64_t bytes = owned_bytes + (int64_t)s->strings.size();
  bytes += (int64_t)(s->r_slot.size() *
                     (sizeof(int64_t) * 9 + sizeof(ContentDesc) + 4));
  bytes += (int64_t)(s->list_next.size() * sizeof(int64_t));
  for (const auto& fc : s->frag_clock)
    bytes += (int64_t)(fc.size() * 2 * sizeof(int64_t));
  bytes += (int64_t)((s->plan.link_rows.size() + s->plan.link_vals.size() +
                      s->plan.sched.size() * 4 + s->plan.sched8.size() * 8 +
                      s->plan.levels.size() + s->plan.delete_rows.size()) *
                     sizeof(int64_t));
  for (const auto& [cl, q] : s->pending)
    bytes += (int64_t)(q.size() * sizeof(PendRef));
  return bytes;
}

// native twin of BatchEngine._flush_apply's pack loop: bins every doc's
// plan into the per-shard scatter-lane layout
//   [4*b_loc counts | k_dn dense vals | k_sp sparse rows | k_sp sparse
//    vals | k_h head segs | k_h head vals | k_d delete rows]
// writing pads (null/oob) for the unused tail of each section.  stats =
// {n_dense, n_sparse, n_heads, n_dels} real lane elements.
}  // extern "C"

template <typename T>
static void pack_apply_t(void** hs, const int64_t* doc_ids, int64_t n_plans,
                         int64_t b_loc, int64_t n_shards, int64_t k_dn,
                         int64_t k_sp, int64_t k_h, int64_t k_d, T oob_r,
                         T oob_s, T null_val, T* lanes, int64_t* stats) {
  int64_t lane_w = 4 * b_loc + k_dn + 2 * k_sp + 2 * k_h + k_d;
  std::vector<int64_t> cur_dn(n_shards, 0), cur_sp(n_shards, 0),
      cur_h(n_shards, 0), cur_d(n_shards, 0);
  for (int64_t s = 0; s < n_shards; s++)
    std::memset(lanes + s * lane_w, 0, (size_t)(4 * b_loc) * sizeof(T));
  for (int64_t pi = 0; pi < n_plans; pi++) {
    Mirror* m = static_cast<Mirror*>(hs[pi]);
    Plan& p = m->plan;
    int64_t i = doc_ids[pi];
    int64_t s = i / b_loc, li = i % b_loc;
    T* counts = lanes + s * lane_w;
    T* dn = counts + 4 * b_loc;
    T* sp_r = dn + k_dn;
    T* sp_v = sp_r + k_sp;
    T* hd_s = sp_v + k_sp;
    T* hd_v = hd_s + k_h;
    T* dl_r = hd_v + k_h;
    int64_t k = (int64_t)p.link_rows.size();
    bool dense = k > 0 && k == p.n_rows && p.link_rows.back() == k - 1;
    if (dense) {
      counts[0 * b_loc + li] = (T)k;
      int64_t o = cur_dn[s];
      for (int64_t j = 0; j < k; j++)
        dn[o + j] = (T)p.link_vals[(size_t)j];
      cur_dn[s] = o + k;
    } else if (k) {
      counts[1 * b_loc + li] = (T)k;
      int64_t o = cur_sp[s];
      for (int64_t j = 0; j < k; j++) {
        sp_r[o + j] = (T)p.link_rows[(size_t)j];
        sp_v[o + j] = (T)p.link_vals[(size_t)j];
      }
      cur_sp[s] = o + k;
    }
    int64_t hn = (int64_t)p.head_segs.size();
    if (hn) {
      counts[2 * b_loc + li] = (T)hn;
      int64_t o = cur_h[s];
      for (int64_t j = 0; j < hn; j++) {
        hd_s[o + j] = (T)p.head_segs[(size_t)j];
        hd_v[o + j] = (T)p.head_vals[(size_t)j];
      }
      cur_h[s] = o + hn;
    }
    int64_t dnn = (int64_t)p.delete_rows.size();
    if (dnn) {
      counts[3 * b_loc + li] = (T)dnn;
      int64_t o = cur_d[s];
      for (int64_t j = 0; j < dnn; j++)
        dl_r[o + j] = (T)p.delete_rows[(size_t)j];
      cur_d[s] = o + dnn;
    }
  }
  stats[0] = stats[1] = stats[2] = stats[3] = 0;
  for (int64_t s = 0; s < n_shards; s++) {
    T* dn = lanes + s * lane_w + 4 * b_loc;
    T* sp_r = dn + k_dn;
    T* sp_v = sp_r + k_sp;
    T* hd_s = sp_v + k_sp;
    T* hd_v = hd_s + k_h;
    T* dl_r = hd_v + k_h;
    stats[0] += cur_dn[s];
    stats[1] += cur_sp[s];
    stats[2] += cur_h[s];
    stats[3] += cur_d[s];
    for (int64_t j = cur_dn[s]; j < k_dn; j++) dn[j] = null_val;
    for (int64_t j = cur_sp[s]; j < k_sp; j++) {
      sp_r[j] = oob_r;
      sp_v[j] = null_val;
    }
    for (int64_t j = cur_h[s]; j < k_h; j++) {
      hd_s[j] = oob_s;
      hd_v[j] = null_val;
    }
    for (int64_t j = cur_d[s]; j < k_d; j++) dl_r[j] = oob_r;
  }
}

extern "C" {

void ymx_pack_apply(void** hs, const int64_t* doc_ids, int64_t n_plans,
                    int64_t b_loc, int64_t n_shards, int64_t k_dn,
                    int64_t k_sp, int64_t k_h, int64_t k_d, int32_t oob_r,
                    int32_t oob_s, int32_t null_val, int32_t* lanes,
                    int64_t* stats) {
  pack_apply_t<int32_t>(hs, doc_ids, n_plans, b_loc, n_shards, k_dn, k_sp,
                        k_h, k_d, oob_r, oob_s, null_val, lanes, stats);
}

// int16 twin: engines whose row/seg capacity fits 16 bits ship half the
// flush bytes (the tunnel/PCIe link is the distinct-flush bottleneck)
void ymx_pack_apply16(void** hs, const int64_t* doc_ids, int64_t n_plans,
                      int64_t b_loc, int64_t n_shards, int64_t k_dn,
                      int64_t k_sp, int64_t k_h, int64_t k_d, int32_t oob_r,
                      int32_t oob_s, int32_t null_val, int16_t* lanes,
                      int64_t* stats) {
  pack_apply_t<int16_t>(hs, doc_ids, n_plans, b_loc, n_shards, k_dn, k_sp,
                        k_h, k_d, (int16_t)oob_r, (int16_t)oob_s,
                        (int16_t)null_val, lanes, stats);
}

void ymx_plan_links(void* h, int64_t* rows, int64_t* vals) {
  Mirror* m = static_cast<Mirror*>(h);
  std::memcpy(rows, m->plan.link_rows.data(),
              m->plan.link_rows.size() * sizeof(int64_t));
  std::memcpy(vals, m->plan.link_vals.data(),
              m->plan.link_vals.size() * sizeof(int64_t));
}

void ymx_plan_heads(void* h, int64_t* segs, int64_t* vals) {
  Mirror* m = static_cast<Mirror*>(h);
  std::memcpy(segs, m->plan.head_segs.data(),
              m->plan.head_segs.size() * sizeof(int64_t));
  std::memcpy(vals, m->plan.head_vals.data(),
              m->plan.head_vals.size() * sizeof(int64_t));
}

void ymx_plan_splits(void* h, int64_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  for (auto& s : m->plan.splits) { *out++ = s[0]; *out++ = s[1]; }
}

void ymx_plan_sched(void* h, int64_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  for (auto& s : m->plan.sched)
    for (int i = 0; i < 4; i++) *out++ = s[i];
}

void ymx_plan_sched8(void* h, int64_t* out8, int64_t* out_lv) {
  Mirror* m = static_cast<Mirror*>(h);
  for (auto& s : m->plan.sched8)
    for (int i = 0; i < 8; i++) *out8++ = s[i];
  for (int64_t lv : m->plan.levels) *out_lv++ = lv;
}

void ymx_plan_deletes(void* h, int64_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  for (int64_t r : m->plan.delete_rows) *out++ = r;
}

void ymx_plan_applied_ds(void* h, int64_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  for (auto& d : m->plan.applied_ds) { *out++ = d[0]; *out++ = d[1]; *out++ = d[2]; }
}

int64_t ymx_n_rows(void* h) { return static_cast<Mirror*>(h)->n_rows(); }
int64_t ymx_n_slots(void* h) {
  return (int64_t)static_cast<Mirror*>(h)->client_of_slot.size();
}
int64_t ymx_n_segs(void* h) { return static_cast<Mirror*>(h)->n_segs(); }
uint64_t ymx_gen(void* h) { return static_cast<Mirror*>(h)->gen; }

// bulk row columns [start:] — 19 parallel int64 arrays
void ymx_rows(void* h, int64_t start,
              int64_t* slot, int64_t* clock, int64_t* len,
              int64_t* oslot, int64_t* oclock, int64_t* rslot,
              int64_t* rclock, int64_t* is_gc, int64_t* countable,
              int64_t* ref, int64_t* seg, int64_t* src_kind,
              int64_t* src_buf, int64_t* src_ofs, int64_t* src_end,
              int64_t* src_ofs2, int64_t* src_end2, int64_t* src_count,
              int64_t* src_v2, int64_t* host_deleted, int64_t* lww_deleted) {
  Mirror* m = static_cast<Mirror*>(h);
  int64_t n = m->n_rows();
  for (int64_t r = start; r < n; r++) {
    int64_t i = r - start;
    slot[i] = m->r_slot[r]; clock[i] = m->r_clock[r]; len[i] = m->r_len[r];
    oslot[i] = m->r_oslot[r]; oclock[i] = m->r_oclock[r];
    rslot[i] = m->r_rslot[r]; rclock[i] = m->r_rclock[r];
    is_gc[i] = m->r_is_gc[r]; countable[i] = m->r_countable[r];
    ref[i] = m->r_ref[r]; seg[i] = m->r_seg[r];
    const ContentDesc& c = m->r_c[(size_t)r];
    src_kind[i] = c.kind; src_buf[i] = c.buf;
    src_ofs[i] = c.ofs; src_end[i] = c.end;
    src_ofs2[i] = c.ofs2; src_end2[i] = c.end2;
    src_count[i] = c.count; src_v2[i] = c.v2;
    host_deleted[i] = m->r_host_deleted[r];
    lww_deleted[i] = m->r_lww_deleted[r];
  }
}

// device static columns for rows [start:] (engine _upload_statics shapes)
void ymx_static_cols(void* h, int64_t start, uint32_t* client_key,
                     int32_t* oslot, int32_t* oclock, int32_t* rslot,
                     int32_t* rclock, int32_t* origin_row) {
  Mirror* m = static_cast<Mirror*>(h);
  int64_t n = m->n_rows();
  for (int64_t r = start; r < n; r++) {
    int64_t i = r - start;
    client_key[i] = (uint32_t)m->client_of_slot[(size_t)m->r_slot[r]];
    oslot[i] = (int32_t)m->r_oslot[r];
    oclock[i] = (int32_t)m->r_oclock[r];
    rslot[i] = (int32_t)m->r_rslot[r];
    rclock[i] = (int32_t)m->r_rclock[r];
    if (m->r_oslot[r] == kNull) {
      origin_row[i] = (int32_t)kNull;
    } else {
      int64_t fi = m->frag_containing(m->r_oslot[r], m->r_oclock[r]);
      origin_row[i] =
          (int32_t)(fi == kNull ? kNull
                                : m->frag_row[(size_t)m->r_oslot[r]][(size_t)fi]);
    }
  }
}

void ymx_clients(void* h, int64_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  for (int64_t c : m->client_of_slot) *out++ = c;
}

void ymx_state(void* h, int64_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  for (int64_t s : m->state) *out++ = s;
}

void ymx_segs(void* h, int64_t* name_ofs, int64_t* name_len,
              int64_t* sub_ofs, int64_t* sub_len, int64_t* parent_row) {
  Mirror* m = static_cast<Mirror*>(h);
  for (int64_t s = 0; s < m->n_segs(); s++) {
    int64_t ni = m->seg_name_id[s], si = m->seg_sub_id[s];
    name_ofs[s] = ni == kNull ? kNull : m->intern_ofs[(size_t)ni];
    name_len[s] = ni == kNull ? 0 : m->intern_len[(size_t)ni];
    sub_ofs[s] = si == kNull ? kNull : m->intern_ofs[(size_t)si];
    sub_len[s] = si == kNull ? 0 : m->intern_len[(size_t)si];
    parent_row[s] = m->seg_parent[s];
  }
}

uint64_t ymx_strings_len(void* h) {
  return (uint64_t)static_cast<Mirror*>(h)->strings.size();
}
void ymx_strings(void* h, uint8_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  std::memcpy(out, m->strings.data(), m->strings.size());
}

int64_t ymx_chain_len(void* h, int64_t seg) {
  Mirror* m = static_cast<Mirror*>(h);
  auto it = m->map_chain.find(seg);
  return it == m->map_chain.end() ? 0 : (int64_t)it->second.size();
}
void ymx_chain(void* h, int64_t seg, int64_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  auto it = m->map_chain.find(seg);
  if (it == m->map_chain.end()) return;
  for (int64_t r : it->second) *out++ = r;
}

// raw DS ranges in slot first-note order: (slot, clock, len) triples
int64_t ymx_ds_count(void* h) {
  Mirror* m = static_cast<Mirror*>(h);
  int64_t n = 0;
  for (auto& v : m->ds) n += (int64_t)v.size();
  return n;
}
void ymx_ds(void* h, int64_t* slot, int64_t* clock, int64_t* len) {
  Mirror* m = static_cast<Mirror*>(h);
  for (int64_t s : m->ds_slot_order)
    for (auto& [c, l] : m->ds[(size_t)s]) {
      *slot++ = s; *clock++ = c; *len++ = l;
    }
}

// host list state (the device right_link/starts mirror)
void ymx_links(void* h, int64_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  std::memcpy(out, m->list_next.data(),
              m->list_next.size() * sizeof(int64_t));
}

void ymx_heads(void* h, int64_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  std::memcpy(out, m->head_of_seg.data(),
              m->head_of_seg.size() * sizeof(int64_t));
}

// fragment-index export: per-slot sizes, then one slot's (clock, row)
// pairs — lets the facade mirror the index with memcpys instead of a
// Python-side sort/rebuild
void ymx_frag_counts(void* h, int64_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  for (size_t s = 0; s < m->client_of_slot.size(); s++)
    out[s] = (int64_t)m->frag_clock[s].size();
}

void ymx_frag(void* h, int64_t slot, int64_t* clocks, int64_t* rows) {
  Mirror* m = static_cast<Mirror*>(h);
  auto& fc = m->frag_clock[(size_t)slot];
  auto& fr = m->frag_row[(size_t)slot];
  std::memcpy(clocks, fc.data(), fc.size() * sizeof(int64_t));
  std::memcpy(rows, fr.data(), fr.size() * sizeof(int64_t));
}

int64_t ymx_pending_depth(void* h) {
  Mirror* m = static_cast<Mirror*>(h);
  int64_t depth = (int64_t)m->pending_ds.size();
  for (auto& [c, q] : m->pending) depth += (int64_t)q.size();
  return depth;
}
int ymx_has_pending(void* h) {
  Mirror* m = static_cast<Mirror*>(h);
  return (m->pending.empty() && m->pending_ds.empty()) ? 0 : 1;
}

int64_t ymx_find_seg(void* h, const uint8_t* name, int64_t name_len,
                     const uint8_t* sub, int64_t sub_len, int64_t parent_row) {
  Mirror* m = static_cast<Mirror*>(h);
  auto find_id = [&](const uint8_t* p, int64_t n) -> int64_t {
    if (n < 0) return kNull;
    std::string key(reinterpret_cast<const char*>(p), (size_t)n);
    auto it = m->interned.find(key);
    return it == m->interned.end() ? -2 : it->second;  // -2: never interned
  };
  int64_t ni = find_id(name, name_len);
  int64_t si = find_id(sub, sub_len);
  if (ni == -2 || si == -2) return kNull;
  auto it = m->seg_lookup.find(std::make_tuple(ni, si, parent_row));
  return it == m->seg_lookup.end() ? kNull : it->second;
}

int64_t ymx_segs_of_parent(void* h, int64_t row, int64_t* out, int64_t cap) {
  Mirror* m = static_cast<Mirror*>(h);
  auto it = m->segs_of_parent.find(row);
  if (it == m->segs_of_parent.end()) return 0;
  int64_t n = 0;
  for (int64_t s : it->second) {
    if (n < cap) out[n] = s;
    n++;
  }
  return n;
}

// copy bytes out of a registered buffer (arena chunks included) so Python
// can realize synthesized content
int ymx_copy_bytes(void* h, int64_t buf, int64_t ofs, int64_t end,
                   uint8_t* out) {
  Mirror* m = static_cast<Mirror*>(h);
  if (buf < 0 || (size_t)buf >= m->bufs.size()) return -1;
  if (ofs < 0 || end < ofs || (uint64_t)end > m->buf_len(buf)) return -1;
  std::memcpy(out, m->buf_ptr(buf) + ofs, (size_t)(end - ofs));
  return 0;
}

// upper bound on any encode of this mirror (all rows + framing slack)
int64_t ymx_encode_bound(void* h) {
  Mirror* m = static_cast<Mirror*>(h);
  int64_t content = 0;
  for (auto& c : m->r_c)
    content += (c.end >= 0 && c.ofs >= 0) ? (c.end - c.ofs) : 16;
  int64_t n_ds = 0;
  for (auto& v : m->ds) n_ds += (int64_t)v.size();
  return 256 + m->n_rows() * 80 + content + (int64_t)m->strings.size() * 2 +
         24 * n_ds;
}

// encode the diff against a remote state vector, fully natively.
// sv: n_sv (client, clock) pairs.  ds_override!=0 replaces the derived
// DeleteSet with the given (client, clock, len) triples.  Returns bytes
// written, -7 = needs the Python spill path, other <0 = writer error.
int64_t ymx_encode_diff(void* h, const int64_t* sv_clients,
                        const int64_t* sv_clocks, int64_t n_sv,
                        const int64_t* ds_ranges, int64_t n_ds,
                        int ds_override, uint8_t* out, uint64_t cap) {
  return mirror_encode_diff(static_cast<Mirror*>(h), sv_clients, sv_clocks,
                            n_sv, ds_ranges, n_ds, ds_override, out, cap);
}

// V2 twin of ymx_encode_diff (byte-identical to the Python
// UpdateEncoderV2 output).  Same fallback contract: -7 -> Python writer.
int64_t ymx_encode_diff_v2(void* h, const int64_t* sv_clients,
                           const int64_t* sv_clocks, int64_t n_sv,
                           const int64_t* ds_ranges, int64_t n_ds,
                           int ds_override, uint8_t* out, uint64_t cap) {
  std::vector<uint8_t> bytes;
  int64_t rc = mirror_encode_diff_v2(static_cast<Mirror*>(h), sv_clients,
                                     sv_clocks, n_sv, ds_ranges, n_ds,
                                     ds_override, &bytes);
  if (rc < 0) return rc;
  if (bytes.size() > cap)  // needed size, negative-encoded (caller
    return -(int64_t)bytes.size();  // retries once with an exact buffer)
  std::memcpy(out, bytes.data(), bytes.size());
  return (int64_t)bytes.size();
}

int64_t ymx_compact(void* h, const int32_t* right_link,
                    const uint8_t* deleted, const int32_t* heads,
                    int64_t n_heads, int gc, int32_t* new_right,
                    uint8_t* new_deleted, int32_t* new_heads,
                    int64_t new_heads_cap) {
  return static_cast<Mirror*>(h)->compact(right_link, deleted, heads,
                                          n_heads, gc, new_right,
                                          new_deleted, new_heads,
                                          new_heads_cap);
}

// compaction from the mirror's OWN list/deleted state — the flush
// invariant keeps these equal to the device arrays, so no device
// read-back is needed to decide merges (the r3 readback-rebuild cycle
// was the 100k-doc scaling liability); the device gets the rebuilt
// arrays in one write-only scatter
int64_t ymx_compact_self(void* h, int gc, int32_t* new_right,
                         uint8_t* new_deleted, int32_t* new_heads,
                         int64_t new_heads_cap) {
  Mirror* m = static_cast<Mirror*>(h);
  int64_t n = m->n_rows();
  int64_t nseg = m->n_segs();
  std::vector<int32_t> right((size_t)std::max<int64_t>(1, n));
  std::vector<uint8_t> del((size_t)std::max<int64_t>(1, n));
  std::vector<int32_t> heads((size_t)std::max<int64_t>(1, nseg));
  for (int64_t i = 0; i < n; i++) {
    right[(size_t)i] = (int32_t)m->list_next[(size_t)i];
    del[(size_t)i] = m->r_host_deleted[(size_t)i];
  }
  for (int64_t s = 0; s < nseg; s++)
    heads[(size_t)s] = (int32_t)m->head_of_seg[(size_t)s];
  return m->compact(right.data(), del.data(), heads.data(), nseg, gc,
                    new_right, new_deleted, new_heads, new_heads_cap);
}

}  // extern "C"
