// Native columnar transcoder for the Yjs V1 and V2 wire formats.
//
// The host-side decode of update blobs (reference src/utils/encoding.js
// readClientsStructRefs, encoding.js:127-198, the V2 9-stream columnar
// container of UpdateDecoder.js:270-293, and the DS sections of
// DeleteSet.js:270-285) is the per-item hot loop of the marshaling pipeline
// feeding the TPU batch engine (SURVEY.md §7 phase 1: "the only candidate
// for a C++ component — varint/RLE transcode at 100k-doc scale").  This
// library scans an update once and emits fixed-width columns; variable
// payloads stay in the source buffer, referenced by byte ranges, and are
// decoded lazily by the Python side only when materialized.
//
// Two-pass C ABI: ytpu_count_v1/v2 sizes the outputs, ytpu_decode_v1/v2
// fills caller-allocated arrays.  All columns are int64 with -1 as the null
// sentinel.  Returns 0 on success, a negative error code otherwise.

#include "wire.h"

using namespace ytpu_wire;
extern "C" {

// Returns bytes written into `out`, or a negative error code.
int64_t ytpu_encode_v1(
    const uint8_t** bufs, const uint64_t* buf_lens, uint64_t n_bufs,
    // write groups: one per client, descending id order
    const int64_t* group_client, const int64_t* group_start,
    const int64_t* group_len, uint64_t n_groups,
    // per written row, flat in group order
    const int64_t* clock, const int64_t* length, const int64_t* offset,
    const int64_t* origin_client, const int64_t* origin_clock,
    const int64_t* right_client, const int64_t* right_clock,
    const int64_t* content_ref,
    const int64_t* name_ofs, const int64_t* name_len,
    const int64_t* sub_ofs, const int64_t* sub_len,
    const int64_t* parent_client, const int64_t* parent_clock,
    const int64_t* src_kind, const int64_t* src_buf,
    const int64_t* src_ofs, const int64_t* src_end,
    const uint8_t* strings, uint64_t strings_len,
    // delete-set groups (client order as given)
    const int64_t* ds_group_client, const int64_t* ds_group_start,
    const int64_t* ds_group_len, uint64_t n_ds_groups,
    const int64_t* ds_clock, const int64_t* ds_len,
    uint8_t* out, uint64_t out_cap) {
  Writer w{out, out_cap, 0, false};
  w.varuint(n_groups);
  for (uint64_t g = 0; g < n_groups && !w.fail; g++) {
    int64_t start = group_start[g], n = group_len[g];
    w.varuint((uint64_t)n);
    w.varuint((uint64_t)group_client[g]);
    w.varuint((uint64_t)(clock[start] + offset[start]));
    for (int64_t r = start; r < start + n && !w.fail; r++) {
      int64_t ofs = offset[r];
      int64_t ref = content_ref[r];
      if (src_kind[r] == kSrcNone) {  // GC struct (GC.js:45-48)
        w.u8(0);
        w.varuint((uint64_t)(length[r] - ofs));
        continue;
      }
      // resolve origin under the partial-first-struct rule
      int64_t oc = origin_client[r], ok = origin_clock[r];
      if (ofs > 0) { oc = group_client[g]; ok = clock[r] + ofs - 1; }
      bool has_o = oc >= 0, has_r = right_client[r] >= 0;
      bool has_sub = sub_ofs[r] >= 0;
      // BIT6 always reflects parentSub presence (Item.js:631); the parent
      // strings themselves are only written when neither neighbor id is
      // (canCopyParentInfo, Item.js:640-652)
      uint8_t info = (uint8_t)(ref & kBits5);
      if (has_o) info |= kBit8;
      if (has_r) info |= kBit7;
      if (has_sub) info |= kBit6;
      w.u8(info);
      if (has_o) { w.varuint((uint64_t)oc); w.varuint((uint64_t)ok); }
      if (has_r) {
        w.varuint((uint64_t)right_client[r]);
        w.varuint((uint64_t)right_clock[r]);
      }
      if (!has_o && !has_r) {
        if (name_ofs[r] >= 0) {
          w.varuint(1);  // parent_info: root-type key (Item.js:640-652)
          if ((uint64_t)(name_ofs[r] + name_len[r]) > strings_len) return -3;
          w.varuint((uint64_t)name_len[r]);
          w.bytes(strings + name_ofs[r], (uint64_t)name_len[r]);
        } else if (parent_client[r] >= 0) {
          w.varuint(0);  // parent is the nested type item's id (Item.js:644)
          w.varuint((uint64_t)parent_client[r]);
          w.varuint((uint64_t)parent_clock[r]);
        } else {
          return -3;
        }
        if (has_sub) {
          if ((uint64_t)(sub_ofs[r] + sub_len[r]) > strings_len) return -3;
          w.varuint((uint64_t)sub_len[r]);
          w.bytes(strings + sub_ofs[r], (uint64_t)sub_len[r]);
        }
      }
      switch (src_kind[r]) {
        case kSrcDeleted:
          w.varuint((uint64_t)(length[r] - ofs));
          break;
        case kSrcFramed: case kSrcSpill: case kSrcUtf8:
        case kSrcAnys: case kSrcJsons: {
          if (src_buf[r] < 0 || (uint64_t)src_buf[r] >= n_bufs) return -4;
          const uint8_t* sb = bufs[src_buf[r]];
          uint64_t sl = buf_lens[src_buf[r]];
          if (src_ofs[r] < 0 || src_end[r] < src_ofs[r] ||
              (uint64_t)src_end[r] > sl)
            return -4;
          if (src_kind[r] == kSrcUtf8) {
            write_cut_string(&w, sb + src_ofs[r],
                             (uint64_t)(src_end[r] - src_ofs[r]), ofs);
          } else if (src_kind[r] == kSrcAnys || src_kind[r] == kSrcJsons) {
            // `length` elements at [ofs,end): re-frame as varuint count +
            // element bytes, skipping the first `ofs` elements (the
            // partial-first-struct rule applied element-wise)
            w.varuint((uint64_t)(length[r] - ofs));
            Reader er{sb, (uint64_t)src_end[r], (uint64_t)src_ofs[r], false};
            for (int64_t i = 0; i < ofs && !er.fail; i++) {
              if (src_kind[r] == kSrcAnys) er.skip_any();
              else { uint64_t o, b; er.var_string(&o, &b); }
            }
            if (er.fail) return -4;
            w.bytes(sb + er.pos, (uint64_t)(src_end[r] - (int64_t)er.pos));
          } else {
            if (src_kind[r] == kSrcFramed && ofs != 0) return -5;
            w.bytes(sb + src_ofs[r], (uint64_t)(src_end[r] - src_ofs[r]));
          }
          break;
        }
        default:
          return -6;
      }
    }
  }
  // DS section (DeleteSet.js:219-232)
  w.varuint(n_ds_groups);
  for (uint64_t g = 0; g < n_ds_groups && !w.fail; g++) {
    w.varuint((uint64_t)ds_group_client[g]);
    int64_t start = ds_group_start[g], n = ds_group_len[g];
    w.varuint((uint64_t)n);
    for (int64_t i = start; i < start + n; i++) {
      w.varuint((uint64_t)ds_clock[i]);
      w.varuint((uint64_t)ds_len[i]);
    }
  }
  if (w.fail) return -2;
  return (int64_t)w.pos;
}

int ytpu_count_v1(const uint8_t* buf, uint64_t len,
                  uint64_t* n_structs, uint64_t* n_ds) {
  Reader r{buf, len, 0, false};
  *n_structs = parse_structs(&r, nullptr);
  if (r.fail) return -1;
  *n_ds = parse_ds(&r, nullptr, nullptr, nullptr);
  if (r.fail) return -2;
  if (r.pos != len) return -3;  // trailing garbage
  return 0;
}

int ytpu_decode_v1(const uint8_t* buf, uint64_t len,
                   int64_t* client, int64_t* clock, int64_t* length,
                   int64_t* origin_client, int64_t* origin_clock,
                   int64_t* right_client, int64_t* right_clock,
                   int64_t* info,
                   int64_t* parent_name_ofs, int64_t* parent_name_len,
                   int64_t* parent_id_client, int64_t* parent_id_clock,
                   int64_t* parent_sub_ofs, int64_t* parent_sub_len,
                   int64_t* content_ofs, int64_t* content_end,
                   int64_t* ds_client, int64_t* ds_clock, int64_t* ds_len) {
  Reader r{buf, len, 0, false};
  StructOut out{client, clock, length, origin_client, origin_clock,
                right_client, right_clock, info,
                parent_name_ofs, parent_name_len,
                parent_id_client, parent_id_clock,
                parent_sub_ofs, parent_sub_len,
                content_ofs, content_end};
  parse_structs(&r, &out);
  if (r.fail) return -1;
  parse_ds(&r, ds_client, ds_clock, ds_len);
  if (r.fail) return -2;
  return 0;
}

int ytpu_count_v2(const uint8_t* buf, uint64_t len,
                  uint64_t* n_structs, uint64_t* n_ds) {
  V2Streams v;
  if (!v.init(buf, len)) return -1;
  int err = 0;
  *n_structs = parse_structs_v2(&v, nullptr, &err);
  if (err != 0) return err;
  *n_ds = parse_ds_v2(&v.rest, nullptr, nullptr, nullptr);
  if (v.rest.fail) return -2;
  if (v.rest.pos != len) return -3;  // trailing garbage
  return 0;
}

int ytpu_decode_v2(const uint8_t* buf, uint64_t len,
                   int64_t* client, int64_t* clock, int64_t* length,
                   int64_t* origin_client, int64_t* origin_clock,
                   int64_t* right_client, int64_t* right_clock,
                   int64_t* info,
                   int64_t* parent_name_ofs, int64_t* parent_name_len,
                   int64_t* parent_id_client, int64_t* parent_id_clock,
                   int64_t* parent_sub_ofs, int64_t* parent_sub_len,
                   int64_t* content_ofs, int64_t* content_end,
                   int64_t* content_ofs2, int64_t* content_end2,
                   int64_t* content_count,
                   int64_t* ds_client, int64_t* ds_clock, int64_t* ds_len) {
  V2Streams v;
  if (!v.init(buf, len)) return -1;
  StructOut2 out{client, clock, length, origin_client, origin_clock,
                 right_client, right_clock, info,
                 parent_name_ofs, parent_name_len,
                 parent_id_client, parent_id_clock,
                 parent_sub_ofs, parent_sub_len,
                 content_ofs, content_end,
                 content_ofs2, content_end2, content_count};
  int err = 0;
  parse_structs_v2(&v, &out, &err);
  if (err != 0) return err;
  parse_ds_v2(&v.rest, ds_client, ds_clock, ds_len);
  if (v.rest.fail) return -2;
  return 0;
}

}  // extern "C"
