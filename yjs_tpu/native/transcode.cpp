// Native columnar transcoder for the Yjs V1 wire format.
//
// The host-side decode of update blobs (reference src/utils/encoding.js
// readClientsStructRefs, encoding.js:127-198, and the DS section of
// DeleteSet.js:270-285) is the per-item hot loop of the marshaling pipeline
// feeding the TPU batch engine (SURVEY.md §7 phase 1: "the only candidate
// for a C++ component — varint/RLE transcode at 100k-doc scale").  This
// library scans an update once and emits fixed-width columns; variable
// payloads stay in the source buffer, referenced by byte ranges, and are
// decoded lazily by the Python side only when materialized.
//
// Two-pass C ABI: ytpu_count_v1 sizes the outputs, ytpu_decode_v1 fills
// caller-allocated arrays.  All columns are int64 with -1 as the null
// sentinel.  Returns 0 on success, a negative error code otherwise.

#include <cstdint>
#include <cstddef>

namespace {

struct Reader {
  const uint8_t* buf;
  uint64_t len;
  uint64_t pos;
  bool fail;

  uint8_t u8() {
    if (pos >= len) { fail = true; return 0; }
    return buf[pos++];
  }

  // lib0 varuint (7 bits per byte, little-endian groups)
  uint64_t varuint() {
    uint64_t num = 0;
    int shift = 0;
    while (true) {
      if (pos >= len || shift > 63) { fail = true; return 0; }
      uint8_t r = buf[pos++];
      num |= (uint64_t)(r & 0x7f) << shift;
      shift += 7;
      if (r < 0x80) return num;
    }
  }

  // lib0 varint: first byte holds sign bit 0x40 and 6 bits of payload
  void varint() {
    if (pos >= len) { fail = true; return; }
    uint8_t r = buf[pos++];
    if (r < 0x80) return;
    int shift = 6;
    while (true) {
      if (pos >= len || shift > 63) { fail = true; return; }
      uint8_t c = buf[pos++];
      shift += 7;
      if (c < 0x80) return;
    }
  }

  void skip(uint64_t n) {
    if (n > len - pos) { fail = true; return; }  // overflow-safe bound check
    pos += n;
  }

  // var_string: varuint byte length + utf8; returns (ofs, bytelen)
  void var_string(uint64_t* ofs, uint64_t* blen) {
    uint64_t n = varuint();
    *ofs = pos;
    *blen = n;
    skip(n);
  }

  // UTF-16 code-unit count of a utf8 range (JS string .length semantics)
  uint64_t utf16_len(uint64_t ofs, uint64_t blen) const {
    uint64_t units = 0;
    for (uint64_t i = ofs; i < ofs + blen && i < len; ) {
      uint8_t b = buf[i];
      if (b < 0x80) { units += 1; i += 1; }
      else if (b < 0xE0) { units += 1; i += 2; }
      else if (b < 0xF0) { units += 1; i += 3; }
      else { units += 2; i += 4; }
    }
    return units;
  }

  // skip one lib0 "any" value
  void skip_any(int depth = 0) {
    if (depth > 64) { fail = true; return; }
    uint8_t tag = u8();
    if (fail) return;
    switch (tag) {
      case 127: case 126: case 121: case 120: break;  // undefined/null/bools
      case 125: varint(); break;
      case 124: skip(4); break;                        // float32
      case 123: skip(8); break;                        // float64
      case 122: skip(8); break;                        // bigint64
      case 119: { uint64_t o, b; var_string(&o, &b); break; }
      case 118: {                                      // object
        uint64_t n = varuint();
        for (uint64_t i = 0; i < n && !fail; i++) {
          uint64_t o, b; var_string(&o, &b);
          skip_any(depth + 1);
        }
        break;
      }
      case 117: {                                      // array
        uint64_t n = varuint();
        for (uint64_t i = 0; i < n && !fail; i++) skip_any(depth + 1);
        break;
      }
      case 116: { uint64_t n = varuint(); skip(n); break; }  // uint8array
      default: fail = true;
    }
  }
};

constexpr uint8_t kBit6 = 0x20, kBit7 = 0x40, kBit8 = 0x80, kBits5 = 0x1f;

struct StructOut {
  int64_t *client, *clock, *length;
  int64_t *origin_client, *origin_clock;
  int64_t *right_client, *right_clock;
  int64_t *info;
  int64_t *parent_name_ofs, *parent_name_len;
  int64_t *parent_id_client, *parent_id_clock;
  int64_t *parent_sub_ofs, *parent_sub_len;
  int64_t *content_ofs, *content_end;
};

// Parse the struct section.  When out == nullptr, only counts.
// Returns the number of structs, or sets r->fail.
uint64_t parse_structs(Reader* r, StructOut* out) {
  uint64_t idx = 0;
  uint64_t n_updates = r->varuint();
  for (uint64_t u = 0; u < n_updates && !r->fail; u++) {
    uint64_t n_structs = r->varuint();
    uint64_t client = r->varuint();
    uint64_t clock = r->varuint();
    for (uint64_t s = 0; s < n_structs && !r->fail; s++) {
      uint8_t info = r->u8();
      uint8_t ref = info & kBits5;
      int64_t oc = -1, ok = 0, rc = -1, rk = 0;
      int64_t pno = -1, pnl = -1, pic = -1, pik = -1, pso = -1, psl = -1;
      uint64_t length = 0, c_ofs = 0, c_end = 0;
      if (ref != 0) {
        if (info & kBit8) { oc = (int64_t)r->varuint(); ok = (int64_t)r->varuint(); }
        if (info & kBit7) { rc = (int64_t)r->varuint(); rk = (int64_t)r->varuint(); }
        if (!(info & (kBit7 | kBit8))) {
          if (r->varuint() == 1) {                       // parent is root name
            uint64_t o, b; r->var_string(&o, &b);
            pno = (int64_t)o; pnl = (int64_t)b;
          } else {                                       // parent is an id
            pic = (int64_t)r->varuint(); pik = (int64_t)r->varuint();
          }
          if (info & kBit6) {
            uint64_t o, b; r->var_string(&o, &b);
            pso = (int64_t)o; psl = (int64_t)b;
          }
        }
        c_ofs = r->pos;
        switch (ref) {
          case 1: length = r->varuint(); break;          // ContentDeleted
          case 2: {                                      // ContentJSON
            uint64_t n = r->varuint();
            for (uint64_t i = 0; i < n && !r->fail; i++) {
              uint64_t o, b; r->var_string(&o, &b);
            }
            length = n;
            break;
          }
          case 3: { uint64_t n = r->varuint(); r->skip(n); length = 1; break; }
          case 4: {                                      // ContentString
            uint64_t o, b; r->var_string(&o, &b);
            length = r->utf16_len(o, b);
            break;
          }
          case 5: {                                      // ContentEmbed (json string)
            uint64_t o, b; r->var_string(&o, &b);
            length = 1;
            break;
          }
          case 6: {                                      // ContentFormat
            uint64_t o, b;
            r->var_string(&o, &b);                       // key
            r->var_string(&o, &b);                       // json value
            length = 1;
            break;
          }
          case 7: {                                      // ContentType
            uint64_t tref = r->varuint();
            if (tref == 3 || tref == 5) {                // XmlElement / XmlHook
              uint64_t o, b; r->var_string(&o, &b);
            }
            length = 1;
            break;
          }
          case 8: {                                      // ContentAny
            uint64_t n = r->varuint();
            for (uint64_t i = 0; i < n && !r->fail; i++) r->skip_any();
            length = n;
            break;
          }
          case 9: {                                      // ContentDoc
            uint64_t o, b; r->var_string(&o, &b);        // guid
            r->skip_any();                               // opts
            length = 1;
            break;
          }
          default: r->fail = true;
        }
        c_end = r->pos;
      } else {
        length = r->varuint();                           // GC
      }
      if (r->fail) break;
      if (length == 0 && ref != 0) { r->fail = true; break; }
      if (out != nullptr) {
        out->client[idx] = (int64_t)client;
        out->clock[idx] = (int64_t)clock;
        out->length[idx] = (int64_t)length;
        out->origin_client[idx] = oc; out->origin_clock[idx] = ok;
        out->right_client[idx] = rc; out->right_clock[idx] = rk;
        out->info[idx] = info;
        out->parent_name_ofs[idx] = pno; out->parent_name_len[idx] = pnl;
        out->parent_id_client[idx] = pic; out->parent_id_clock[idx] = pik;
        out->parent_sub_ofs[idx] = pso; out->parent_sub_len[idx] = psl;
        out->content_ofs[idx] = (int64_t)c_ofs; out->content_end[idx] = (int64_t)c_end;
      }
      idx++;
      clock += length;
    }
  }
  return idx;
}

uint64_t parse_ds(Reader* r, int64_t* ds_client, int64_t* ds_clock, int64_t* ds_len) {
  uint64_t idx = 0;
  uint64_t n_clients = r->varuint();
  for (uint64_t c = 0; c < n_clients && !r->fail; c++) {
    uint64_t client = r->varuint();
    uint64_t n = r->varuint();
    for (uint64_t i = 0; i < n && !r->fail; i++) {
      uint64_t clock = r->varuint();
      uint64_t len = r->varuint();
      if (ds_client != nullptr) {
        ds_client[idx] = (int64_t)client;
        ds_clock[idx] = (int64_t)clock;
        ds_len[idx] = (int64_t)len;
      }
      idx++;
    }
  }
  return idx;
}

}  // namespace

extern "C" {

int ytpu_count_v1(const uint8_t* buf, uint64_t len,
                  uint64_t* n_structs, uint64_t* n_ds) {
  Reader r{buf, len, 0, false};
  *n_structs = parse_structs(&r, nullptr);
  if (r.fail) return -1;
  *n_ds = parse_ds(&r, nullptr, nullptr, nullptr);
  if (r.fail) return -2;
  if (r.pos != len) return -3;  // trailing garbage
  return 0;
}

int ytpu_decode_v1(const uint8_t* buf, uint64_t len,
                   int64_t* client, int64_t* clock, int64_t* length,
                   int64_t* origin_client, int64_t* origin_clock,
                   int64_t* right_client, int64_t* right_clock,
                   int64_t* info,
                   int64_t* parent_name_ofs, int64_t* parent_name_len,
                   int64_t* parent_id_client, int64_t* parent_id_clock,
                   int64_t* parent_sub_ofs, int64_t* parent_sub_len,
                   int64_t* content_ofs, int64_t* content_end,
                   int64_t* ds_client, int64_t* ds_clock, int64_t* ds_len) {
  Reader r{buf, len, 0, false};
  StructOut out{client, clock, length, origin_client, origin_clock,
                right_client, right_clock, info,
                parent_name_ofs, parent_name_len,
                parent_id_client, parent_id_clock,
                parent_sub_ofs, parent_sub_len,
                content_ofs, content_end};
  parse_structs(&r, &out);
  if (r.fail) return -1;
  parse_ds(&r, ds_client, ds_clock, ds_len);
  if (r.fail) return -2;
  return 0;
}

}  // extern "C"
