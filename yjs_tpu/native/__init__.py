"""ctypes loader for the native transcoder (transcode.cpp).

Builds lazily with g++ on first use (cached as _transcode.so next to the
source); unavailable when no toolchain exists or YTPU_NO_NATIVE is set —
callers fall back to the pure-Python codec.  Unavailability is logged ONCE
(a silent 10-50x host-path slowdown would otherwise be invisible,
r1-VERDICT "silent degradation"); set YTPU_NO_NATIVE to opt out quietly.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

logger = logging.getLogger("yjs_tpu.native")

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "transcode.cpp")
_SRC_PLAN = os.path.join(_DIR, "plancore.cpp")
_SRC_WIRE = os.path.join(_DIR, "wire.h")
_SO = os.path.join(_DIR, "_transcode.so")

_lib = None
_tried = False


def _build() -> bool:
    try:
        srcs = [_SRC] + ([_SRC_PLAN] if os.path.exists(_SRC_PLAN) else [])
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
             "-o", _SO] + srcs,
            check=True,
            capture_output=True,
            timeout=240,
        )
        return True
    except subprocess.CalledProcessError as e:
        logger.warning(
            "native transcoder failed to compile (pure-Python codec will "
            "serve the host path, 10-50x slower): %s",
            (e.stderr or b"").decode(errors="replace")[-500:],
        )
        return False
    except Exception as e:
        logger.warning(
            "native transcoder unavailable (%s: %s); pure-Python codec "
            "will serve the host path, 10-50x slower",
            type(e).__name__, e,
        )
        return False


def load():
    """The loaded library, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("YTPU_NO_NATIVE"):
        return None
    # a shipped .so with no source is fine (binary-only install); rebuild
    # only when a source file exists and is newer
    needs_build = not os.path.exists(_SO) or any(
        os.path.exists(s) and os.path.getmtime(_SO) < os.path.getmtime(s)
        for s in (_SRC, _SRC_PLAN, _SRC_WIRE)
    )
    if needs_build:
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        logger.warning(
            "native transcoder failed to load (%s); pure-Python codec "
            "will serve the host path, 10-50x slower", e,
        )
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.ytpu_count_v1.restype = ctypes.c_int
    lib.ytpu_count_v1.argtypes = [u8p, ctypes.c_uint64, u64p, u64p]
    lib.ytpu_decode_v1.restype = ctypes.c_int
    lib.ytpu_decode_v1.argtypes = [u8p, ctypes.c_uint64] + [i64p] * 19
    lib.ytpu_count_v2.restype = ctypes.c_int
    lib.ytpu_count_v2.argtypes = [u8p, ctypes.c_uint64, u64p, u64p]
    lib.ytpu_decode_v2.restype = ctypes.c_int
    lib.ytpu_decode_v2.argtypes = [u8p, ctypes.c_uint64] + [i64p] * 22
    lib.ytpu_encode_v1.restype = ctypes.c_int64
    lib.ytpu_encode_v1.argtypes = (
        [ctypes.POINTER(u8p), u64p, ctypes.c_uint64]      # bufs
        + [i64p] * 3 + [ctypes.c_uint64]                  # row groups
        + [i64p] * 18                                     # row columns
        + [u8p, ctypes.c_uint64]                          # strings blob
        + [i64p] * 3 + [ctypes.c_uint64] + [i64p] * 2     # ds groups
        + [u8p, ctypes.c_uint64]                          # out
    )
    # plan-core (plancore.cpp) entry points; absent in a stale binary-only
    # .so — the caller checks has_plancore()
    try:
        i64 = ctypes.c_int64
        u64 = ctypes.c_uint64
        vp = ctypes.c_void_p
        i32p = ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.ymx_new.restype = vp
        lib.ymx_free.argtypes = [vp]
        lib.ymx_add_buf.restype = i64
        lib.ymx_add_buf.argtypes = [vp, u8p, u64]
        lib.ymx_n_bufs.restype = i64
        lib.ymx_n_bufs.argtypes = [vp]
        lib.ymx_buf_len.restype = i64
        lib.ymx_buf_len.argtypes = [vp, i64]
        lib.ymx_prepare.restype = ctypes.c_int
        lib.ymx_prepare.argtypes = [vp, i64p, i64p, i64, ctypes.c_int, i64p]
        vpp = ctypes.POINTER(vp)
        lib.ymx_prepare_many.restype = None
        lib.ymx_prepare_many.argtypes = [vpp, i64, i64p, i64p, i64p,
                                         ctypes.c_int, ctypes.c_int, i64p,
                                         i64p]
        for pack_name in ("ymx_pack_apply", "ymx_pack_apply16"):
            fn = getattr(lib, pack_name)
            fn.restype = None
            fn.argtypes = [vpp, i64p, i64, i64, i64, i64, i64, i64, i64,
                           ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                           vp, i64p]
        for name, args in [
            ("ymx_plan_splits", [vp, i64p]),
            ("ymx_plan_sched", [vp, i64p]),
            ("ymx_plan_sched8", [vp, i64p, i64p]),
            ("ymx_plan_deletes", [vp, i64p]),
            ("ymx_plan_applied_ds", [vp, i64p]),
            ("ymx_plan_links", [vp, i64p, i64p]),
            ("ymx_links", [vp, i64p]),
            ("ymx_heads", [vp, i64p]),
            ("ymx_plan_heads", [vp, i64p, i64p]),
            ("ymx_clients", [vp, i64p]),
            ("ymx_state", [vp, i64p]),
            ("ymx_segs", [vp, i64p, i64p, i64p, i64p, i64p]),
            ("ymx_strings", [vp, u8p]),
            ("ymx_chain", [vp, i64, i64p]),
            ("ymx_ds", [vp, i64p, i64p, i64p]),
        ]:
            getattr(lib, name).restype = None
            getattr(lib, name).argtypes = args
        lib.ymx_frag_counts.restype = None
        lib.ymx_frag_counts.argtypes = [vp, i64p]
        lib.ymx_frag.restype = None
        lib.ymx_frag.argtypes = [vp, i64, i64p, i64p]
        lib.ymx_drop_bufs_from.restype = None
        lib.ymx_drop_bufs_from.argtypes = [vp, i64]
        for name in ("ymx_n_rows", "ymx_n_slots", "ymx_n_segs",
                     "ymx_pending_depth", "ymx_ds_count"):
            getattr(lib, name).restype = i64
            getattr(lib, name).argtypes = [vp]
        lib.ymx_gen.restype = u64
        lib.ymx_gen.argtypes = [vp]
        lib.ymx_strings_len.restype = u64
        lib.ymx_strings_len.argtypes = [vp]
        lib.ymx_chain_len.restype = i64
        lib.ymx_chain_len.argtypes = [vp, i64]
        lib.ymx_has_pending.restype = ctypes.c_int
        lib.ymx_has_pending.argtypes = [vp]
        lib.ymx_rows.restype = None
        lib.ymx_rows.argtypes = [vp, i64] + [i64p] * 21
        lib.ymx_static_cols.restype = None
        lib.ymx_static_cols.argtypes = [vp, i64, u32p] + [i32p] * 5
        lib.ymx_copy_bytes.restype = ctypes.c_int
        lib.ymx_copy_bytes.argtypes = [vp, i64, i64, i64, u8p]
        lib.ymx_encode_bound.restype = i64
        lib.ymx_encode_bound.argtypes = [vp]
        lib.ymx_encode_diff.restype = i64
        lib.ymx_encode_diff.argtypes = [vp, i64p, i64p, i64, i64p, i64,
                                        ctypes.c_int, u8p, u64]
        lib.ymx_encode_diff_v2.restype = i64
        lib.ymx_encode_diff_v2.argtypes = [vp, i64p, i64p, i64, i64p, i64,
                                           ctypes.c_int, u8p, u64]
        lib.ymx_compact.restype = i64
        lib.ymx_compact.argtypes = [vp, i32p, u8p, i32p, i64, ctypes.c_int,
                                    i32p, u8p, i32p, i64]
        lib._has_plancore = True
    except AttributeError:
        lib._has_plancore = False
    # per-feature probes: symbols added after r3 degrade gracefully on a
    # stale binary-only .so instead of disabling the whole planner
    try:
        p32 = ctypes.POINTER(ctypes.c_int32)
        pu8 = ctypes.POINTER(ctypes.c_uint8)
        lib.ymx_compact_self.restype = ctypes.c_int64
        lib.ymx_compact_self.argtypes = [
            ctypes.c_void_p, ctypes.c_int, p32, pu8, p32, ctypes.c_int64,
        ]
        lib._has_compact_self = True
    except AttributeError:
        lib._has_compact_self = False
    try:
        # r5 diagnostic: ymx_prepare_many's worker-pool width (surfaced as
        # last_flush_metrics["plan_threads"])
        lib.ymx_plan_threads.restype = ctypes.c_int
        lib.ymx_plan_threads.argtypes = []
        lib._has_plan_threads = True
    except AttributeError:
        lib._has_plan_threads = False
    try:
        # r5: one ctypes crossing registers every staged buffer of a flush
        lib.ymx_add_bufs_many.restype = None
        lib.ymx_add_bufs_many.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib._has_add_bufs_many = True
    except AttributeError:
        lib._has_add_bufs_many = False
    try:
        # r9: deep state clone — the frontier-keyed plan cache replays a
        # cached post-prepare mirror state onto another doc's handle
        lib.ymx_clone_state.restype = ctypes.c_int64
        lib.ymx_clone_state.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib._has_clone_state = True
    except AttributeError:
        lib._has_clone_state = False
    try:
        # r15: emit_row chain-run anchor adoption — Python mirrors the
        # YTPU_PLAN_SEGMENT knob into the lib and diffs the hit/lookup
        # totals around each flush for the shared metrics schema
        lib.ymx_set_plan_segment.restype = None
        lib.ymx_set_plan_segment.argtypes = [ctypes.c_int]
        lib.ymx_plan_segment_stats.restype = None
        lib.ymx_plan_segment_stats.argtypes = [
            ctypes.POINTER(ctypes.c_int64)
        ]
        lib._has_plan_segment = True
    except AttributeError:
        lib._has_plan_segment = False
    _lib = lib
    return _lib


def has_plancore() -> bool:
    lib = load()
    return bool(lib is not None and getattr(lib, "_has_plancore", False))


# content-source kinds for ytpu_encode_v1 (must match transcode.cpp)
SRC_NONE, SRC_DELETED, SRC_FRAMED, SRC_UTF8, SRC_SPILL = 0, 1, 2, 3, 4
# element-range kinds emitted by the native plan builder (plancore.cpp):
# `length` elements at [ofs,end) — ContentAny any-values / ContentJSON
# var_strings; SRC_V2LAZY marks V2-framed embed/format/type payloads that
# must be re-framed via the Python spill path when writing V1
SRC_ANYS, SRC_JSONS, SRC_V2LAZY = 5, 6, 7


def encode_v1_update(
    bufs: list[bytes],
    group_client, group_start, group_len,
    row_cols: dict,
    strings: bytes,
    ds_group_client, ds_group_start, ds_group_len,
    ds_clock, ds_len,
    out_cap: int,
) -> bytes:
    """Assemble a V1 update natively from pre-marshalled columns.  All
    array arguments are int64 numpy arrays; ``row_cols`` holds the 18
    per-row columns in ABI order.  Raises NativeDecodeError when the
    library is unavailable or encoding fails (caller falls back to the
    Python encoder)."""
    lib = load()
    if lib is None:
        raise NativeDecodeError("native transcoder unavailable")
    u8p = ctypes.POINTER(ctypes.c_uint8)
    n_bufs = len(bufs)
    buf_arrs = [np.frombuffer(b, dtype=np.uint8) for b in bufs]
    buf_ptrs = (u8p * max(1, n_bufs))(
        *(a.ctypes.data_as(u8p) for a in buf_arrs)
    )
    buf_lens = np.asarray([len(b) for b in bufs], np.uint64)
    strings_a = np.frombuffer(strings, dtype=np.uint8) if strings else np.zeros(1, np.uint8)
    out = np.empty(out_cap, np.uint8)
    row_order = (
        "clock", "length", "offset",
        "origin_client", "origin_clock", "right_client", "right_clock",
        "content_ref", "name_ofs", "name_len", "sub_ofs", "sub_len",
        "parent_client", "parent_clock",
        "src_kind", "src_buf", "src_ofs", "src_end",
    )
    # materialize every array first: the ctypes pointers do not keep their
    # backing buffers alive
    keep = (
        [np.ascontiguousarray(a, np.int64)
         for a in (group_client, group_start, group_len)]
        + [np.ascontiguousarray(row_cols[k], np.int64) for k in row_order]
        + [np.ascontiguousarray(a, np.int64)
           for a in (ds_group_client, ds_group_start, ds_group_len,
                     ds_clock, ds_len)]
    )
    i64ptr = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    rc = lib.ytpu_encode_v1(
        buf_ptrs,
        buf_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n_bufs,
        i64ptr(keep[0]), i64ptr(keep[1]), i64ptr(keep[2]),
        len(keep[0]),
        *(i64ptr(a) for a in keep[3:21]),
        strings_a.ctypes.data_as(u8p), len(strings),
        i64ptr(keep[21]), i64ptr(keep[22]), i64ptr(keep[23]),
        len(keep[21]),
        i64ptr(keep[24]), i64ptr(keep[25]),
        out.ctypes.data_as(u8p), out_cap,
    )
    if rc < 0:
        raise NativeDecodeError(f"native encode failed: {rc}")
    return out[:rc].tobytes()


class NativeDecodeError(Exception):
    pass


def decode_v1_columns(update: bytes):
    """Decode a V1 update into int64 column arrays via the native scanner.

    Returns (structs: dict[str, np.ndarray], ds: dict[str, np.ndarray]).
    Raises NativeDecodeError if the library is unavailable or parsing fails
    (caller falls back to the Python decoder).
    """
    lib = load()
    if lib is None:
        raise NativeDecodeError("native transcoder unavailable")
    buf = np.frombuffer(update, dtype=np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    n_structs = ctypes.c_uint64()
    n_ds = ctypes.c_uint64()
    rc = lib.ytpu_count_v1(bp, len(update), ctypes.byref(n_structs), ctypes.byref(n_ds))
    if rc != 0:
        raise NativeDecodeError(f"count pass failed: {rc}")
    ns, nd = n_structs.value, n_ds.value
    cols = {
        k: np.empty(ns, np.int64)
        for k in (
            "client", "clock", "length",
            "origin_client", "origin_clock", "right_client", "right_clock",
            "info", "parent_name_ofs", "parent_name_len",
            "parent_id_client", "parent_id_clock",
            "parent_sub_ofs", "parent_sub_len", "content_ofs", "content_end",
        )
    }
    ds = {k: np.empty(nd, np.int64) for k in ("client", "clock", "len")}
    ptr = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    rc = lib.ytpu_decode_v1(
        bp, len(update),
        ptr(cols["client"]), ptr(cols["clock"]), ptr(cols["length"]),
        ptr(cols["origin_client"]), ptr(cols["origin_clock"]),
        ptr(cols["right_client"]), ptr(cols["right_clock"]),
        ptr(cols["info"]),
        ptr(cols["parent_name_ofs"]), ptr(cols["parent_name_len"]),
        ptr(cols["parent_id_client"]), ptr(cols["parent_id_clock"]),
        ptr(cols["parent_sub_ofs"]), ptr(cols["parent_sub_len"]),
        ptr(cols["content_ofs"]), ptr(cols["content_end"]),
        ptr(ds["client"]), ptr(ds["clock"]), ptr(ds["len"]),
    )
    if rc != 0:
        raise NativeDecodeError(f"decode pass failed: {rc}")
    return cols, ds


_V2_COLS = (
    "client", "clock", "length",
    "origin_client", "origin_clock", "right_client", "right_clock",
    "info", "parent_name_ofs", "parent_name_len",
    "parent_id_client", "parent_id_clock",
    "parent_sub_ofs", "parent_sub_len",
    "content_ofs", "content_end", "content_ofs2", "content_end2",
    "content_count",
)


def decode_v2_columns(update: bytes):
    """Decode a V2 columnar update (the 9-stream container, reference
    UpdateDecoder.js:270-293) into int64 column arrays via the native
    scanner.  String contents stay lazy as byte ranges into the in-buffer
    UTF-8 arena; rest-stream payloads (binary/embed/any) as self-delimiting
    byte ranges.  Raises NativeDecodeError when unavailable, on malformed
    input, or on legacy ContentJSON / subdoc ContentDoc payloads (caller
    falls back to the Python decoder)."""
    lib = load()
    if lib is None:
        raise NativeDecodeError("native transcoder unavailable")
    buf = np.frombuffer(update, dtype=np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    n_structs = ctypes.c_uint64()
    n_ds = ctypes.c_uint64()
    rc = lib.ytpu_count_v2(bp, len(update), ctypes.byref(n_structs), ctypes.byref(n_ds))
    if rc != 0:
        raise NativeDecodeError(f"v2 count pass failed: {rc}")
    ns, nd = n_structs.value, n_ds.value
    cols = {k: np.empty(ns, np.int64) for k in _V2_COLS}
    ds = {k: np.empty(nd, np.int64) for k in ("client", "clock", "len")}
    ptr = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    rc = lib.ytpu_decode_v2(
        bp, len(update),
        *(ptr(cols[k]) for k in _V2_COLS),
        ptr(ds["client"]), ptr(ds["clock"]), ptr(ds["len"]),
    )
    if rc != 0:
        raise NativeDecodeError(f"v2 decode pass failed: {rc}")
    return cols, ds
