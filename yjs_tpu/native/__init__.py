"""ctypes loader for the native V1 transcoder (transcode.cpp).

Builds lazily with g++ on first use (cached as _transcode.so next to the
source); silently unavailable when no toolchain exists or YTPU_NO_NATIVE is
set — callers fall back to the pure-Python decoder.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "transcode.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_transcode.so")

_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", _SO, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def load():
    """The loaded library, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("YTPU_NO_NATIVE"):
        return None
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.ytpu_count_v1.restype = ctypes.c_int
    lib.ytpu_count_v1.argtypes = [u8p, ctypes.c_uint64, u64p, u64p]
    lib.ytpu_decode_v1.restype = ctypes.c_int
    lib.ytpu_decode_v1.argtypes = [u8p, ctypes.c_uint64] + [i64p] * 19
    _lib = lib
    return _lib


class NativeDecodeError(Exception):
    pass


def decode_v1_columns(update: bytes):
    """Decode a V1 update into int64 column arrays via the native scanner.

    Returns (structs: dict[str, np.ndarray], ds: dict[str, np.ndarray]).
    Raises NativeDecodeError if the library is unavailable or parsing fails
    (caller falls back to the Python decoder).
    """
    lib = load()
    if lib is None:
        raise NativeDecodeError("native transcoder unavailable")
    buf = np.frombuffer(update, dtype=np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    n_structs = ctypes.c_uint64()
    n_ds = ctypes.c_uint64()
    rc = lib.ytpu_count_v1(bp, len(update), ctypes.byref(n_structs), ctypes.byref(n_ds))
    if rc != 0:
        raise NativeDecodeError(f"count pass failed: {rc}")
    ns, nd = n_structs.value, n_ds.value
    cols = {
        k: np.empty(ns, np.int64)
        for k in (
            "client", "clock", "length",
            "origin_client", "origin_clock", "right_client", "right_clock",
            "info", "parent_name_ofs", "parent_name_len",
            "parent_id_client", "parent_id_clock",
            "parent_sub_ofs", "parent_sub_len", "content_ofs", "content_end",
        )
    }
    ds = {k: np.empty(nd, np.int64) for k in ("client", "clock", "len")}
    ptr = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    rc = lib.ytpu_decode_v1(
        bp, len(update),
        ptr(cols["client"]), ptr(cols["clock"]), ptr(cols["length"]),
        ptr(cols["origin_client"]), ptr(cols["origin_clock"]),
        ptr(cols["right_client"]), ptr(cols["right_clock"]),
        ptr(cols["info"]),
        ptr(cols["parent_name_ofs"]), ptr(cols["parent_name_len"]),
        ptr(cols["parent_id_client"]), ptr(cols["parent_id_clock"]),
        ptr(cols["parent_sub_ofs"]), ptr(cols["parent_sub_len"]),
        ptr(cols["content_ofs"]), ptr(cols["content_end"]),
        ptr(ds["client"]), ptr(ds["clock"]), ptr(ds["len"]),
    )
    if rc != 0:
        raise NativeDecodeError(f"decode pass failed: {rc}")
    return cols, ds
