"""One cluster shard = one OS process wrapping one :class:`TpuProvider`
(ISSUE 14).

Run as ``python -m yjs_tpu.cluster.shard --id K --wal-dir D [--port 0]
[--docs N]``.  On start the process either builds a fresh provider or —
when the WAL directory already holds segments — rebuilds through the
existing ``TpuProvider.recover`` snapshot-then-tail path, so a
supervisor restart after ``kill -9`` replays every journaled update
(WAL appends flush to the OS page cache per record, which survives
process death; see ``persistence/wal.py``).  It then prints ONE ready
line to stdout::

    YTPU_SHARD_READY {"shard": K, "port": P, "pid": …, "recovery": …}

and serves the cluster RPC (``cluster/rpc.py``) until told to shut
down.  All provider access is serialized under one process-wide RLock —
RPC connections are one thread each and the provider is not
thread-safe.  Flush cadence is driven by a local ticker thread through
the PR 12 adaptive ``flush_tick``.

Every flush-emitted update broadcasts to all connected RPC peers as an
``update`` event — the supervisor/gateway subscribe and fan rooms out
to client connections.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

from ..obs import dist as obs_dist
from ..obs.admin import AdminConfig, AdminServer
from .config import ClusterConfig
from .rpc import RpcBusy, RpcServer, b64d, b64e


class ShardServer:
    """RPC facade over one provider process (see module docstring)."""

    def __init__(
        self,
        shard_id: int,
        wal_dir: str,
        n_docs: int = 64,
        host: str | None = None,
        port: int = 0,
        backend: str = "cpu",
        tick_s: float = 0.05,
        config: ClusterConfig | None = None,
        admin_port: int | None = None,
    ):
        from ..provider import TpuProvider

        self.shard_id = int(shard_id)
        self.config = config if config is not None else ClusterConfig()
        self.tick_s = tick_s
        self._plock = threading.RLock()
        self._stop = threading.Event()
        # fencing-epoch currency (ISSUE 16 readiness): the highest
        # fleet epoch any control frame carried, vs the epoch the
        # supervisor last TOLD us is current.  A fence (demotion to
        # replica at epoch E) raises _epoch_seen past routing_epoch,
        # and /readyz answers 503 until the post-resolution epoch push
        # catches us up — the "fenced corpse" window.
        self.routing_epoch = 0
        self._epoch_seen = 0
        self._init_done = False
        # the admin plane starts BEFORE the provider is built so
        # /healthz answers (and /readyz says 503 "recovering") during a
        # long WAL replay — exactly the window probes care about
        self.admin: AdminServer | None = None
        try:
            self.admin = AdminServer(
                self,
                role="shard",
                config=AdminConfig(port=admin_port),
            ).start()
        except OSError:
            self.admin = None  # port taken: serve data plane anyway
        has_wal = os.path.isdir(wal_dir) and any(
            os.scandir(wal_dir)
        )
        if has_wal:
            self.provider = TpuProvider.recover(
                wal_dir, n_docs=n_docs, backend=backend
            )
            stats = self.provider.last_recovery or {}
            self.recovery = {
                "outcome": "recovered",
                "records_applied": stats.get("records_applied", 0),
                "session_acks": stats.get("session_acks", 0),
                "migrations_pending": sorted(
                    (stats.get("migrations_pending") or {}).keys()
                ),
                "repl_roles": {
                    g: info.get("role", "")
                    for g, info in (stats.get("repl_roles") or {}).items()
                },
            }
        else:
            self.provider = TpuProvider(
                n_docs, backend=backend, wal_dir=wal_dir
            )
            self.recovery = {"outcome": "fresh"}
        self.provider.shard_id = self.shard_id
        # journal-only replica copies (PR 8 fan-out over sockets): the
        # engine never sees these, so WAL compaction would destroy them
        # — checkpoints fold only engine-resident docs.  Track them
        # host-side and re-journal after every checkpoint, the same
        # durability interplay ReplicationManager.rejournal_after_
        # checkpoint handles for the in-process fleet.
        self._replica_records: dict[str, list[tuple[int, bytes, bool]]] = {}
        self._replica_roles: dict[str, dict] = {}
        self.server = RpcServer(
            self,
            host=host if host is not None else self.config.host,
            port=port,
        )
        self.provider.on_update(self._on_flush_update)
        self._ticker = threading.Thread(
            target=self._tick_loop,
            name=f"ytpu-shard-tick-{self.shard_id}",
            daemon=True,
        )
        self._ticker.start()
        self._init_done = True

    @property
    def port(self) -> int:
        return self.server.port

    @property
    def admin_port(self) -> int:
        return self.admin.port if self.admin is not None else 0

    # -- admin-plane target (ISSUE 16) ---------------------------------------

    def metrics_text(self) -> str:
        prov = getattr(self, "provider", None)
        if prov is None:
            from ..obs import global_registry, prometheus_text

            return prometheus_text(global_registry())
        return prov.metrics_text()

    def metrics_snapshot(self) -> dict:
        """The federation payload: the provider's full snapshot plus
        the shard identity keys — byte-identical to what the supervisor
        writes as ``shard-K.json``, so HTTP-scrape and file-drop
        federation merge the exact same input."""
        prov = getattr(self, "provider", None)
        if prov is None:
            snap = {}
        else:
            with self._plock:
                snap = prov.metrics_snapshot()
        snap["shard"] = self.shard_id
        snap["pid"] = os.getpid()
        snap["label"] = f"shard-{self.shard_id:03d}"
        snap["role"] = "primary"
        return snap

    def statusz(self) -> dict:
        prov = getattr(self, "provider", None)
        if prov is None:
            status = {"recovering": True}
        else:
            with self._plock:
                status = prov.statusz()
        status.update({
            "role": "shard",
            "shard": self.shard_id,
            "rpc_port": self.server.port if self._init_done else 0,
            "routing_epoch": self.routing_epoch,
            "epoch_seen": self._epoch_seen,
            "recovery": getattr(self, "recovery", {}),
        })
        return status

    def readiness(self) -> dict:
        """``/readyz``: not ready while the provider is still being
        built/recovered, while brownout rejects writes, or while this
        shard's routing epoch lags a fence it witnessed (a stale
        primary must not take traffic until the supervisor publishes
        the post-resolution epoch).  Lock-free on purpose — reads are
        plain attributes, so a wedged provider lock cannot wedge the
        probe (liveness stays /healthz's job)."""
        prov = getattr(self, "provider", None)
        recovering = (
            not self._init_done
            or prov is None
            or getattr(prov, "recovering", False)
        )
        level = (
            prov.admission.brownout.level if prov is not None else 0
        )
        current = self.routing_epoch >= self._epoch_seen
        ready = (not recovering) and level < 3 and current
        return {
            "ready": ready,
            "checks": {
                "recovery_complete": not recovering,
                "brownout_level": level,
                "accepting_writes": level < 3,
                "epoch_current": current,
                "routing_epoch": self.routing_epoch,
                "epoch_seen": self._epoch_seen,
            },
        }

    def trace_events(self) -> list:
        prov = getattr(self, "provider", None)
        if prov is None:
            return []
        return prov.trace_events()

    def _on_flush_update(self, guid: str, update: bytes) -> None:
        # flush-emitted merged update: push to every RPC subscriber
        # (the gateway fans it to the room's client connections)
        self.server.broadcast(
            "update", {"guid": guid, "update": b64e(update)}
        )

    def _tick_loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            with self._plock:
                try:
                    self.provider.flush_tick()
                    self.provider.tick_sessions()
                except Exception:
                    pass  # a failed tick retries next round

    # -- RPC ingress seam ----------------------------------------------------

    def handle_rpc_request(self, method: str, payload: dict, ctx):
        """The shard's ingress seam: every cross-process frame enters
        here.  Adopts the carried :class:`TraceContext` (PR 11) before
        dispatch, so provider-side spans join the gateway's trace, and
        delegates data traffic to the provider's own seams
        (``receive_update`` / ``handle_sync_message``) which feed the
        WAL, admission, and SLO pipelines."""
        with obs_dist.use_context(ctx):
            with self._plock:
                return self._dispatch(method, payload)

    def _dispatch(self, method: str, payload: dict):
        from ..admission import AdmissionRejected
        from ..provider import ProviderFullError

        prov = self.provider
        if method == "hello":
            return {
                "shard": self.shard_id,
                "pid": os.getpid(),
                "port": self.server.port,
                "recovery": self.recovery,
            }
        if method == "heartbeat":
            return prov.heartbeat()
        if method == "sync":
            guid = payload["guid"]
            frame = b64d(payload["frame"])
            try:
                reply = prov.handle_sync_message(guid, frame)
            except ProviderFullError:
                prov.admission.note_full("provider")
                raise RpcBusy(prov.admission.retry_after)
            return {"reply": b64e(reply) if reply is not None else None}
        if method == "update":
            guid = payload["guid"]
            update = b64d(payload["update"])
            try:
                ok = prov.receive_update(
                    guid,
                    update,
                    v2=bool(payload.get("v2")),
                    internal=bool(payload.get("internal")),
                )
            except AdmissionRejected as e:
                raise RpcBusy(e.retry_after)
            except ProviderFullError:
                prov.admission.note_full("provider")
                raise RpcBusy(prov.admission.retry_after)
            return {"accepted": bool(ok)}
        if method == "sv":
            prov.flush()
            sv = prov.engine.encode_state_vector(prov.doc_id(payload["guid"]))
            return {"sv": b64e(sv)}
        if method == "diff":
            sv = payload.get("sv")
            diff = prov.encode_state_as_update(
                payload["guid"], b64d(sv) if sv else None
            )
            return {"update": b64e(diff)}
        if method == "text":
            prov.flush()
            return {"text": prov.text(payload["guid"])}
        if method == "guids":
            return {"guids": prov.guids()}
        if method == "flush":
            prov.flush()
            return {}
        if method == "checkpoint":
            return {"checkpoint": bool(self._checkpoint())}
        if method == "metrics":
            # same payload the admin plane serves at /metrics.json —
            # RPC fallback and HTTP scrape federate identical input
            # (_plock is an RLock; re-entering here is fine)
            return {"snapshot": self.metrics_snapshot()}
        if method == "journal_ack":
            prov.journal_session_ack(
                payload["guid"], payload["peer"],
                int(payload["sid"]), int(payload["seq"]),
            )
            return {}
        if method == "ack_hints":
            # journaled resume floors recovered from the WAL: the
            # gateway re-arms surviving sessions with these so a
            # restarted shard resumes retransmission, not full resync
            hints = {}
            for (guid, peer), (sid, seq) in getattr(
                prov, "_recovered_acks", {}
            ).items():
                hints.setdefault(guid, {})[peer] = [sid, seq]
            return {"hints": hints}
        if method == "journal_migration":
            prov.journal_migration(
                payload["guid"], int(payload["dst"]), int(payload["epoch"])
            )
            return {}
        if method == "journal_repl_role":
            guid = payload["guid"]
            role = payload["role"]
            prov.journal_repl_role(
                guid,
                role,
                int(payload["epoch"]),
                primary=payload.get("primary"),
            )
            # witnessing a fleet epoch ahead of our routing epoch (a
            # fence/demotion decided while we were dead) flips /readyz
            # until the supervisor's post-resolution epoch push
            self._epoch_seen = max(self._epoch_seen, int(payload["epoch"]))
            self._replica_roles[guid] = {
                "role": str(role),
                "epoch": int(payload["epoch"]),
                "primary": payload.get("primary"),
            }
            if role == "primary":
                # promotion: the doc is (or is about to be) engine-
                # resident, so checkpoints fold it from the engine now
                self._replica_records.pop(guid, None)
            return {}
        if method == "repl_record":
            # replication fan-out target (PR 8 semantics over sockets):
            # journal-only on the replica's own WAL — promotion
            # materializes by restart-with-recover
            guid = payload["guid"]
            kind = int(payload["kind"])
            data = b64d(payload["payload"])
            v2 = bool(payload.get("v2"))
            ok = prov.journal_replica_record(kind, guid, data, v2=v2)
            if ok:
                self._track_replica_record(guid, kind, data, v2)
            return {"journaled": bool(ok)}
        if method == "release":
            guid = payload["guid"]
            final = prov.release_doc(guid)
            # the release record clears the WAL claim; drop the mirror
            self._replica_records.pop(guid, None)
            self._replica_roles.pop(guid, None)
            return {"update": b64e(final)}
        if method == "epoch":
            # routing-epoch bump (fencing, PR 8): a shard holding a
            # lower epoch than the fleet's learns it here — this is
            # the "you are current again" signal that restores /readyz
            # after a fence raised _epoch_seen
            self.routing_epoch = max(
                self.routing_epoch, int(payload["epoch"])
            )
            self._epoch_seen = max(self._epoch_seen, self.routing_epoch)
            return {"epoch": self.routing_epoch}
        if method == "shutdown":
            self._stop.set()
            return {"stopping": True}
        raise ValueError(f"unknown rpc method: {method}")

    # -- replica-record durability (PR 8 interplay) ---------------------------

    def _track_replica_record(
        self, guid: str, kind: int, data: bytes, v2: bool
    ) -> None:
        """Mirror one journal-only record host-side so it survives WAL
        compaction.  Plain v1 update records coalesce through
        ``merge_updates`` past a small threshold — the mirror stays
        bounded by doc-state size, not fan-out volume."""
        from ..persistence import KIND_UPDATE

        recs = self._replica_records.setdefault(guid, [])
        recs.append((kind, bytes(data), v2))
        mergeable = [
            p for k, p, r2 in recs if k == KIND_UPDATE and not r2
        ]
        if len(mergeable) > 16:
            from ..updates import merge_updates

            rest = [
                e for e in recs if not (e[0] == KIND_UPDATE and not e[2])
            ]
            self._replica_records[guid] = rest + [
                (KIND_UPDATE, merge_updates(mergeable), False)
            ]

    def _rejournal_replicas(self) -> int:
        """Re-append every mirrored replica record + role marker after
        a checkpoint compacted the segments they lived in (the cluster-
        process twin of ``ReplicationManager.rejournal_after_
        checkpoint``)."""
        n = 0
        for guid in sorted(self._replica_roles):
            info = self._replica_roles[guid]
            self.provider.journal_repl_role(
                guid, info["role"], info["epoch"],
                primary=info.get("primary"),
            )
            n += 1
        for guid in sorted(self._replica_records):
            for kind, data, v2 in self._replica_records[guid]:
                if self.provider.journal_replica_record(
                    kind, guid, data, v2=v2
                ):
                    n += 1
        return n

    def _checkpoint(self) -> dict | None:
        res = self.provider.checkpoint()
        if res is not None:
            self._rejournal_replicas()
        return res

    # -- lifecycle -----------------------------------------------------------

    def run_forever(self) -> None:
        while not self._stop.wait(0.2):
            pass

    def close(self, checkpoint: bool = True) -> None:
        self._stop.set()
        if self._ticker.is_alive():
            self._ticker.join(timeout=2.0)
        if self.admin is not None:
            self.admin.close()
        self.server.close()
        with self._plock:
            try:
                if checkpoint and self.provider.wal is not None:
                    # checkpoint through the rejournal wrapper: the
                    # final compaction must not destroy journal-only
                    # replica copies a successor's recover will need
                    self._checkpoint()
                    self.provider.close(checkpoint=False)
                else:
                    self.provider.close(checkpoint=checkpoint)
            except Exception:
                pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="one y-tpu cluster shard process"
    )
    ap.add_argument("--id", type=int, required=True)
    ap.add_argument("--wal-dir", required=True)
    ap.add_argument("--docs", type=int, default=64)
    ap.add_argument("--host", default=None)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--backend", default="cpu")
    ap.add_argument("--tick-s", type=float, default=0.05)
    ap.add_argument(
        "--admin-port", type=int, default=None,
        help="admin-plane HTTP port (default: YTPU_ADMIN_PORT or 0; "
        "YTPU_ADMIN_DISABLED=1 turns the plane off)",
    )
    args = ap.parse_args(argv)

    shard = ShardServer(
        args.id,
        args.wal_dir,
        n_docs=args.docs,
        host=args.host,
        port=args.port,
        backend=args.backend,
        tick_s=args.tick_s,
        admin_port=args.admin_port,
    )
    ready = {
        "shard": shard.shard_id,
        "port": shard.port,
        "pid": os.getpid(),
        "admin_port": shard.admin_port,
        "recovery": shard.recovery,
    }
    sys.stdout.write(
        "YTPU_SHARD_READY " + json.dumps(ready, separators=(",", ":")) + "\n"
    )
    sys.stdout.flush()

    def _term(signum, frame):
        shard._stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        shard.run_forever()
    finally:
        shard.close(checkpoint=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
