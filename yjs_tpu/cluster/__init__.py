"""Process-native cluster (ISSUE 14): real OS-process shards behind a
y-websocket-compatible gateway.

Layering (each importable without jax until a provider is built):

- :mod:`.config` — ``YTPU_CLUSTER_*`` / ``YTPU_GATEWAY_*`` knobs
- :mod:`.rpc` — envelope-121 RPC framing over length-prefixed TCP, plus
  :class:`SocketTransport`, the threaded session transport with the
  drain-then-join shutdown contract
- :mod:`.shard` — one shard = one process wrapping one ``TpuProvider``
  (``python -m yjs_tpu.cluster.shard``)
- :mod:`.supervisor` — spawn/monitor/restart/fail-over, federated
  metrics, structured recovery report
- :mod:`.gateway` — the wire-compatible front door (y-websocket and
  raw-session dialects) and :class:`LocalCluster`, the in-process
  facade for tests and the bench baseline
"""

from .config import ClusterConfig, GatewayConfig  # noqa: F401
from .gateway import (  # noqa: F401
    Gateway,
    LocalCluster,
    encode_room_preamble,
)
from .rpc import (  # noqa: F401
    FrameConn,
    RpcBusy,
    RpcClient,
    RpcClosed,
    RpcError,
    RpcServer,
    SocketTransport,
)
from .supervisor import Supervisor  # noqa: F401

__all__ = [
    "ClusterConfig",
    "FrameConn",
    "Gateway",
    "GatewayConfig",
    "LocalCluster",
    "RpcBusy",
    "RpcClient",
    "RpcClosed",
    "RpcError",
    "RpcServer",
    "SocketTransport",
    "Supervisor",
    "encode_room_preamble",
]
