"""The cluster's wire-compatible front door (ISSUE 14).

One TCP listener, two dialects, sniffed per connection:

- **y-websocket** — a connection starting with an HTTP ``GET`` gets the
  RFC 6455 handshake and then speaks exactly what a stock
  ``y-websocket`` client (Yjs v13.4.9) expects: binary messages whose
  first varuint is the outer type (``0`` sync, ``1`` awareness, ``3``
  query-awareness), with the 2-step sync handshake inside type 0 —
  step 1 answered with a byte-identical step 2 diff, updates applied
  and fanned out to the room.  Unknown outer types are counted and
  skipped (the y-protocols tolerance contract), awareness frames pass
  through to room members and are cached for late joiners.  The room
  name is the URL path.
- **raw session** — anything else is the PR 5 enhanced protocol over
  ``<I``-length-prefixed frames (the ``cluster/rpc.py`` transport): a
  varstring ``room`` + ``peer`` preamble, then a full server-side
  :class:`SyncSession` per connection — acked outbox, BUSY
  backpressure, digest anti-entropy, rehome on migration/failover.
  ``examples/socket_connector.py`` is the matching client.

Behind either dialect every frame routes to the room's owner shard via
the cluster facade — :class:`~yjs_tpu.cluster.supervisor.Supervisor`
for real OS processes, or :class:`LocalCluster` (below) wrapping an
in-process :class:`~yjs_tpu.fleet.FleetRouter` so tests and the bench
can compare the same gateway over both fabrics.  While a shard is
down the facade raises :class:`RpcBusy`; session connections answer
with the BUSY envelope (the peer retransmits — zero acked loss) and
y-websocket frames are dropped and counted (stock clients carry no ack
to lose; they re-sync on reconnect).

Failover/migration rehoming: the facade's ``on_epoch`` fires after a
routing change; session connections :meth:`~SyncSession.rehome` (digest
→ targeted repair, not full resync) and y-websocket rooms get a fresh
step 1 so clients send back anything the dead shard never flushed.
"""

from __future__ import annotations

import base64
import hashlib
import socket
import struct
import threading
import time
from urllib.parse import unquote

from ..lib0 import decoding, encoding
from ..lib0.decoding import Decoder
from ..lib0.encoding import Encoder
from ..obs import dist as obs_dist
from ..obs import global_registry
from ..sync import protocol
from ..sync.session import SessionConfig, SyncSession, encode_busy
from .config import GatewayConfig
from .rpc import FrameConn, RpcBusy, RpcError, SocketTransport

# y-websocket outer message types (y-websocket/bin/utils.js)
MESSAGE_SYNC = 0
MESSAGE_AWARENESS = 1
MESSAGE_AUTH = 2
MESSAGE_QUERY_AWARENESS = 3

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

_OUTER_NAMES = {
    MESSAGE_SYNC: "sync",
    MESSAGE_AWARENESS: "awareness",
    MESSAGE_AUTH: "auth",
    MESSAGE_QUERY_AWARENESS: "query_awareness",
}


class _GatewayMetrics:
    """``ytpu_gateway_*`` families (process-global, re-register safe)."""

    def __init__(self):
        reg = global_registry()
        self.conns = reg.gauge(
            "ytpu_gateway_conns", "Live gateway client connections"
        )
        self.rooms = reg.gauge(
            "ytpu_gateway_rooms", "Rooms with at least one connection"
        )
        self.frames = reg.counter(
            "ytpu_gateway_frames_total",
            "Gateway frames by direction and outer kind",
            labelnames=("dir", "kind"),
        )
        self.unknown = reg.counter(
            "ytpu_gateway_unknown_total",
            "Unknown outer message types skipped (tolerance contract)",
        )
        self.busy_drops = reg.counter(
            "ytpu_gateway_busy_drops_total",
            "y-websocket frames dropped while the owner shard was "
            "unavailable (stock clients re-sync on reconnect)",
        )
        self.rehomes = reg.counter(
            "ytpu_gateway_rehomes_total",
            "Connection rehomes after a routing-epoch bump",
        )


# -- cluster-backed session host ----------------------------------------------


class _ClusterSessionHost:
    """Session host over the cluster facade — the cross-process twin of
    ``_ProviderSessionHost`` / ``_FleetSessionHost``.  Every path a
    session drives lands on the room's owner shard; shard unavailability
    surfaces as BUSY (``handle_frame``) or a stale-but-safe cached state
    vector (``state_vector``) so nothing ever escapes into the
    transport pump."""

    __slots__ = ("cluster", "guid", "peer", "_sv_cache")

    def __init__(self, cluster, guid: str, peer: str):
        self.cluster = cluster
        self.guid = guid
        self.peer = peer
        self._sv_cache = b"\x00"  # empty state vector

    def state_vector(self) -> bytes:
        try:
            sv = self.cluster.state_vector_bytes(self.guid)
        except (RpcBusy, RpcError):
            # shard mid-restart: a stale digest at worst triggers one
            # extra repair round; raising would kill the rx thread
            return self._sv_cache
        self._sv_cache = sv
        return sv

    def diff_update(self, sv: bytes | None) -> bytes:
        return self.cluster.diff_update(self.guid, sv)

    def apply_update(self, update: bytes) -> None:
        self.cluster.receive_update(self.guid, update)

    def handle_frame(self, frame: bytes) -> bytes | None:
        try:
            return self.cluster.handle_sync_message(self.guid, frame)
        except RpcBusy as e:
            # the zero-acked-loss seam: refuse instead of ack — the
            # peer keeps the frame in its outbox and retransmits once
            # the shard is back
            return encode_busy(e.retry_after)

    def dead_letter(self, payload: bytes, reason: str) -> None:
        # the refusing shard already quarantined its copy (or was down,
        # in which case the peer still holds the frame); the gateway
        # only surfaces the event
        _GatewayMetricsSingleton.get().frames.labels(
            dir="rx", kind="dead_letter"
        ).inc()

    def journal_ack(self, sid: int, seq: int) -> None:
        self.cluster.journal_ack(self.guid, self.peer, sid, seq)


class _GatewayMetricsSingleton:
    _inst = None
    _lock = threading.Lock()

    @classmethod
    def get(cls) -> _GatewayMetrics:
        with cls._lock:
            if cls._inst is None:
                cls._inst = _GatewayMetrics()
            return cls._inst


# -- in-process cluster facade ------------------------------------------------


class LocalCluster:
    """The Supervisor facade over an in-process
    :class:`~yjs_tpu.fleet.FleetRouter` — same gateway, no processes.
    This is the bench baseline ("gateway over in-process fleet") and
    the fast path for wire-compat tests; it also makes the facade
    contract explicit: anything both fabrics implement is what the
    gateway may call."""

    def __init__(self, fleet):
        self.fleet = fleet
        self._lock = threading.RLock()
        self.on_update = None
        self.on_epoch = None
        # flush-emitted updates re-dispatch on a dedicated thread, the
        # same shape as Supervisor._evt_loop: the fleet fires its
        # on_update bridge synchronously inside flush() — i.e. while
        # this facade's lock is held — so calling the gateway (which
        # takes gw._lock) from here would invert the gateway's
        # gw._lock → cluster-lock order and deadlock against the tick
        # loop.  The queue keeps the facade lock a leaf for callbacks.
        self._evt_q: list[tuple[str, bytes]] = []
        self._evt_wake = threading.Condition()
        self._evt_stop = False
        self._evt_thread = threading.Thread(
            target=self._evt_loop, name="ytpu-localcluster-evt", daemon=True
        )
        fleet.on_update(self._fan)
        self._evt_thread.start()

    def _fan(self, guid: str, update: bytes) -> None:
        with self._evt_wake:
            if self._evt_stop:
                return
            self._evt_q.append((guid, bytes(update)))
            self._evt_wake.notify()

    def _evt_loop(self) -> None:
        while True:
            with self._evt_wake:
                while not self._evt_q and not self._evt_stop:
                    self._evt_wake.wait()
                if not self._evt_q and self._evt_stop:
                    return
                batch, self._evt_q[:] = list(self._evt_q), []
            cb = self.on_update
            if cb is None:
                continue
            for guid, update in batch:
                try:
                    cb(guid, update)
                except Exception:
                    pass  # a bad subscriber must not stall fan-out

    @property
    def epoch(self) -> int:
        with self._lock:
            return self.fleet.table.epoch

    def owner_of(self, guid: str):
        with self._lock:
            return self.fleet.shard_of(guid)

    def receive_update(self, guid: str, update: bytes, v2: bool = False,
                       internal: bool = False) -> bool:
        ctx = obs_dist.current_context() or obs_dist.mint_for_update(
            bytes(update)
        )
        with obs_dist.use_context(ctx):
            with self._lock:
                return self.fleet.receive_update(
                    guid, update, v2=v2, internal=internal
                )

    def handle_sync_message(self, guid: str, message: bytes) -> bytes | None:
        ctx = obs_dist.current_context()
        with obs_dist.use_context(ctx):
            with self._lock:
                return self.fleet.handle_sync_message(guid, message)

    def state_vector_bytes(self, guid: str) -> bytes:
        with self._lock:
            p = self.fleet.provider_for(guid)
            p.flush()
            return p.engine.encode_state_vector(p.doc_id(guid))

    def diff_update(self, guid: str, sv: bytes | None) -> bytes:
        with self._lock:
            return self.fleet.encode_state_as_update(guid, sv)

    def text(self, guid: str) -> str:
        with self._lock:
            return self.fleet.text(guid)

    def flush(self, guid: str | None = None) -> None:
        with self._lock:
            self.fleet.flush()

    def journal_ack(self, guid: str, peer: str, sid: int, seq: int) -> None:
        with self._lock:
            self.fleet.provider_for(guid).journal_session_ack(
                guid, peer, sid, seq
            )

    def tick(self) -> None:
        with self._lock:
            self.fleet.flush_tick()
            self.fleet.tick_sessions()

    def metrics_snapshot(self) -> dict:
        with self._lock:
            return self.fleet.metrics_snapshot()

    def recovery_report(self) -> dict:
        with self._lock:
            return self.fleet.recovery_report()

    def close(self) -> None:
        with self._evt_wake:
            self._evt_stop = True
            self._evt_wake.notify_all()
        if (
            self._evt_thread.is_alive()
            and self._evt_thread is not threading.current_thread()
        ):
            self._evt_thread.join(timeout=5.0)
        with self._lock:
            self.fleet.close()


# -- websocket plumbing (stdlib only) -----------------------------------------


def ws_accept_key(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + _WS_GUID).encode("ascii")).digest()
    ).decode("ascii")


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def ws_read_message(sock: socket.socket, max_frame: int):
    """One complete (possibly fragmented) message → ``(opcode, bytes)``
    or ``None`` on EOF/protocol error.  Control frames are handled
    inline (ping answered, close echoed then ``None``)."""
    message = b""
    opcode0 = None
    while True:
        hdr = _recv_exact(sock, 2)
        if hdr is None:
            return None
        fin = hdr[0] & 0x80
        opcode = hdr[0] & 0x0F
        masked = hdr[1] & 0x80
        ln = hdr[1] & 0x7F
        if ln == 126:
            ext = _recv_exact(sock, 2)
            if ext is None:
                return None
            ln = int.from_bytes(ext, "big")
        elif ln == 127:
            ext = _recv_exact(sock, 8)
            if ext is None:
                return None
            ln = int.from_bytes(ext, "big")
        if ln > max_frame:
            return None
        mask = _recv_exact(sock, 4) if masked else None
        if mask is None and masked:
            return None
        payload = _recv_exact(sock, ln) if ln else b""
        if payload is None:
            return None
        if mask:
            payload = bytes(
                b ^ mask[i & 3] for i, b in enumerate(payload)
            )
        if opcode == 0x8:  # close: echo and stop
            ws_send_message(sock, payload, opcode=0x8)
            return None
        if opcode == 0x9:  # ping → pong
            ws_send_message(sock, payload, opcode=0xA)
            continue
        if opcode == 0xA:  # pong
            continue
        if opcode in (0x1, 0x2):
            opcode0 = opcode
            message = payload
        elif opcode == 0x0:  # continuation
            message += payload
        else:
            return None
        if fin:
            return (opcode0 if opcode0 is not None else opcode, message)


def ws_send_message(sock: socket.socket, payload: bytes,
                    opcode: int = 0x2) -> bool:
    """One unmasked (server→client) message, single frame."""
    n = len(payload)
    hdr = bytes([0x80 | opcode])
    if n < 126:
        hdr += bytes([n])
    elif n < 1 << 16:
        hdr += bytes([126]) + n.to_bytes(2, "big")
    else:
        hdr += bytes([127]) + n.to_bytes(8, "big")
    try:
        sock.sendall(hdr + payload)
        return True
    except OSError:
        return False


def encode_room_preamble(room: str, peer: str = "peer") -> bytes:
    """The raw-dialect hello: first length-prefixed frame on the wire."""
    enc = Encoder()
    encoding.write_var_string(enc, room)
    encoding.write_var_string(enc, peer)
    return enc.to_bytes()


# -- one client connection ----------------------------------------------------


class _GatewayConn:
    """One accepted client connection, either dialect."""

    def __init__(self, gateway: "Gateway", sock: socket.socket, addr):
        self.gateway = gateway
        self.sock = sock
        self.addr = addr
        self.dialect = ""  # "ws" | "raw"
        self.room = ""
        self.peer = f"{addr[0]}:{addr[1]}"
        self.session = None     # raw dialect only
        self.transport = None   # raw dialect only
        self.awareness = None   # ws dialect: last awareness payload
        self._send_lock = threading.Lock()
        self._thread = None

    # -- ws dialect ----------------------------------------------------------

    def send_ws(self, payload: bytes) -> bool:
        with self._send_lock:
            return ws_send_message(self.sock, payload)

    def _ws_handshake(self) -> bool:
        data = b""
        while b"\r\n\r\n" not in data:
            try:
                chunk = self.sock.recv(4096)
            except OSError:
                return False
            if not chunk:
                return False
            data += chunk
            if len(data) > 64 * 1024:
                return False
        head = data.split(b"\r\n\r\n", 1)[0].decode("latin-1")
        lines = head.split("\r\n")
        try:
            path = lines[0].split(" ")[1]
        except IndexError:
            return False
        key = ""
        for line in lines[1:]:
            name, _, value = line.partition(":")
            if name.strip().lower() == "sec-websocket-key":
                key = value.strip()
        if not key:
            return False
        self.room = unquote(path.lstrip("/").split("?")[0]) or "default"
        resp = (
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\n"
            "Connection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {ws_accept_key(key)}\r\n\r\n"
        )
        try:
            self.sock.sendall(resp.encode("latin-1"))
        except OSError:
            return False
        return True

    def _ws_serve(self) -> None:
        gw = self.gateway
        if not self._ws_handshake():
            gw._drop_conn(self)
            return
        t = gw.config.send_timeout_s
        if t > 0:
            # send-side bound only (a plain settimeout would also make
            # idle recv() loops time out): a client with a full TCP
            # send buffer fails the send instead of blocking forever
            try:
                self.sock.setsockopt(
                    socket.SOL_SOCKET,
                    socket.SO_SNDTIMEO,
                    struct.pack("ll", int(t), int((t % 1.0) * 1e6)),
                )
            except (OSError, struct.error):
                pass
        gw._register(self)
        # y-websocket servers open with their step 1 (+ cached awareness)
        try:
            sv = gw.cluster.state_vector_bytes(self.room)
        except (RpcBusy, RpcError):
            sv = b"\x00"
        enc = Encoder()
        encoding.write_var_uint(enc, MESSAGE_SYNC)
        encoding.write_var_uint(enc, protocol.MESSAGE_YJS_SYNC_STEP_1)
        encoding.write_var_uint8_array(enc, sv)
        self.send_ws(enc.to_bytes())
        gw.metrics.frames.labels(dir="tx", kind="sync").inc()
        for frame in gw._cached_awareness(self):
            self.send_ws(frame)
        while True:
            msg = ws_read_message(self.sock, gw.config.max_frame)
            if msg is None:
                break
            _, payload = msg
            if payload:
                self.handle_client_message(payload)
        gw._drop_conn(self)

    def handle_client_message(self, data: bytes) -> None:
        """The gateway's y-websocket ingress seam: adopt-or-mint the
        trace for the frame, then route the inner sync message to the
        room's owner shard through the cluster facade (which stamps the
        SLO and carries the context across the RPC hop)."""
        ctx = obs_dist.current_context() or obs_dist.mint_for_update(
            bytes(data)
        )
        with obs_dist.use_context(ctx):
            self._dispatch_client(data)

    def _dispatch_client(self, data: bytes) -> None:
        gw = self.gateway
        dec = Decoder(bytes(data))
        try:
            outer = decoding.read_var_uint(dec)
        except Exception:
            gw.metrics.unknown.inc()
            return
        kind = _OUTER_NAMES.get(outer, "unknown")
        gw.metrics.frames.labels(dir="rx", kind=kind).inc()
        if outer == MESSAGE_SYNC:
            inner = bytes(data[dec.pos:])
            # the facade serializes internally — holding gw._lock across
            # a shard call would stall every other connection for the
            # RPC's duration (and is never needed for lock ordering:
            # gw._lock → cluster is the one legal order)
            try:
                reply = gw.cluster.handle_sync_message(self.room, inner)
            except (RpcBusy, RpcError):
                # no ack concept on this dialect: count the drop; the
                # client repairs via its reconnect resync
                gw.metrics.busy_drops.inc()
                return
            if reply is not None:
                enc = Encoder()
                encoding.write_var_uint(enc, MESSAGE_SYNC)
                out = enc.to_bytes() + reply
                self.send_ws(out)
                gw.metrics.frames.labels(dir="tx", kind="sync").inc()
        elif outer == MESSAGE_AWARENESS:
            self.awareness = bytes(data)
            gw._broadcast_ws(self.room, bytes(data), exclude=self)
        elif outer == MESSAGE_QUERY_AWARENESS:
            for frame in gw._cached_awareness(self):
                self.send_ws(frame)
        elif outer == MESSAGE_AUTH:
            pass  # permissive gateway: auth frames are acknowledged noise
        else:
            # tolerance contract: unknown outer types skip, never kill
            # the connection (mirrors y-protocols readSyncMessage)
            gw.metrics.unknown.inc()

    # -- raw session dialect -------------------------------------------------

    def _raw_serve(self, first: bytes) -> None:
        gw = self.gateway
        try:
            dec = Decoder(first)
            self.room = decoding.read_var_string(dec)
            if dec.has_content():
                self.peer = decoding.read_var_string(dec)
        except Exception:
            gw._drop_conn(self)
            return
        host = _ClusterSessionHost(gw.cluster, self.room, self.peer)
        session = SyncSession(
            host, config=gw.session_config, peer=self.peer
        )
        transport = SocketTransport(
            self.sock,
            frame_lock=gw._lock,
            max_frame=gw.config.max_frame,
            name=self.peer,
        )
        with gw._lock:
            self.session = session
            self.transport = transport
            session.attach(transport)
            # busy-guard the pump: a facade RpcBusy mid-handshake (shard
            # restarting) drops that frame — unacked, so the peer
            # retransmits — instead of killing the rx thread
            inner_frame = transport.on_frame
            def _guarded(frame, _cb=inner_frame):
                try:
                    _cb(frame)
                except (RpcBusy, RpcError):
                    gw.metrics.busy_drops.inc()
            transport.on_frame = _guarded
            inner_close = transport.on_close
            def _closed(_cb=inner_close):
                if _cb is not None:
                    _cb()
                gw._drop_conn(self)
            transport.on_close = _closed
        gw._register(self)
        gw.metrics.frames.labels(dir="rx", kind="session_hello").inc()
        transport.start()

    # -- common --------------------------------------------------------------

    def _sniff(self) -> bytes:
        """Peek the first bytes without consuming them.  TCP may hand
        the head over split (a ws client's ``GET`` can arrive as just
        ``G``), so keep peeking until ≥3 bytes, EOF, or a grace
        deadline — a single short peek would misclassify the dialect."""
        deadline = time.monotonic() + 5.0
        while True:
            try:
                head = self.sock.recv(4, socket.MSG_PEEK)
            except OSError:
                return b""
            if not head or len(head) >= 3:
                return head
            if time.monotonic() >= deadline:
                return head
            time.sleep(0.005)

    def serve(self) -> None:
        """Sniff the dialect and run the connection (its own thread)."""
        head = self._sniff()
        if not head:
            self.gateway._drop_conn(self)
            return
        if head.startswith(b"GET"):
            self.dialect = "ws"
            self._ws_serve()
        else:
            self.dialect = "raw"
            pre = FrameConn(
                self.sock, max_frame=self.gateway.config.max_frame
            )
            first = pre.recv()
            if first is None:
                self.gateway._drop_conn(self)
                return
            self._raw_serve(first)

    def close(self) -> None:
        if self.transport is not None:
            t = self.transport
            t.close()
            t.join()
        else:
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self.sock.close()
            except OSError:
                pass


# -- the gateway --------------------------------------------------------------


class Gateway:
    """The y-websocket-compatible cluster endpoint (module docstring)."""

    def __init__(
        self,
        cluster,
        config: GatewayConfig | None = None,
        session_config: SessionConfig | None = None,
    ):
        self.cluster = cluster
        self.config = config if config is not None else GatewayConfig()
        self.session_config = (
            session_config if session_config is not None else SessionConfig()
        )
        self.metrics = _GatewayMetricsSingleton.get()
        self._lock = threading.RLock()
        self._conns: set = set()
        self._rooms: dict[str, set] = {}
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.config.host, self.config.port))
        self._sock.listen(64)
        self._accept = threading.Thread(
            target=self._accept_loop, name="ytpu-gateway-accept", daemon=True
        )
        self._ticker = threading.Thread(
            target=self._tick_loop, name="ytpu-gateway-tick", daemon=True
        )
        self.admin = None  # started alongside the loops in start()
        cluster.on_update = self._on_room_update
        cluster.on_epoch = self._on_epoch

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def start(self) -> "Gateway":
        self._accept.start()
        self._ticker.start()
        from ..obs.admin import AdminServer

        try:
            self.admin = AdminServer(self, role="gateway").start()
        except OSError:
            self.admin = None  # port taken; ws plane still serves
        return self

    # -- admin-plane target (ISSUE 16) ---------------------------------------

    def statusz(self) -> dict:
        with self._lock:
            n_conns = len(self._conns)
            rooms = {r: len(cs) for r, cs in self._rooms.items()}
        epoch = getattr(self.cluster, "epoch", None)
        return {
            "role": "gateway",
            "port": self.port,
            "conns": n_conns,
            "rooms": rooms,
            "epoch": epoch() if callable(epoch) else epoch,
        }

    def readiness(self) -> dict:
        """Ready once the accept loop is live and the cluster facade is
        still attached — a closing gateway flips not-ready first."""
        accepting = self._accept.is_alive() and not self._stop.is_set()
        return {
            "ready": accepting,
            "checks": {"accepting": accepting},
        }

    def close(self) -> None:
        self._stop.set()
        admin = getattr(self, "admin", None)
        if admin is not None:
            admin.close()
            self.admin = None
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept.is_alive():
            self._accept.join(timeout=5.0)
        if self._ticker.is_alive():
            self._ticker.join(timeout=5.0)
        with self._lock:
            conns = list(self._conns)
            sessions = [
                c.session for c in conns if c.session is not None
            ]
            for s in sessions:
                s.close()
        for c in conns:
            c.close()

    # -- loops ---------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return
            conn = _GatewayConn(self, sock, addr)
            t = threading.Thread(
                target=conn.serve,
                name=f"ytpu-gw-{addr[1]}",
                daemon=True,
            )
            conn._thread = t
            t.start()

    def _tick_loop(self) -> None:
        while not self._stop.wait(self.config.tick_s):
            with self._lock:
                conns = list(self._conns)
                for c in conns:
                    if c.session is not None and not c.session._closed:
                        try:
                            c.session.tick()
                        except (RpcBusy, RpcError):
                            pass  # shard mid-restart; next tick retries
                tick = getattr(self.cluster, "tick", None)
                if tick is not None:
                    try:
                        tick()
                    except Exception:
                        pass

    # -- room registry -------------------------------------------------------

    def _register(self, conn: _GatewayConn) -> None:
        with self._lock:
            self._conns.add(conn)
            self._rooms.setdefault(conn.room, set()).add(conn)
            n_conns = len(self._conns)
            n_rooms = len(self._rooms)
        self.metrics.conns.set(n_conns)
        self.metrics.rooms.set(n_rooms)

    def _drop_conn(self, conn: _GatewayConn) -> None:
        with self._lock:
            self._conns.discard(conn)
            members = self._rooms.get(conn.room)
            if members is not None:
                members.discard(conn)
                if not members:
                    self._rooms.pop(conn.room, None)
            n_conns = len(self._conns)
            n_rooms = len(self._rooms)
        self.metrics.conns.set(n_conns)
        self.metrics.rooms.set(n_rooms)

    def _room_conns(self, room: str) -> list:
        with self._lock:
            return list(self._rooms.get(room, ()))

    def _cached_awareness(self, requester: _GatewayConn) -> list[bytes]:
        if not self.config.awareness:
            return []
        return [
            c.awareness
            for c in self._room_conns(requester.room)
            if c is not requester and c.awareness is not None
        ]

    def _broadcast_ws(self, room: str, frame: bytes,
                      exclude: _GatewayConn | None = None) -> None:
        if not self.config.awareness:
            return
        for c in self._room_conns(room):
            if c is exclude or c.dialect != "ws":
                continue
            c.send_ws(frame)
            self.metrics.frames.labels(dir="tx", kind="awareness").inc()

    # -- cluster callbacks ---------------------------------------------------

    def _on_room_update(self, guid: str, update: bytes) -> None:
        """A shard flushed a merged update for ``guid``: fan it to every
        connection in the room (both dialects).  Yjs integration is
        idempotent, so echoing the originator its own merged delta is
        harmless and keeps the path branch-free.

        Session sends only enqueue to the transport's writer thread, so
        they stay under the lock; ws sends block in ``sendall``, so they
        happen OUTSIDE ``gw._lock`` — one stalled client must never
        wedge the tick loop, raw-frame delivery, or other rooms."""
        ws_conns = []
        with self._lock:
            for c in list(self._rooms.get(guid, ())):
                if c.session is not None:
                    if not c.session._closed:
                        c.session.send_update(update)
                        self.metrics.frames.labels(
                            dir="tx", kind="session_update"
                        ).inc()
                elif c.dialect == "ws":
                    ws_conns.append(c)
        if not ws_conns:
            return
        enc = Encoder()
        encoding.write_var_uint(enc, MESSAGE_SYNC)
        protocol.write_update(enc, update)
        ws_frame = enc.to_bytes()
        for c in ws_conns:
            if c.send_ws(ws_frame):
                self.metrics.frames.labels(dir="tx", kind="sync").inc()
            else:
                # send failed (dead peer or SO_SNDTIMEO expired on a
                # stalled one): sever the connection so its rx loop
                # exits instead of wedging future fan-outs
                self._drop_conn(c)
                c.close()

    def _on_epoch(self, epoch: int, shards) -> None:
        """Routing epoch bumped (restart/failover/migration): rehome
        every session (digest → targeted anti-entropy repair) and
        re-offer step 1 to y-websocket rooms so stock clients push back
        whatever the dead shard never flushed."""
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            session = c.session
            if session is not None:
                with self._lock:
                    if not session._closed:
                        session.rehome(epoch)
                self.metrics.rehomes.inc()
            elif c.dialect == "ws" and c.room:
                try:
                    sv = self.cluster.state_vector_bytes(c.room)
                except (RpcBusy, RpcError):
                    continue
                enc = Encoder()
                encoding.write_var_uint(enc, MESSAGE_SYNC)
                encoding.write_var_uint(
                    enc, protocol.MESSAGE_YJS_SYNC_STEP_1
                )
                encoding.write_var_uint8_array(enc, sv)
                c.send_ws(enc.to_bytes())
                self.metrics.rehomes.inc()

    # -- introspection -------------------------------------------------------

    def sessions_snapshot(self) -> list[dict]:
        with self._lock:
            conns = list(self._conns)
            rows = []
            for c in conns:
                if c.session is not None:
                    row = c.session.snapshot()
                    row["room"] = c.room
                    row["dialect"] = c.dialect
                    rows.append(row)
                else:
                    rows.append({
                        "peer": c.peer,
                        "room": c.room,
                        "dialect": c.dialect,
                    })
        return rows
