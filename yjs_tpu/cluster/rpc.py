"""Socket RPC for the process-native cluster (ISSUE 14).

Inter-shard traffic — migration, replication fan-out, failover probes,
routing-epoch bumps — crosses process boundaries here, riding the SAME
type-121 envelope the session layer (PR 5) put on the wire, extended
with three request/response kinds the session reader tolerantly skips:

- ``K_RPC_REQ``  (8): ``121 | 8 | varint corr | varstring method |
  varuint8array payload | varuint8array trace`` — the trailing trace
  blob is the 25-byte :class:`~yjs_tpu.obs.dist.TraceContext` wire form
  (empty = unsampled/uncarried), so causal traces cross the process
  boundary exactly as they cross the session DATA frames (PR 11).
- ``K_RPC_RSP``  (9): ``121 | 9 | varint corr | varint status |
  varuint8array payload``.  Status 0 = ok, 1 = error (payload carries
  the message), 2 = busy (payload carries ``retry_after`` ticks) — the
  BUSY lane is how PR 10's admission backpressure rides the RPC: a
  refused call surfaces as :class:`RpcBusy` and the caller's session
  leaves the frame un-acked for retransmission.
- ``K_RPC_EVT`` (10): ``121 | 10 | varstring topic | varuint8array
  payload`` — unsolicited server→client pushes (a shard's
  flush-emitted updates fanning out to the gateway).

Framing is the length-prefix (``<I``) framing
``examples/socket_connector.py`` established; payloads are canonical
JSON with base64 for binary fields (debuggable, schema-free — the
volume path is the gateway's session frames, not the RPC envelope).

:class:`SocketTransport` is the reusable threaded transport under all
of this: a :class:`~yjs_tpu.sync.transport.Transport` over one TCP
socket whose writer thread drains the outbox and whose ``close()``
JOINS both threads after the drain — frames accepted before close are
on the wire before the FIN (the satellite-1 contract the old
connector's fire-and-forget shutdown broke).
"""

from __future__ import annotations

import base64
import itertools
import json
import socket
import struct
import threading

from ..lib0 import decoding, encoding
from ..lib0.decoding import Decoder
from ..lib0.encoding import Encoder
from ..obs import global_registry
from ..obs.dist import TraceContext, current_context
from ..sync.session import MESSAGE_YTPU_SESSION
from ..sync.transport import Transport

# envelope kinds 0..7 belong to the session layer (HELLO..BUSY); the
# RPC lane extends the same space so a misrouted frame is skipped, not
# fatal, on either side of the seam
K_RPC_REQ = 8
K_RPC_RSP = 9
K_RPC_EVT = 10

STATUS_OK = 0
STATUS_ERROR = 1
STATUS_BUSY = 2

_HDR = struct.Struct("<I")
DEFAULT_MAX_FRAME = 32 * 1024 * 1024


class RpcError(Exception):
    """The remote handler raised, or the reply was malformed."""


class RpcBusy(RpcError):
    """The remote refused the call under backpressure (PR 10 admission
    verdict or a shard mid-restart): back off ``retry_after`` ticks and
    retransmit — the refusal is loud, never a silent drop."""

    def __init__(self, retry_after: int = 1):
        super().__init__(f"busy: retry after {retry_after} ticks")
        self.retry_after = max(1, int(retry_after))


class RpcClosed(RpcError):
    """The connection died before the reply arrived."""


def b64e(raw: bytes) -> str:
    return base64.b64encode(bytes(raw)).decode("ascii")


def b64d(text: str) -> bytes:
    return base64.b64decode(text)


class _RpcMetrics:
    """``ytpu_cluster_rpc_*`` families on the process-global registry."""

    def __init__(self):
        reg = global_registry()
        self.calls = reg.counter(
            "ytpu_cluster_rpc_calls_total",
            "Cluster RPC calls completed, by method and outcome status",
            labelnames=("method", "status"),
        )
        self.events = reg.counter(
            "ytpu_cluster_rpc_events_total",
            "Cluster RPC event frames (unsolicited pushes), by topic "
            "and direction",
            labelnames=("topic", "dir"),
        )
        self.frames = reg.counter(
            "ytpu_cluster_rpc_frames_total",
            "Cluster RPC frames on the wire, by direction",
            labelnames=("dir",),
        )
        self.unknown = reg.counter(
            "ytpu_cluster_rpc_unknown_total",
            "Cluster RPC frames skipped for an unknown envelope kind "
            "(newer protocol revision tolerance, PR 2 contract)",
        )


_METRICS = None
_METRICS_LOCK = threading.Lock()


def rpc_metrics() -> _RpcMetrics:
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            _METRICS = _RpcMetrics()
        return _METRICS


# -- wire encoding ------------------------------------------------------------


def encode_request(
    corr: int, method: str, payload: dict, ctx: TraceContext | None = None
) -> bytes:
    enc = Encoder()
    encoding.write_var_uint(enc, MESSAGE_YTPU_SESSION)
    encoding.write_var_uint(enc, K_RPC_REQ)
    encoding.write_var_uint(enc, corr)
    encoding.write_var_string(enc, method)
    encoding.write_var_uint8_array(
        enc, json.dumps(payload, separators=(",", ":")).encode("utf-8")
    )
    encoding.write_var_uint8_array(
        enc, ctx.to_bytes() if ctx is not None else b""
    )
    return enc.to_bytes()


def encode_response(corr: int, status: int, payload: dict) -> bytes:
    enc = Encoder()
    encoding.write_var_uint(enc, MESSAGE_YTPU_SESSION)
    encoding.write_var_uint(enc, K_RPC_RSP)
    encoding.write_var_uint(enc, corr)
    encoding.write_var_uint(enc, status)
    encoding.write_var_uint8_array(
        enc, json.dumps(payload, separators=(",", ":")).encode("utf-8")
    )
    return enc.to_bytes()


def encode_event(topic: str, payload: dict) -> bytes:
    enc = Encoder()
    encoding.write_var_uint(enc, MESSAGE_YTPU_SESSION)
    encoding.write_var_uint(enc, K_RPC_EVT)
    encoding.write_var_string(enc, topic)
    encoding.write_var_uint8_array(
        enc, json.dumps(payload, separators=(",", ":")).encode("utf-8")
    )
    return enc.to_bytes()


def decode_frame(frame: bytes):
    """Parse one RPC frame → ``(kind, fields…)`` or ``None`` for any
    frame this reader does not understand (wrong type, session kind, a
    future kind): the caller counts and skips — one unknown frame must
    never kill the connection."""
    try:
        dec = Decoder(frame)
        if decoding.read_var_uint(dec) != MESSAGE_YTPU_SESSION:
            return None
        kind = decoding.read_var_uint(dec)
        if kind == K_RPC_REQ:
            corr = decoding.read_var_uint(dec)
            method = decoding.read_var_string(dec)
            payload = json.loads(
                decoding.read_var_uint8_array(dec).decode("utf-8")
            )
            ctx = None
            if dec.has_content():
                blob = decoding.read_var_uint8_array(dec)
                if blob:
                    ctx = TraceContext.from_bytes(blob)
            return (K_RPC_REQ, corr, method, payload, ctx)
        if kind == K_RPC_RSP:
            corr = decoding.read_var_uint(dec)
            status = decoding.read_var_uint(dec)
            payload = json.loads(
                decoding.read_var_uint8_array(dec).decode("utf-8")
            )
            return (K_RPC_RSP, corr, status, payload)
        if kind == K_RPC_EVT:
            topic = decoding.read_var_string(dec)
            payload = json.loads(
                decoding.read_var_uint8_array(dec).decode("utf-8")
            )
            return (K_RPC_EVT, topic, payload)
        return None
    except Exception:
        return None


# -- framed socket ------------------------------------------------------------


class FrameConn:
    """Length-prefixed (``<I``) frames over one blocking TCP socket.

    ``send`` is lock-serialized (many threads write one socket);
    ``recv`` is single-reader by construction.  This is a leaf lock —
    nothing else is ever taken inside it."""

    def __init__(self, sock: socket.socket, max_frame: int = DEFAULT_MAX_FRAME):
        self.sock = sock
        self.max_frame = max_frame
        self._send_lock = threading.Lock()
        self._closed = False
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass

    def send(self, payload: bytes) -> bool:
        with self._send_lock:
            if self._closed:
                return False
            try:
                self.sock.sendall(_HDR.pack(len(payload)) + bytes(payload))
                return True
            except OSError:
                return False

    def _read_exact(self, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            try:
                chunk = self.sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def recv(self) -> bytes | None:
        """One whole frame, or ``None`` on EOF/error/oversize."""
        hdr = self._read_exact(_HDR.size)
        if hdr is None:
            return None
        (n,) = _HDR.unpack(hdr)
        if n > self.max_frame:
            return None
        if n == 0:
            return b""
        return self._read_exact(n)

    def close(self) -> None:
        with self._send_lock:
            if self._closed:
                return
            self._closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


# -- the threaded session transport ------------------------------------------


class SocketTransport(Transport):
    """A :class:`~yjs_tpu.sync.transport.Transport` over one TCP
    socket with owned rx/tx threads.

    Outbound frames queue through a writer thread (the session's
    ``send`` may fire while the caller holds its doc lock — blocking in
    ``sendall`` there deadlocks two back-pressured peers).  Inbound
    frames are delivered to ``on_frame`` under ``frame_lock`` when one
    is given (the owner's doc/session lock — :class:`SyncSession` is
    not thread-safe).

    ``close()`` is the satellite-1 contract: enqueue a sentinel, JOIN
    the writer (every frame accepted before close reaches the socket
    before the FIN), then close the socket and join the reader.  Frames
    the peer never acked remain in the session outbox — the session
    retransmits them on the next attach; the transport's job is only to
    never drop what it accepted."""

    def __init__(
        self,
        sock: socket.socket,
        frame_lock=None,
        max_frame: int = DEFAULT_MAX_FRAME,
        name: str = "",
    ):
        super().__init__()
        self.conn = FrameConn(sock, max_frame=max_frame)
        self.name = name or f"fd{sock.fileno()}"
        self._frame_lock = frame_lock
        self._outbox: list = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closing = False
        self._rx = threading.Thread(
            target=self._recv_loop, name=f"ytpu-rx-{self.name}", daemon=True
        )
        self._tx = threading.Thread(
            target=self._send_loop, name=f"ytpu-tx-{self.name}", daemon=True
        )

    def start(self) -> None:
        self._rx.start()
        self._tx.start()

    # -- Transport contract --------------------------------------------------

    def send(self, frame: bytes) -> bool:
        with self._wake:
            if self._closing or not self.alive:
                return False
            self._outbox.append(bytes(frame))
            self._wake.notify()
        return True

    # -- threads -------------------------------------------------------------

    def _send_loop(self) -> None:
        while True:
            with self._wake:
                while not self._outbox and not self._closing:
                    self._wake.wait()
                if not self._outbox and self._closing:
                    return
                frame = self._outbox.pop(0)
            if frame is None:
                return
            if not self.conn.send(frame):
                # peer is gone: the reader sees the same failure and
                # emits the single on_close; just stop writing
                return

    def _recv_loop(self) -> None:
        while True:
            frame = self.conn.recv()
            if frame is None:
                break
            cb = self.on_frame
            if cb is None:
                continue
            if self._frame_lock is not None:
                with self._frame_lock:
                    cb(bytes(frame))
            else:
                cb(bytes(frame))
        with self._wake:
            quiet = self._closing
        if not quiet:
            self.close()

    # -- shutdown ------------------------------------------------------------

    def close(self) -> None:
        """Drain-then-join shutdown; safe to call from any thread
        (including the reader itself on EOF) and idempotent."""
        with self._wake:
            if self._closing:
                return
            self._closing = True
            self._wake.notify_all()
        me = threading.current_thread()
        if self._tx.is_alive() and self._tx is not me:
            self._tx.join(timeout=5.0)
        self.conn.close()
        if self._rx.is_alive() and self._rx is not me:
            self._rx.join(timeout=5.0)
        super().close()  # fires on_close exactly once (alive gate)

    @property
    def queued(self) -> int:
        with self._wake:
            return len(self._outbox)

    def join(self, timeout: float = 5.0) -> bool:
        """True when both threads exited (the shutdown pin)."""
        me = threading.current_thread()
        for t in (self._tx, self._rx):
            if t is me or not t.is_alive():
                continue
            t.join(timeout=timeout)
        return not (
            (self._tx.is_alive() and self._tx is not me)
            or (self._rx.is_alive() and self._rx is not me)
        )


# -- client -------------------------------------------------------------------


class RpcClient:
    """One connection to a shard's :class:`RpcServer`.

    ``call()`` is synchronous (correlation-id matched, many in flight
    from different threads); ``on_event`` receives unsolicited pushes
    on the reader thread.  A dead connection fails every waiter with
    :class:`RpcClosed` — callers translate that to BUSY at the session
    seam so peers retransmit instead of losing frames."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 30.0,
        max_frame: int = DEFAULT_MAX_FRAME,
        connect_timeout: float = 10.0,
    ):
        self.addr = (host, int(port))
        self.timeout = timeout
        self.on_event = None  # callable(topic: str, payload: dict)
        self._corr = itertools.count(1)
        self._lock = threading.Lock()
        self._pending: dict = {}  # corr -> [threading.Event, reply|None]
        self._alive = True
        sock = socket.create_connection(self.addr, timeout=connect_timeout)
        sock.settimeout(None)
        self.conn = FrameConn(sock, max_frame=max_frame)
        self._rx = threading.Thread(
            target=self._recv_loop, name=f"ytpu-rpc-{port}", daemon=True
        )
        self._rx.start()

    @property
    def alive(self) -> bool:
        with self._lock:
            return self._alive

    def _recv_loop(self) -> None:
        m = rpc_metrics()
        while True:
            frame = self.conn.recv()
            if frame is None:
                break
            m.frames.labels(dir="rx").inc()
            parsed = decode_frame(frame)
            if parsed is None:
                m.unknown.inc()
                continue
            if parsed[0] == K_RPC_RSP:
                _, corr, status, payload = parsed
                with self._lock:
                    slot = self._pending.get(corr)
                    if slot is not None:
                        slot[1] = (status, payload)
                        slot[0].set()
            elif parsed[0] == K_RPC_EVT:
                _, topic, payload = parsed
                m.events.labels(topic=topic, dir="rx").inc()
                cb = self.on_event
                if cb is not None:
                    try:
                        cb(topic, payload)
                    except Exception:
                        pass  # a bad event handler must not kill rx
        self._fail_all()

    def _fail_all(self) -> None:
        with self._lock:
            self._alive = False
            slots = list(self._pending.values())
            self._pending.clear()
        for slot in slots:
            slot[0].set()

    def call(
        self, method: str, payload: dict | None = None,
        timeout: float | None = None,
    ) -> dict:
        """Invoke ``method`` remotely; returns the reply payload.

        Raises :class:`RpcBusy` on a BUSY status, :class:`RpcError` on
        a remote error, :class:`RpcClosed` on connection loss or
        timeout.  The current :class:`TraceContext`, if any, rides the
        request so the remote seam adopts (not re-mints) it."""
        corr = next(self._corr)
        ev = threading.Event()
        slot = [ev, None]
        with self._lock:
            if not self._alive:
                raise RpcClosed(f"rpc connection to {self.addr} is closed")
            self._pending[corr] = slot
        frame = encode_request(
            corr, method, payload or {}, current_context()
        )
        m = rpc_metrics()
        if not self.conn.send(frame):
            with self._lock:
                self._pending.pop(corr, None)
            self._fail_all()
            m.calls.labels(method=method, status="closed").inc()
            raise RpcClosed(f"send to {self.addr} failed")
        m.frames.labels(dir="tx").inc()
        if not ev.wait(timeout if timeout is not None else self.timeout):
            with self._lock:
                self._pending.pop(corr, None)
            m.calls.labels(method=method, status="timeout").inc()
            raise RpcClosed(f"rpc {method} to {self.addr} timed out")
        with self._lock:
            self._pending.pop(corr, None)
        reply = slot[1]
        if reply is None:
            m.calls.labels(method=method, status="closed").inc()
            raise RpcClosed(f"rpc connection to {self.addr} died")
        status, body = reply
        if status == STATUS_BUSY:
            m.calls.labels(method=method, status="busy").inc()
            raise RpcBusy(int(body.get("retry_after", 1)))
        if status != STATUS_OK:
            m.calls.labels(method=method, status="error").inc()
            raise RpcError(str(body.get("error", "remote error")))
        m.calls.labels(method=method, status="ok").inc()
        return body

    def close(self) -> None:
        self.conn.close()
        self._fail_all()
        if self._rx.is_alive() and self._rx is not threading.current_thread():
            self._rx.join(timeout=5.0)


# -- server -------------------------------------------------------------------


class RpcServer:
    """Accept loop + per-connection reader threads dispatching to one
    handler object (anything with ``handle_rpc_request(method, payload,
    ctx) -> dict``; raise :class:`RpcBusy` for the backpressure lane).

    ``broadcast`` pushes an EVT frame to every live connection — the
    shard's update fan-out to supervisor/gateway subscribers."""

    def __init__(
        self,
        handler,
        host: str = "127.0.0.1",
        port: int = 0,
        max_frame: int = DEFAULT_MAX_FRAME,
    ):
        self.handler = handler
        self.max_frame = max_frame
        self._lock = threading.Lock()
        self._conns: list = []
        self._closing = False
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"ytpu-rpcsrv-{self.port}",
            daemon=True,
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            conn = FrameConn(sock, max_frame=self.max_frame)
            with self._lock:
                if self._closing:
                    conn.close()
                    return
                self._conns.append(conn)
            t = threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                name=f"ytpu-rpcconn-{self.port}",
                daemon=True,
            )
            t.start()

    def _serve_conn(self, conn: FrameConn) -> None:
        m = rpc_metrics()
        while True:
            frame = conn.recv()
            if frame is None:
                break
            m.frames.labels(dir="rx").inc()
            parsed = decode_frame(frame)
            if parsed is None or parsed[0] != K_RPC_REQ:
                m.unknown.inc()
                continue
            _, corr, method, payload, ctx = parsed
            try:
                body = self.handler.handle_rpc_request(method, payload, ctx)
                status = STATUS_OK
                if body is None:
                    body = {}
                m.calls.labels(method=method, status="ok").inc()
            except RpcBusy as e:
                status, body = STATUS_BUSY, {"retry_after": e.retry_after}
                m.calls.labels(method=method, status="busy").inc()
            except Exception as e:
                status = STATUS_ERROR
                body = {"error": f"{type(e).__name__}: {e}"}
                m.calls.labels(method=method, status="error").inc()
            if conn.send(encode_response(corr, status, body)):
                m.frames.labels(dir="tx").inc()
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)
        conn.close()

    def broadcast(self, topic: str, payload: dict) -> int:
        """Push one EVT frame to every live connection; returns the
        number of peers reached."""
        frame = encode_event(topic, payload)
        with self._lock:
            conns = list(self._conns)
        m = rpc_metrics()
        sent = 0
        for conn in conns:
            if conn.send(frame):
                sent += 1
                m.events.labels(topic=topic, dir="tx").inc()
        return sent

    def close(self) -> None:
        with self._lock:
            if self._closing:
                return
            self._closing = True
            conns = list(self._conns)
            self._conns.clear()
        try:
            self._listener.close()
        except OSError:
            pass
        for conn in conns:
            conn.close()
        if self._accept_thread.is_alive():
            self._accept_thread.join(timeout=5.0)
