"""Supervisor: spawn, monitor, restart, and fail over real shard
processes (ISSUE 14).

The supervisor owns the cluster's control plane in the gateway process:

- **Topology** — the PR 6 :class:`HashRing` + versioned
  :class:`RoutingTable` place rooms on shard ids exactly as the
  in-process :class:`FleetRouter` does; the data plane just crosses a
  socket now (``cluster/rpc.py``) instead of a method call.
- **Supervision** — a monitor thread watches every child with
  ``proc.poll()`` (a ``kill -9`` is visible immediately) plus an RPC
  heartbeat probe for hangs.  A dead shard restarts into the SAME WAL
  directory through ``TpuProvider.recover`` — journaled (= acked)
  updates replay, resume floors re-arm — up to
  ``YTPU_CLUSTER_RESTART_MAX`` times; past the budget the shard is
  declared lost and its rooms fail over to the ring-walk successor,
  whose WAL holds the journal-only replica records (PR 8 fan-out over
  RPC) and materializes them by a recover-restart.  Either way the
  routing epoch bumps and ``on_epoch`` fires so the gateway rehomes
  live sessions (digest → targeted repair, not full resync).
- **Recovery report** (satellite 2) — every restart/failover appends a
  structured event: per-shard outcome (``recovered`` / ``fenced`` /
  ``aborted`` / ``failover``), replay counts from the shard's ready
  line, and the ownership resolution (completed/aborted migrations,
  fenced stale claims).  ``recovery_report()`` returns the merged view
  ``ytpu_top --cluster`` renders; ``dump_snapshots()`` writes it next
  to the per-shard metric snapshots for the federated dashboard
  (``obs/federate.py`` file-drop format).

While a shard is down, calls targeting its rooms raise
:class:`RpcBusy` — the gateway session replies with the PR 5/10 BUSY
envelope, the peer keeps the frame in its outbox, and zero acked
updates are lost across the outage window.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

from ..fleet.hashring import HashRing, RoutingTable
from ..lib0 import decoding
from ..lib0.decoding import Decoder
from ..obs import dist as obs_dist
from ..obs import global_registry
from ..obs.expo import registry_snapshot
from ..obs.federate import federate_snapshots
from ..obs.slo import ConvergenceTracker
from ..persistence import KIND_UPDATE
from ..sync import protocol
from .config import ClusterConfig
from .rpc import RpcBusy, RpcClient, RpcClosed, RpcError, b64d, b64e

READY_PREFIX = "YTPU_SHARD_READY "


class _ShardProc:
    """Supervisor-side record of one shard child process."""

    __slots__ = (
        "shard_id", "wal_dir", "proc", "port", "pid", "client",
        "restarts", "state", "recovery", "probe_fails", "admin_port",
    )

    def __init__(self, shard_id: int, wal_dir: str):
        self.shard_id = shard_id
        self.wal_dir = wal_dir
        self.proc = None
        self.port = 0
        self.pid = 0
        self.client = None
        self.restarts = 0
        self.state = "starting"  # starting|live|restarting|lost
        self.recovery = {}
        self.probe_fails = 0  # consecutive unanswered heartbeat probes
        self.admin_port = 0  # the child's introspection-plane port

    def row(self) -> dict:
        return {
            "shard": self.shard_id,
            "state": self.state,
            "pid": self.pid,
            "port": self.port,
            "admin_port": self.admin_port,
            "restarts": self.restarts,
            "outcome": self.recovery.get("outcome", ""),
            "records_applied": self.recovery.get("records_applied", 0),
        }


class _ClusterMetrics:
    """``ytpu_cluster_*`` supervision families (process-global)."""

    def __init__(self):
        reg = global_registry()
        self.restarts = reg.counter(
            "ytpu_cluster_restarts_total",
            "Shard process restarts, by outcome (recovered = WAL "
            "replayed; failover = replica successor promoted)",
            labelnames=("outcome",),
        )
        self.shards_live = reg.gauge(
            "ytpu_cluster_shards_live",
            "Shard processes currently serving RPC",
        )
        self.resolutions = reg.counter(
            "ytpu_cluster_resolutions_total",
            "Per-room ownership resolutions after a restart/failover "
            "(completed/aborted migrations, fenced stale claims)",
            labelnames=("kind",),
        )
        self.unavailable_s = reg.gauge(
            "ytpu_cluster_unavailable_seconds",
            "Length of the last shard outage window (death detected "
            "to serving again)",
        )


class Supervisor:
    """Process-per-shard fleet behind the FleetRouter-shaped facade
    (see module docstring)."""

    def __init__(
        self,
        n_shards: int,
        wal_root: str,
        docs_per_shard: int = 64,
        config: ClusterConfig | None = None,
        backend: str = "cpu",
        shard_tick_s: float = 0.05,
    ):
        self.config = config if config is not None else ClusterConfig()
        self.wal_root = str(wal_root)
        self.docs_per_shard = docs_per_shard
        self.backend = backend
        self.shard_tick_s = shard_tick_s
        self.ring = HashRing(range(n_shards))
        self.table = RoutingTable()
        self.slo = ConvergenceTracker(global_registry())
        self.metrics = _ClusterMetrics()
        self._lock = threading.RLock()
        self._shards: dict[int, _ShardProc] = {
            k: _ShardProc(
                k, os.path.join(self.wal_root, f"shard-{k:03d}")
            )
            for k in range(n_shards)
        }
        self._events: list[dict] = []
        self._stop = threading.Event()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="ytpu-supervisor", daemon=True
        )
        # shard events re-dispatch on a dedicated thread: the RPC rx
        # thread must never block on a subscriber lock, or it starves
        # the responses that subscriber's own call() is waiting for
        self._evt_q: list[tuple[str, bytes]] = []
        self._evt_wake = threading.Condition()
        self._evt_thread = threading.Thread(
            target=self._evt_loop, name="ytpu-supervisor-evt", daemon=True
        )
        self.on_update = None  # callable(guid: str, update: bytes)
        self.on_epoch = None   # callable(epoch: int, shards: list[int])
        # inter-region replication (ISSUE 17): a GeoReplicator attached
        # via attach_geo is ticked by the monitor loop and fed routing-
        # epoch bumps so WAN links rehome when a shard fails over
        self.geo = None
        # the supervisor's own introspection plane (ISSUE 16): serves
        # the FEDERATED cluster view at /metrics.json, so one scrape of
        # the supervisor renders the whole cluster
        self.admin = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Supervisor":
        from ..obs.admin import AdminServer

        with self._lock:
            shards = list(self._shards.values())
        for sp in shards:
            self._spawn(sp)
        self._monitor.start()
        self._evt_thread.start()
        try:
            self.admin = AdminServer(self, role="supervisor").start()
        except OSError:
            self.admin = None
        return self

    def _spawn(self, sp: _ShardProc) -> None:
        """Start (or re-start) one shard child and connect its RPC."""
        os.makedirs(sp.wal_dir, exist_ok=True)
        cmd = [
            sys.executable, "-m", "yjs_tpu.cluster.shard",
            "--id", str(sp.shard_id),
            "--wal-dir", sp.wal_dir,
            "--docs", str(self.docs_per_shard),
            "--host", self.config.host,
            "--port", "0",
            "--backend", self.backend,
            "--tick-s", str(self.shard_tick_s),
            # every child gets an ephemeral admin port: a fixed
            # YTPU_ADMIN_PORT in the supervisor's env must not make N
            # children fight over one socket (YTPU_ADMIN_DISABLED=1
            # still turns the plane off)
            "--admin-port", "0",
        ]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env=env,
        )
        ready = self._read_ready(proc)
        client = RpcClient(
            self.config.host,
            ready["port"],
            timeout=self.config.rpc_timeout_s,
        )
        client.on_event = self._on_shard_event
        with self._lock:
            sp.proc = proc
            sp.port = ready["port"]
            sp.pid = ready["pid"]
            sp.client = client
            sp.recovery = ready.get("recovery") or {}
            sp.admin_port = int(ready.get("admin_port") or 0)
            sp.state = "live"
            sp.probe_fails = 0
            live = sum(
                1 for s in self._shards.values() if s.state == "live"
            )
        self.metrics.shards_live.set(live)

    def _read_ready(self, proc) -> dict:
        """The ready line, under a real deadline, from a thread that
        then owns the child's stdout for its whole lifetime.

        A plain ``readline()`` would block past ``spawn_timeout_s`` on
        a child that starts but never prints (hung import), wedging the
        caller — which during a restart is the monitor thread, i.e. all
        supervision.  And once ready, the pipe still needs a reader:
        stdout chatter from the shard or its libraries would otherwise
        fill the 64KB pipe buffer and block the shard process."""
        slot: list = []
        got = threading.Event()

        def _pump(out=proc.stdout):
            try:
                for line in out:
                    if not got.is_set() and line.startswith(READY_PREFIX):
                        try:
                            slot.append(
                                json.loads(line[len(READY_PREFIX):])
                            )
                        except ValueError:
                            pass
                        got.set()
                    # post-ready lines: drained and discarded
            except (OSError, ValueError):
                pass
            finally:
                got.set()  # EOF before ready: wake the waiter now

        threading.Thread(
            target=_pump,
            name=f"ytpu-shard-stdout-{proc.pid}",
            daemon=True,
        ).start()
        got.wait(self.config.spawn_timeout_s)
        if slot:
            return slot[0]
        if got.is_set():
            # EOF without a ready line: the child is on its way out —
            # reap it so the error carries the real exit code
            try:
                rc = proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                rc = None
            if rc is not None:
                raise RuntimeError(
                    f"shard process exited before ready (rc={rc})"
                )
        proc.kill()
        proc.wait()
        raise RuntimeError("shard ready line timed out")

    def close(self) -> None:
        self._stop.set()
        if self.admin is not None:
            self.admin.close()
            self.admin = None
        with self._evt_wake:
            self._evt_wake.notify_all()
        if self._monitor.is_alive():
            self._monitor.join(timeout=5.0)
        if self._evt_thread.is_alive():
            self._evt_thread.join(timeout=5.0)
        with self._lock:
            shards = list(self._shards.values())
        for sp in shards:
            client, proc = sp.client, sp.proc
            if client is not None and client.alive:
                try:
                    client.call("shutdown", timeout=2.0)
                except RpcError:
                    pass
                client.close()
            if proc is not None and proc.poll() is None:
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    proc.terminate()
                    try:
                        proc.wait(timeout=5.0)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()

    # -- routing -------------------------------------------------------------

    def owner_of(self, guid: str) -> int:
        with self._lock:
            k = self.table.lookup(guid)
            if k is None:
                k = self.ring.owner(guid)
                self.table.assign(guid, k)
            return k

    def replica_of(self, guid: str) -> int | None:
        """Ring-walk successor after the owner (PR 8 placement)."""
        with self._lock:
            owner = self.owner_of(guid)
            for k in self.ring.walk(guid):
                if k != owner:
                    return k
            return None

    @property
    def epoch(self) -> int:
        with self._lock:
            return self.table.epoch

    def _client_of(self, k: int):
        with self._lock:
            sp = self._shards.get(k)
            if sp is None:
                raise RpcError(f"no shard {k}")
            if sp.state != "live" or sp.client is None:
                raise RpcBusy(self.config.busy_retry_ticks)
            return sp.client

    def _call(self, k: int, method: str, payload: dict) -> dict:
        """One routed RPC; a dead/mid-restart shard surfaces as BUSY so
        session peers hold and retransmit instead of losing frames."""
        client = self._client_of(k)
        try:
            return client.call(method, payload)
        except RpcBusy:
            raise
        except (RpcClosed, RpcError):
            # connection died mid-call (the kill window): the monitor
            # restarts the shard; meanwhile the room is backpressured
            raise RpcBusy(self.config.busy_retry_ticks)

    # -- data-plane ingress seams -------------------------------------------

    def receive_update(self, guid: str, update: bytes, v2: bool = False,
                       internal: bool = False) -> bool:
        """Cluster ingress for one room update: adopts-or-mints the
        trace (PR 11), stamps the gateway-side convergence SLO (the e2e
        number ``bench_cluster`` reports), routes to the owner shard
        over RPC, and fans a replica record to the ring successor
        (PR 8 semantics over sockets)."""
        ctx = obs_dist.current_context() or obs_dist.mint_for_update(
            bytes(update)
        )
        with obs_dist.use_context(ctx):
            key = self.slo.receive(update, v2=v2, guid=guid, trace=ctx)
            k = self.owner_of(guid)
            try:
                body = self._call(k, "update", {
                    "guid": guid,
                    "update": b64e(update),
                    "v2": bool(v2),
                    "internal": bool(internal),
                })
            except RpcBusy:
                self.slo.rejected(key)
                raise
            accepted = bool(body.get("accepted"))
            if accepted:
                self.slo.integrated(key)
                self._fan_replica(guid, update, v2)
            else:
                self.slo.rejected(key)
            return accepted

    def handle_sync_message(self, guid: str, message: bytes) -> bytes | None:
        """Cluster ingress for one v13.4.9 sync frame: update/step-2
        payloads stamp the gateway-side SLO, then the whole frame
        forwards to the owner shard's own ``handle_sync_message`` seam
        (validation, WAL, admission — unchanged semantics)."""
        ctx = obs_dist.current_context()
        key = None
        inner = self._frame_update_payload(message)
        if inner is not None:
            if ctx is None:
                ctx = obs_dist.mint_for_update(inner)
            key = self.slo.receive(inner, guid=guid, trace=ctx)
        with obs_dist.use_context(ctx):
            k = self.owner_of(guid)
            try:
                body = self._call(k, "sync", {
                    "guid": guid, "frame": b64e(message),
                })
            except RpcBusy:
                if key is not None:
                    self.slo.rejected(key)
                raise
            if key is not None:
                self.slo.integrated(key)
            if inner is not None:
                self._fan_replica(guid, inner, False)
            reply = body.get("reply")
            return b64d(reply) if reply else None

    @staticmethod
    def _frame_update_payload(message: bytes) -> bytes | None:
        """The update payload of a step-2/update sync frame (the SLO
        unit), or ``None`` for step-1/envelope/unknown frames."""
        try:
            dec = Decoder(bytes(message))
            t = decoding.read_var_uint(dec)
            if t in (
                protocol.MESSAGE_YJS_SYNC_STEP_2,
                protocol.MESSAGE_YJS_UPDATE,
            ):
                return decoding.read_var_uint8_array(dec)
        except Exception:
            return None
        return None

    def _fan_replica(self, guid: str, update: bytes, v2: bool) -> None:
        """Journal one replica record on the ring successor's WAL
        (best-effort: replication is a durability bonus on top of the
        owner's own WAL, never a request blocker)."""
        r = self.replica_of(guid)
        if r is None:
            return
        try:
            self._call(r, "repl_record", {
                "kind": KIND_UPDATE,
                "guid": guid,
                "payload": b64e(update),
                "v2": bool(v2),
            })
        except RpcError:
            pass

    # -- read/session facade -------------------------------------------------

    def state_vector_bytes(self, guid: str) -> bytes:
        return b64d(self._call(
            self.owner_of(guid), "sv", {"guid": guid}
        )["sv"])

    def diff_update(self, guid: str, sv: bytes | None) -> bytes:
        return b64d(self._call(self.owner_of(guid), "diff", {
            "guid": guid, "sv": b64e(sv) if sv else None,
        })["update"])

    def text(self, guid: str) -> str:
        return self._call(
            self.owner_of(guid), "text", {"guid": guid}
        )["text"]

    def flush(self, guid: str | None = None) -> None:
        if guid is not None:
            self._call(self.owner_of(guid), "flush", {})
            return
        with self._lock:
            ids = [
                sp.shard_id for sp in self._shards.values()
                if sp.state == "live"
            ]
        for k in ids:
            try:
                self._call(k, "flush", {})
            except RpcError:
                pass

    def journal_ack(self, guid: str, peer: str, sid: int, seq: int) -> None:
        """Durable resume floor on the owner's WAL (best-effort: a
        missed floor costs a resume, never data)."""
        try:
            self._call(self.owner_of(guid), "journal_ack", {
                "guid": guid, "peer": peer, "sid": sid, "seq": seq,
            })
        except RpcError:
            pass

    def _on_shard_event(self, topic: str, payload: dict) -> None:
        if topic != "update":
            return
        try:
            item = (payload["guid"], b64d(payload["update"]))
        except (KeyError, ValueError):
            return
        with self._evt_wake:
            self._evt_q.append(item)
            self._evt_wake.notify()

    def _evt_loop(self) -> None:
        while True:
            with self._evt_wake:
                while not self._evt_q and not self._stop.is_set():
                    self._evt_wake.wait()
                if not self._evt_q and self._stop.is_set():
                    return
                batch, self._evt_q[:] = list(self._evt_q), []
            cb = self.on_update
            if cb is None:
                continue
            for guid, update in batch:
                try:
                    cb(guid, update)
                except Exception:
                    pass  # a bad subscriber must not stall fan-out

    # -- geo replication (ISSUE 17) ------------------------------------------

    def attach_geo(self, replicator) -> None:
        """Join this cluster into a geo mesh: the replicator (a
        :class:`yjs_tpu.geo.GeoReplicator` built over this supervisor
        facade) is driven from the monitor loop — one geo tick per
        heartbeat interval — and fencing epochs follow routing-epoch
        bumps via the replicator's own ``epoch`` poll."""
        self.geo = replicator

    def _geo_tick(self) -> None:
        rep = self.geo
        if rep is None:
            return
        try:
            rep.tick()
        except Exception:
            pass  # a WAN-side fault must never stall shard supervision

    # -- supervision ---------------------------------------------------------

    def _monitor_loop(self) -> None:
        next_snap = time.monotonic() + self.config.snapshot_s
        # the hang lane: a shard whose process is alive and socket open
        # but which stopped serving (e.g. deadlocked under its provider
        # lock) is invisible to poll()/alive — only an unanswered
        # heartbeat RPC convicts it.  Probes run at a coarser cadence
        # than the poll loop; each one blocks this thread for at most
        # probe_timeout_s.
        probe_every = max(
            self.config.heartbeat_s, self.config.probe_timeout_s / 2.0
        )
        next_probe: dict[int, float] = {}
        while not self._stop.wait(self.config.heartbeat_s):
            self._geo_tick()
            if self.config.snapshot_dir and time.monotonic() >= next_snap:
                next_snap = time.monotonic() + self.config.snapshot_s
                try:
                    self.dump_snapshots()
                except (OSError, ValueError):
                    pass
            with self._lock:
                shards = list(self._shards.values())
            for sp in shards:
                with self._lock:
                    live = sp.state == "live"
                    proc = sp.proc
                if not live or proc is None:
                    continue
                dead = proc.poll() is not None
                if not dead:
                    client = sp.client
                    dead = client is None or not client.alive
                if (
                    not dead
                    and time.monotonic()
                    >= next_probe.get(sp.shard_id, 0.0)
                ):
                    next_probe[sp.shard_id] = (
                        time.monotonic() + probe_every
                    )
                    dead = not self._probe(sp)
                if dead and not self._stop.is_set():
                    self._handle_death(sp)

    def _probe(self, sp: _ShardProc) -> bool:
        """One heartbeat RPC against a live-looking shard; False means
        hung.  Two consecutive unanswered probes (timeout or connection
        loss) convict — a remote *error* is still an answer, and one
        slow response (checkpoint, first-flush compile) gets a second
        chance before a restart is forced."""
        client = sp.client
        if client is None:
            return False
        try:
            client.call("heartbeat", timeout=self.config.probe_timeout_s)
        except RpcClosed:
            sp.probe_fails += 1
            return sp.probe_fails < 2
        except RpcError:
            pass
        sp.probe_fails = 0
        return True

    def _handle_death(self, sp: _ShardProc) -> None:
        """Restart through recover, or fail over past the budget."""
        t0 = time.monotonic()
        with self._lock:
            if sp.state != "live":
                return
            sp.state = "restarting"
            restarts = sp.restarts = sp.restarts + 1
            budget_left = restarts <= self.config.restart_max
        old_client = sp.client
        if old_client is not None:
            old_client.close()
        if budget_left:
            time.sleep(self.config.restart_backoff_s)
            try:
                self._spawn(sp)
            except (RpcError, RuntimeError, OSError):
                with self._lock:
                    sp.state = "live"  # re-enter death handling
                return
            resolution = self._resolve_after_restart(sp)
            self.metrics.restarts.labels(outcome="recovered").inc()
            event = {
                "event": "restart",
                "shard": sp.shard_id,
                "outcome": "recovered",
                "restarts": restarts,
                "recovery": sp.recovery,
                "resolution": resolution,
            }
        else:
            event = self._fail_over(sp)
        dt = time.monotonic() - t0
        self.metrics.unavailable_s.set(dt)
        with self._lock:
            epoch = self.table.bump()
            event["epoch"] = epoch
            event["unavailable_s"] = round(dt, 4)
            self._events.append(event)
        # publish the post-resolution epoch to every live shard: a
        # fenced restartee saw epoch E in its demotion frames and is
        # reporting /readyz 503 until this push tells it E+1 is current
        # (ISSUE 16 fencing-epoch readiness)
        self._broadcast_epoch(epoch)
        cb = self.on_epoch
        if cb is not None:
            try:
                cb(epoch, [sp.shard_id])
            except Exception:
                pass

    def _broadcast_epoch(self, epoch: int) -> None:
        with self._lock:
            ids = [
                sp.shard_id for sp in self._shards.values()
                if sp.state == "live"
            ]
        for k in ids:
            try:
                self._call(k, "epoch", {"epoch": int(epoch)})
            except RpcError:
                pass  # a shard mid-restart learns it on the next bump

    def _resolve_after_restart(self, sp: _ShardProc) -> dict:
        """Mirror ``FleetRouter.recover``'s ownership resolution across
        processes: complete or abort the restarted shard's pending
        migration intents, and fence any room claim the routing table
        reassigned (at a higher epoch) during the outage."""
        out = {"completed": 0, "aborted": 0, "fenced": 0}
        pending = list(sp.recovery.get("migrations_pending") or [])
        for guid in pending:
            with self._lock:
                dst = self.table.lookup(guid)
            if dst is None or dst == sp.shard_id:
                out["aborted"] += 1
                self.metrics.resolutions.labels(kind="aborted").inc()
                continue
            try:
                dst_guids = self._call(dst, "guids", {})["guids"]
                if guid in dst_guids:
                    final = b64d(self._call(
                        sp.shard_id, "release", {"guid": guid}
                    )["update"])
                    self._call(dst, "update", {
                        "guid": guid, "update": b64e(final),
                        "internal": True,
                    })
                    out["completed"] += 1
                    self.metrics.resolutions.labels(
                        kind="completed"
                    ).inc()
                else:
                    out["aborted"] += 1
                    self.metrics.resolutions.labels(kind="aborted").inc()
            except RpcError:
                out["aborted"] += 1
                self.metrics.resolutions.labels(kind="aborted").inc()
        # fencing: rooms this shard still holds but the table moved to
        # another owner while it was dead (failover won the race) —
        # fold the stale copy into the new owner and release it
        try:
            held = self._call(sp.shard_id, "guids", {})["guids"]
        except RpcError:
            held = []
        for guid in held:
            with self._lock:
                owner = self.table.lookup(guid)
            if owner is None or owner == sp.shard_id:
                continue
            try:
                final = b64d(self._call(
                    sp.shard_id, "release", {"guid": guid}
                )["update"])
                self._call(owner, "update", {
                    "guid": guid, "update": b64e(final), "internal": True,
                })
                self._call(sp.shard_id, "journal_repl_role", {
                    "guid": guid, "role": "replica",
                    "epoch": self.epoch, "primary": owner,
                })
                out["fenced"] += 1
                self.metrics.resolutions.labels(kind="fenced").inc()
            except RpcError:
                pass
        return out

    def _fail_over(self, sp: _ShardProc) -> dict:
        """Permanent shard loss: promote the ring successor by a
        recover-restart (its WAL materializes the journal-only replica
        records), reassign the dead shard's rooms, and fence the loser
        out of the ring."""
        with self._lock:
            self.ring.remove(sp.shard_id)
            sp.state = "lost"
            moved = self.table.docs_on(sp.shard_id)
            successors = {
                guid: next(iter(self.ring.walk(guid)), None)
                for guid in moved
            }
            live = sum(
                1 for s in self._shards.values() if s.state == "live"
            )
        self.metrics.shards_live.set(live)
        promote_on = sorted(
            {k for k in successors.values() if k is not None}
        )
        for k in promote_on:
            with self._lock:
                succ = self._shards.get(k)
                ok = succ is not None and succ.state == "live"
            if not ok:
                continue
            # graceful recover-restart of the successor: replica
            # KIND_UPDATE records replay into its engine (promotion by
            # materialization)
            client = succ.client
            try:
                if client is not None:
                    client.call("shutdown", timeout=2.0)
            except RpcError:
                pass
            if client is not None:
                client.close()
            proc = succ.proc
            if proc is not None:
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    proc.terminate()
                    proc.wait(timeout=5.0)
            self._spawn(succ)
        promoted = 0
        with self._lock:
            epoch = self.table.epoch + 1
        for guid, k in sorted(successors.items()):
            if k is None:
                continue
            with self._lock:
                self.table.assign(guid, k)
            try:
                self._call(k, "journal_repl_role", {
                    "guid": guid, "role": "primary", "epoch": epoch,
                })
                promoted += 1
            except RpcError:
                pass
        self.metrics.restarts.labels(outcome="failover").inc()
        return {
            "event": "failover",
            "shard": sp.shard_id,
            "outcome": "failover",
            "restarts": sp.restarts,
            "promoted": promoted,
            "successors": {g: k for g, k in successors.items()},
            "recovery": sp.recovery,
            "resolution": {"completed": 0, "aborted": 0, "fenced": 0},
        }

    # -- observability (satellite 2 + federation) ---------------------------

    def heartbeat(self, k: int) -> dict:
        return self._call(k, "heartbeat", {})

    def recovery_report(self) -> dict:
        """One structured per-shard view of everything supervision did
        (the shape ``ytpu_top --cluster`` renders and
        ``FleetRouter.recovery_report`` mirrors in-process)."""
        with self._lock:
            rows = [
                self._shards[k].row() for k in sorted(self._shards)
            ]
            events = list(self._events)
            epoch = self.table.epoch
        outcomes = {"recovered": 0, "failover": 0}
        totals = {"completed": 0, "aborted": 0, "fenced": 0}
        for ev in events:
            outcomes[ev["outcome"]] = outcomes.get(ev["outcome"], 0) + 1
            for kind, n in (ev.get("resolution") or {}).items():
                totals[kind] = totals.get(kind, 0) + n
        return {
            "kind": "cluster",
            "epoch": epoch,
            "shards": rows,
            "events": events,
            "outcomes": outcomes,
            "resolution": totals,
        }

    def scrape_sources(self) -> list[dict]:
        """One federation source per shard, scraped over the admin
        plane's HTTP ``/metrics.json`` (ISSUE 16) with the RPC
        ``metrics`` call as fallback for admin-disabled children.  A
        dead/hung shard yields a stale-marked empty source under the
        per-target ``scrape_timeout_s`` — partial failure renders as a
        blank row, never a federation error."""
        from ..obs.federate import scrape_endpoints

        with self._lock:
            targets = [
                (k, self._shards[k].admin_port, self._shards[k].state)
                for k in sorted(self._shards)
            ]
        sources = []
        for k, admin_port, state in targets:
            label = f"shard-{k:03d}"
            if admin_port:
                src = scrape_endpoints(
                    [f"http://{self.config.host}:{admin_port}"],
                    timeout_s=self.config.scrape_timeout_s,
                )[0]
                src["label"] = label
                src["role"] = src["role"] or "primary"
            else:
                snap: dict = {}
                stale = True
                if state == "live":
                    try:
                        snap = self._call(k, "metrics", {})["snapshot"]
                        stale = False
                    except RpcError:
                        snap = {}
                src = {
                    "label": label,
                    "role": "primary",
                    "snapshot": snap,
                    "stale": stale,
                }
            sources.append(src)
        return sources

    def metrics_snapshot(self) -> dict:
        """Federated view over every shard's registry (HTTP scrape,
        RPC fallback) plus the supervisor's own process-global
        families."""
        return federate_snapshots(
            self.scrape_sources(),
            global_snapshot=registry_snapshot(global_registry()),
        )

    def dump_snapshots(
        self, path: str | None = None, sources: list[dict] | None = None
    ) -> str:
        """Write per-shard ``shard-K.json`` metric snapshots plus the
        ``cluster.json`` recovery report into the snapshot dir — the
        ``obs/federate.py`` file-drop format ``ytpu_top <dir>`` tails
        and the HTTP-scrape mode is byte-equivalent with (both paths
        dump/serve the same shard payload).  ``sources`` reuses an
        existing scrape; stale sources keep the last good file."""
        out = path or self.config.snapshot_dir
        if not out:
            raise ValueError(
                "no snapshot dir (YTPU_CLUSTER_SNAPSHOT_DIR or path=)"
            )
        os.makedirs(out, exist_ok=True)
        if sources is None:
            sources = self.scrape_sources()
        for src in sources:
            if src.get("stale"):
                continue
            name = str(src["label"])
            tmp = os.path.join(out, f".{name}.json.tmp")
            with open(tmp, "w") as f:
                json.dump(src["snapshot"], f)
            os.replace(tmp, os.path.join(out, f"{name}.json"))
        report = self.recovery_report()
        tmp = os.path.join(out, ".cluster.json.tmp")
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1)
        os.replace(tmp, os.path.join(out, "cluster.json"))
        return out

    # -- admin-plane target (ISSUE 16) ---------------------------------------

    def admin_urls(self) -> dict[str, str]:
        """Every process's admin base URL: the supervisor's own plus
        one per live shard child (the smoke harness curls them all)."""
        urls: dict[str, str] = {}
        if self.admin is not None and self.admin.port:
            urls["supervisor"] = self.admin.url
        with self._lock:
            for k in sorted(self._shards):
                sp = self._shards[k]
                if sp.admin_port:
                    urls[f"shard-{k:03d}"] = (
                        f"http://{self.config.host}:{sp.admin_port}"
                    )
        return urls

    def tsdb_query(self, params: dict) -> dict:
        """Federated ``/query`` (ISSUE 19): fan the range query out to
        every live shard child's embedded TSDB over the admin plane and
        merge the per-shard points into one cross-fleet series (dead
        shards contribute a stale-marked empty result, never an
        error).  ``?agg=`` doubles as the cross-shard combiner —
        ``sum`` for fleet totals, ``avg``/``min``/``max`` for spread."""
        from ..obs.tsdb import merge_points, query_endpoints, tsdb

        urls = {
            label: url
            for label, url in self.admin_urls().items()
            if label != "supervisor"
        }
        agg = params.get("agg") or "avg"
        per_shard = query_endpoints(
            urls, params, timeout_s=self.config.scrape_timeout_s
        )
        merged = merge_points(
            {k: v.get("points", []) for k, v in per_shard.items()},
            agg=agg,
            bucket_s=max(1.0, tsdb().config.interval_s),
        )
        return {
            "name": params.get("name", ""),
            "labels": params.get("labels", "") or "",
            "agg": agg,
            "tier": params.get("tier") or "auto",
            "federated": True,
            "shards": sorted(urls),
            "stale": sorted(
                k for k, v in per_shard.items() if v.get("stale")
            ),
            "points": merged,
        }

    def statusz(self) -> dict:
        report = self.recovery_report()
        return {
            "role": "supervisor",
            "epoch": report["epoch"],
            "shards": report["shards"],
            "outcomes": report["outcomes"],
            "resolution": report["resolution"],
            "events": len(report["events"]),
            "geo": None if self.geo is None else self.geo.snapshot(),
        }

    def readiness(self) -> dict:
        """``/readyz`` for the control plane: every shard settled (live
        or failed-over) and at least one serving — a shard mid-restart
        flips the cluster not-ready until recovery resolves."""
        with self._lock:
            states = [sp.state for sp in self._shards.values()]
        live = sum(1 for s in states if s == "live")
        settled = all(s in ("live", "lost") for s in states)
        return {
            "ready": live > 0 and settled,
            "checks": {
                "live_shards": live,
                "all_settled": settled,
                "states": {
                    s: states.count(s) for s in sorted(set(states))
                },
            },
        }
