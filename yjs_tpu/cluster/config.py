"""Knobs for the process-native cluster (ISSUE 14).

Two env families, both documented in README's "Cluster" section and
cross-checked by ytpu-lint's knob-drift checker:

- ``YTPU_CLUSTER_*`` — supervisor/shard process topology: bind host,
  heartbeat cadence, probe timeout, restart budget and backoff, and the
  federated snapshot directory the metrics/trace view writes into.
- ``YTPU_GATEWAY_*`` — the y-websocket-compatible front door: bind
  host/port, maximum accepted frame, session tick cadence, and the
  awareness passthrough toggle.

Both configs are constructor-overridable (tests pin values; the env is
the operator surface), mirroring ``SessionConfig`` / ``FleetConfig``.
"""

from __future__ import annotations

import os


def _env_int(name: str, default: int, lo: int = 0) -> int:
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        return default
    return max(lo, v)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class ClusterConfig:
    """Supervisor-side topology knobs (env-derived defaults)."""

    __slots__ = (
        "host",
        "heartbeat_s",
        "probe_timeout_s",
        "restart_max",
        "restart_backoff_s",
        "snapshot_dir",
        "snapshot_s",
        "spawn_timeout_s",
        "rpc_timeout_s",
        "busy_retry_ticks",
        "scrape_timeout_s",
    )

    def __init__(
        self,
        host: str | None = None,
        heartbeat_s: float | None = None,
        probe_timeout_s: float | None = None,
        restart_max: int | None = None,
        restart_backoff_s: float | None = None,
        snapshot_dir: str | None = None,
        snapshot_s: float | None = None,
        spawn_timeout_s: float | None = None,
        rpc_timeout_s: float | None = None,
        busy_retry_ticks: int | None = None,
        scrape_timeout_s: float | None = None,
    ):
        self.host = (
            host
            if host is not None
            else os.environ.get("YTPU_CLUSTER_HOST", "127.0.0.1")
        )
        self.heartbeat_s = (
            heartbeat_s
            if heartbeat_s is not None
            else _env_float("YTPU_CLUSTER_HEARTBEAT_S", 0.25)
        )
        self.probe_timeout_s = (
            probe_timeout_s
            if probe_timeout_s is not None
            else _env_float("YTPU_CLUSTER_PROBE_TIMEOUT_S", 5.0)
        )
        self.restart_max = (
            restart_max
            if restart_max is not None
            else _env_int("YTPU_CLUSTER_RESTART_MAX", 2)
        )
        self.restart_backoff_s = (
            restart_backoff_s
            if restart_backoff_s is not None
            else _env_float("YTPU_CLUSTER_RESTART_BACKOFF_S", 0.1)
        )
        self.snapshot_dir = (
            snapshot_dir
            if snapshot_dir is not None
            else os.environ.get("YTPU_CLUSTER_SNAPSHOT_DIR", "")
        )
        self.snapshot_s = (
            snapshot_s
            if snapshot_s is not None
            else _env_float("YTPU_CLUSTER_SNAPSHOT_S", 2.0)
        )
        self.spawn_timeout_s = (
            spawn_timeout_s
            if spawn_timeout_s is not None
            else _env_float("YTPU_CLUSTER_SPAWN_TIMEOUT_S", 60.0)
        )
        self.rpc_timeout_s = (
            rpc_timeout_s
            if rpc_timeout_s is not None
            else _env_float("YTPU_CLUSTER_RPC_TIMEOUT_S", 30.0)
        )
        # the BUSY retry-after (in session ticks) a gateway session is
        # told while its room's shard is down/restarting — the peer
        # keeps the frame in its outbox, so nothing acked is ever lost
        self.busy_retry_ticks = (
            busy_retry_ticks
            if busy_retry_ticks is not None
            else _env_int("YTPU_CLUSTER_BUSY_RETRY_TICKS", 8, lo=1)
        )
        # per-target deadline for one HTTP admin-plane scrape during
        # metrics federation (ISSUE 16): a hung shard costs at most
        # this long and renders as a stale row, never an error
        self.scrape_timeout_s = (
            scrape_timeout_s
            if scrape_timeout_s is not None
            else _env_float("YTPU_CLUSTER_SCRAPE_TIMEOUT_S", 2.0)
        )


class GatewayConfig:
    """Front-door knobs (env-derived defaults)."""

    __slots__ = (
        "host",
        "port",
        "max_frame",
        "tick_s",
        "awareness",
        "send_timeout_s",
    )

    def __init__(
        self,
        host: str | None = None,
        port: int | None = None,
        max_frame: int | None = None,
        tick_s: float | None = None,
        awareness: bool | None = None,
        send_timeout_s: float | None = None,
    ):
        self.host = (
            host
            if host is not None
            else os.environ.get("YTPU_GATEWAY_HOST", "127.0.0.1")
        )
        self.port = (
            port
            if port is not None
            else _env_int("YTPU_GATEWAY_PORT", 0)
        )
        self.max_frame = (
            max_frame
            if max_frame is not None
            else _env_int("YTPU_GATEWAY_MAX_FRAME", 32 * 1024 * 1024, lo=1)
        )
        self.tick_s = (
            tick_s
            if tick_s is not None
            else _env_float("YTPU_GATEWAY_TICK_S", 0.05)
        )
        self.awareness = (
            awareness
            if awareness is not None
            else _env_int("YTPU_GATEWAY_AWARENESS", 1) != 0
        )
        # bound on a blocking ws send to one client (SO_SNDTIMEO): a
        # peer that stops reading is severed instead of stalling the
        # fan-out thread forever.  0 disables the bound.
        self.send_timeout_s = (
            send_timeout_s
            if send_timeout_s is not None
            else _env_float("YTPU_GATEWAY_SEND_TIMEOUT_S", 15.0)
        )
