"""The y-tpu Provider: the gating boundary of BASELINE.json's north star.

A Provider owns a fleet of documents (think: a collaboration server holding
thousands of rooms).  Pending binary updates are marshalled per doc and
integrated in one batched device step at ``flush()``; docs whose traffic
falls outside the device path's scope are transparently served by the CPU
reference core (the same wire bytes, the same sync contract — reference
README.md:101-137 describes the provider seam this implements).

Speaks the y-protocols sync framing via :mod:`yjs_tpu.sync.protocol`:
step 1 (state vector) / step 2 (diff update) / incremental updates.
"""

from __future__ import annotations

import base64
import json
import os
import time
from collections import deque

from .admission import AdmissionController, AdmissionRejected
from .lib0.decoding import Decoder
from .lib0.encoding import Encoder
from .lib0 import decoding, encoding
from .obs import dist as obs_dist
from .obs.admin import maybe_start_admin
from .obs.cost import CostLedger
from .obs.slo import ConvergenceTracker
from .obs.tsdb import maybe_attach_tsdb
from .ops.engine import BatchEngine
from .persistence import (
    KIND_ACK,
    KIND_ADM,
    KIND_DLQ,
    KIND_GEO,
    KIND_MIGRATE,
    KIND_RELEASE,
    KIND_REPL,
    KIND_UPDATE,
    WalConfig,
    WalMetrics,
    WriteAheadLog,
)
from .sync import protocol
from .sync.session import (
    SessionConfig,
    SessionMetrics,
    SyncSession,
    encode_busy,
)
from .tiering import TierManager
from .updates import validate_update


class ProviderFullError(ValueError):
    """Raised when every engine slot is taken and a new guid arrives.

    Subclasses ``ValueError`` so pre-ISSUE-3 callers catching the old
    bare ``ValueError("provider is full")`` keep working; new callers
    can catch the typed error and :meth:`TpuProvider.release_doc` a
    cold room to free a slot."""


class _ProviderSessionHost:
    """Session host over one provider room (the shape
    :class:`yjs_tpu.sync.session.SyncSession` drives): state vectors
    and diffs are served by the engine flush-first so they reflect
    pending traffic, and inbound frames route through
    ``handle_sync_message`` — the validation / WAL / SLO / dead-letter
    seam a session must not bypass."""

    __slots__ = ("provider", "guid", "peer")

    def __init__(self, provider: "TpuProvider", guid: str, peer: str):
        self.provider = provider
        self.guid = guid
        self.peer = peer

    def state_vector(self) -> bytes:
        p = self.provider
        p.flush()
        return p.engine.encode_state_vector(p.doc_id(self.guid))

    def diff_update(self, sv: bytes | None) -> bytes:
        return self.provider.encode_state_as_update(self.guid, sv)

    def apply_update(self, update: bytes) -> None:
        self.provider.receive_update(self.guid, update)

    def handle_frame(self, frame: bytes) -> bytes | None:
        p = self.provider
        p.cost.session_frame(self.guid)
        try:
            return p.handle_sync_message(self.guid, frame)
        except ProviderFullError as e:
            # Capacity exhaustion is an overload condition, not a
            # transport fault: record it for the admission controller
            # (which demotes cold docs to make headroom), keep the bytes
            # in the DLQ with a typed reason, and push back on the peer
            # instead of letting the error escape into its pump loop.
            p.admission.note_full("provider")
            p.engine._dead_letter(
                -1, bytes(frame), False,
                f"admission-full: {e} (peer {self.peer})",
            )
            return encode_busy(p.admission.retry_after)

    def dead_letter(self, payload: bytes, reason: str) -> None:
        p = self.provider
        try:
            doc = p.doc_id(self.guid)
        except ProviderFullError:
            p.admission.note_full("provider")
            doc = -1
        p.engine._dead_letter(
            doc, bytes(payload), False,
            f"{reason} (peer {self.peer})",
        )

    def journal_ack(self, sid: int, seq: int) -> None:
        self.provider.journal_session_ack(self.guid, self.peer, sid, seq)


class FlushTickController:
    """Adaptive flush batch window (ISSUE 12): how long a provider lets
    traffic coalesce before the next :meth:`TpuProvider.flush_tick`
    actually flushes.

    Inputs, per tick:

    - the SLO burn-rate verdict (ISSUE 4, ``ConvergenceTracker.state()``):
      any non-"ok" state snaps the window to the minimum — visibility
      latency is the thing being violated, so stop batching;
    - the brownout level (ISSUE 10) via
      ``AdmissionController.flush_interval_scale`` — the window is
      multiplied by the brownout scale so an overloaded shard coalesces
      flushes instead of thrashing the device, and ``force_coalesce``
      pins the window to the maximum outright;
    - idleness: a tick that found nothing dirty widens the window
      geometrically (x ``YTPU_FLUSH_TICK_GROW``) up to the maximum —
      bigger batches amortize dispatch better when nobody is waiting.

    Knobs: ``YTPU_FLUSH_TICK_MIN_MS`` (default 2), ``YTPU_FLUSH_TICK_MAX_MS``
    (default 64), ``YTPU_FLUSH_TICK_GROW`` (default 2).  Explicit
    :meth:`TpuProvider.flush` calls bypass the window entirely."""

    def __init__(self, registry=None):
        def _env(name, default):
            try:
                return float(os.environ.get(name, default))
            except ValueError:
                return float(default)

        self.min_ms = max(0.0, _env("YTPU_FLUSH_TICK_MIN_MS", 2.0))
        self.max_ms = max(self.min_ms, _env("YTPU_FLUSH_TICK_MAX_MS", 64.0))
        self.grow = max(1.0, _env("YTPU_FLUSH_TICK_GROW", 2.0))
        # current base window; starts tight so a fresh provider is
        # responsive and only widens by observing idleness
        self.window_ms = self.min_ms
        # applied windows (ms) — bench_flush reads p50/p99 from here
        self.windows: deque = deque(maxlen=512)
        self._last: float | None = None
        self._g_window = self._h_window = None
        if registry is not None:
            self._g_window = registry.gauge(
                "ytpu_flush_tick_window_ms",
                "Current adaptive flush batch window",
            )
            self._h_window = registry.histogram(
                "ytpu_flush_tick_window_seconds",
                "Adaptive flush batch windows as applied per tick",
                unit="s",
            )

    def window(self, slo_state: str, scale: float = 1.0,
               coalesce: bool = False) -> float:
        """Effective window (ms) for this tick from the SLO verdict +
        brownout inputs; mutates the base window on a burn verdict."""
        if slo_state != "ok":
            self.window_ms = self.min_ms
        w = self.max_ms if coalesce else self.window_ms
        return w * max(1.0, scale)

    def due(self, now: float, window_ms: float) -> bool:
        return self._last is None or (now - self._last) * 1000.0 >= window_ms

    def applied(self, now: float, window_ms: float, busy: bool) -> None:
        """Book one elapsed tick; idle ticks widen the base window."""
        self._last = now
        self.windows.append(window_ms)
        if self._g_window is not None:
            self._g_window.set(window_ms)
            self._h_window.observe(window_ms / 1000.0)
        if not busy:
            self.window_ms = min(
                self.max_ms, max(self.window_ms, self.min_ms, 0.001) * self.grow
            )

    def percentiles(self) -> dict:
        """p50/p99 of recently applied windows (ms) — the bench surface."""
        if not self.windows:
            return {"p50_ms": 0.0, "p99_ms": 0.0}
        xs = sorted(self.windows)
        return {
            "p50_ms": xs[len(xs) // 2],
            "p99_ms": xs[min(len(xs) - 1, int(len(xs) * 0.99))],
        }


class TpuProvider:
    """Batched multi-doc provider backed by :class:`BatchEngine`.

    ``backend`` is the selector the north star puts at the Provider
    boundary (BASELINE.json: "the Provider plugin boundary gates whether
    applyUpdate dispatches to the JS path or the TPU batch path"):

    - ``"auto"`` (default): device path, transparently demoting docs whose
      traffic is out of scope (subdocuments) to the CPU core.
    - ``"cpu"``: every doc on the CPU reference core (the interactive
      path; no device work at all).
    - ``"device"``: device path with demotion FORBIDDEN — out-of-scope
      traffic raises instead, for deployments that must not absorb CPU
      work silently.

    Durability (ISSUE 3): pass ``wal_dir`` (or set ``YTPU_WAL_DIR``) to
    journal every accepted update to a checksummed write-ahead log
    before it reaches the engine; :meth:`checkpoint` compacts the log
    into per-doc snapshots, and :meth:`recover` rebuilds a provider
    from a crashed predecessor's directory.  See
    :mod:`yjs_tpu.persistence` and README "Durability".
    """

    def __init__(
        self,
        n_docs: int,
        root_name: str = "text",
        mesh=None,
        gc: bool = False,
        backend: str = "auto",
        wal_dir=None,
        wal_config: WalConfig | None = None,
        tier_config=None,
        admission: AdmissionController | None = None,
        admission_config=None,
    ):
        self.backend = backend
        self.engine = BatchEngine(
            n_docs, root_name=root_name, mesh=mesh, gc=gc, policy=backend
        )
        self._guids: dict[str, int] = {}
        self._guid_of: dict[int, str] = {}
        self._next = 0
        self._dirty = False
        # per-room server-side undo stacks (opt-in; see enable_undo)
        self._undo: dict[str, object] = {}
        self._undo_settings: dict[str, tuple] = {}
        # memoized attribution views (see user_data)
        self._user_data: dict[tuple[str, str], object] = {}
        # provider-level counters live on the ENGINE's registry so one
        # exposition call (metrics_text / metrics_snapshot) covers the
        # whole stack; all are no-ops under YTPU_OBS_DISABLED=1
        r = self.engine.obs.registry
        self._m_updates_rx = r.counter(
            "ytpu_provider_updates_received_total",
            "Updates queued via receive_update",
        )
        self._m_ingress_bytes = r.counter(
            "ytpu_provider_update_ingress_bytes_total",
            "Bytes of update payloads ingested (receive_update + sync "
            "step2/update frames)",
            unit="bytes",
        )
        self._m_step1 = r.counter(
            "ytpu_provider_sync_step1_total",
            "Sync step-1 messages produced (sync_step1)",
        )
        self._m_step2 = r.counter(
            "ytpu_provider_sync_step2_total",
            "Sync step-2 replies produced (handle_sync_message + batch)",
        )
        self._m_step2_bytes = r.counter(
            "ytpu_provider_sync_step2_bytes_total",
            "Bytes of framed sync step-2 replies",
            unit="bytes",
        )
        self._m_sync_msgs = r.counter(
            "ytpu_provider_sync_messages_total",
            "Sync messages handled by handle_sync_message, by frame type",
            labelnames=("type",),
        )
        self._m_undo = r.counter(
            "ytpu_provider_undo_total",
            "Server-side undo-stack operations that reverted something",
            labelnames=("op",),
        )
        self._m_events = r.counter(
            "ytpu_provider_events_delivered_total",
            "Observe-bridge events delivered to callbacks (post path "
            "filter)",
        )
        self._m_evicted = r.counter(
            "ytpu_provider_docs_evicted_total",
            "Docs released from their engine slot (release_doc + "
            "recovered release records)",
        )
        # slots freed by release_doc, reused before _next advances
        self._free: list[int] = []
        # end-to-end convergence SLO tracker (ISSUE 4): updates are keyed
        # by their natural (client, clock) first-struct id, so origin /
        # receive / integrate / visible timestamps need ZERO wire changes
        self.slo = ConvergenceTracker(r, tracer=self.engine.obs.tracer)
        # WAL metric families register unconditionally (exposition and
        # the schema checker must see them WAL or no WAL); the journal
        # itself attaches only when a directory is configured
        self._wal_metrics = WalMetrics(r)
        if wal_dir is None:
            wal_dir = os.environ.get("YTPU_WAL_DIR")
        self.wal: WriteAheadLog | None = (
            WriteAheadLog(
                wal_dir, wal_config, self._wal_metrics,
                tracer=self.engine.obs.tracer,
            )
            if wal_dir
            else None
        )
        # stats dict of the replay that built this provider (recover())
        self.last_recovery: dict | None = None
        # per-peer session layer (ISSUE 5): sessions keyed by
        # (room guid, peer name); families register unconditionally so
        # exposition and the schema checker see the full surface
        self._session_metrics = SessionMetrics(r)
        self._sessions: dict[tuple[str, str], SyncSession] = {}
        self._sessions_bridged = False
        # (guid, peer) -> (peer sid, recv floor) journaled ack facts
        # collected by replay_wal; armed onto sessions as resume hints
        self._recovered_acks: dict[tuple[str, str], tuple[int, int]] = {}
        # geo replication (ISSUE 17): region -> {"sid", "seq", "epoch"}
        # link floors collected by replay_wal; the attached GeoReplicator
        # (if any) arms them onto its WAN links as resume hints
        self._recovered_geo: dict[str, dict] = {}
        self.geo = None  # set by GeoReplicator.__init__ when attached
        # fleet membership (ISSUE 6): set by FleetRouter so admission
        # errors and dashboards name the shard, None standalone
        self.shard_id: int | None = None
        # doc lifecycle tiering (ISSUE 7): the manager (and its
        # ytpu_tier_* families) exists unconditionally, but demotion /
        # auto-eviction / promotion only activate when the config says
        # enabled — default-off keeps the hard ProviderFullError cap
        self.tiers = TierManager(self, tier_config)
        # admission control + brownout (ISSUE 10): a FLEET injects one
        # shared controller into every shard (fleet-wide tenant buckets
        # and one brownout level); standalone providers get a private
        # one.  Families register unconditionally; default-off config
        # keeps every seam check to a single attribute read.
        self.admission = (
            admission
            if admission is not None
            else AdmissionController(admission_config, registry=r)
        )
        self.admission.attach(self)
        # adaptive flush tick (ISSUE 12): paces flush_tick() callers by
        # SLO burn verdict + brownout level; explicit flush() ignores it
        self.flush_ticks = FlushTickController(r)
        # cost attribution + telemetry history (ISSUE 19): the ledger
        # rides the ingress/flush/WAL seams below; the embedded TSDB
        # sampler (one per process) adopts this provider's registry so
        # its families — ytpu_cost_* included — gain history.  Neither
        # touches engine state: output is byte-identical on or off.
        self.cost = CostLedger(r)
        self.tsdb = maybe_attach_tsdb(r)
        # mid-recovery flag the admin plane's /readyz keys off (ISSUE
        # 16): recover() raises it around the WAL replay
        self.recovering = False
        # per-process HTTP introspection plane (ISSUE 16): opt-in for
        # library-constructed providers — serves only when
        # YTPU_ADMIN_PORT is set, so tests building hundreds of
        # providers open zero sockets.  Cluster processes embed their
        # own AdminServer around the whole shard/gateway instead.
        self.admin = maybe_start_admin(self, "provider")

    # -- doc management -----------------------------------------------------

    def doc_id(self, guid: str) -> int:
        """The engine slot for a doc guid (allocating on first use;
        slots freed by :meth:`release_doc` are reused first).

        With tiering enabled (ISSUE 7) this is the demand-promotion and
        auto-eviction seam: a demoted guid is promoted back into a slot
        (warm hydrates columns, cold replays journaled state), and a
        full provider demotes its coldest eligible hot doc instead of
        raising :class:`ProviderFullError`."""
        i = self._guids.get(guid)
        if i is None:
            tiers = self.tiers
            if tiers.enabled and tiers.tier_of(guid) is not None:
                i = tiers.promote(guid)
                tiers.touch(guid)
                return i
            if self._free:
                i = self._free.pop()
            elif self._next < self.engine.n_docs:
                i = self._next
                self._next += 1
            elif tiers.enabled and tiers.make_room():
                i = self._free.pop()
            else:
                where = (
                    f"shard {self.shard_id}"
                    if self.shard_id is not None
                    else "provider"
                )
                # capacity exhaustion is a black-box moment (ISSUE 11):
                # the rejected guid and the in-flight trace land in the
                # flight recorder, and a dump ships the forensics (the
                # recorder dedupes, so a rejection burst emits one file)
                ctx = obs_dist.current_context()
                if ctx is not None:
                    ctx.force("provider_full")
                bb = self.engine.obs.blackbox
                bb.record(
                    "provider", "full", severity="error", guid=guid,
                    shard=self.shard_id,
                    trace=ctx.trace_hex if ctx is not None else None,
                    n_docs=self.engine.n_docs,
                )
                bb.dump("provider_full", guid=guid, shard=self.shard_id)
                raise ProviderFullError(
                    f"{where} is full ({self.engine.n_docs} docs); "
                    "release_doc() a cold room to admit "
                    f"{guid!r}"
                )
            self._guids[guid] = i
            self._guid_of[i] = guid
        self.tiers.touch(guid)
        return i

    def has_doc(self, guid: str) -> bool:
        """Whether the guid currently holds an engine slot (no
        allocation side effect, unlike :meth:`doc_id`)."""
        return guid in self._guids

    def guids(self) -> list[str]:
        """The rooms currently admitted, sorted (stable for the fleet
        rebalancer's deterministic candidate ordering)."""
        return sorted(self._guids)

    @property
    def occupancy(self) -> float:
        """Admitted docs / slot capacity — the gauge the fleet
        rebalancer ticks on (1.0 means the next new guid raises
        :class:`ProviderFullError`)."""
        n = self.engine.n_docs
        return (len(self._guids) / n) if n else 1.0

    @property
    def resident_docs(self) -> int:
        """Docs this provider owns across ALL tiers — hot slots plus
        warm/cold demoted rooms.  With tiering disabled this equals
        ``len(self._guids)``; the fleet router balances on this, not on
        slot occupancy, so tiered shards are compared by what they
        actually hold."""
        return self.tiers.resident_count()

    def on_update(self, callback) -> None:
        """Register ``callback(guid, update_bytes)``: the flush-emitted
        incremental update per room — the server's broadcast-to-peers seam
        (a transport pushes these as MESSAGE_YJS_UPDATE frames)."""
        def bridge(doc, update):
            # stamp the ORIGIN timestamp the moment the update is born:
            # a peer provider receiving these bytes measures end-to-end
            # convergence from here (obs/slo.py; in-process floor)
            self.slo.origin(update)
            callback(self._guid_of[doc], update)

        self.engine.on_update(bridge)

    def observe(self, guid: str, path, callback):
        """Register ``callback(guid, event)`` for events whose path starts
        with ``path`` (a sequence; ``[]`` = every type in the room;
        ``["text"]`` = the root text).  Events are YEvent-shaped dicts
        ``{"path", "delta", "keys"}`` computed from each flush's step plan
        (reference observe/observeDeep + YEvent.changes) — the server-side
        "what changed in room X" seam without replaying into a CPU doc.
        Returns an unsubscribe callable.

        Numeric list positions in ``path`` match the reference getPathTo
        (YEvent.js:207-228): one per undeleted item before the target,
        with mirror rows grouped into CPU-merged-item runs so the count
        equals what a CPU doc reports (ops/events._path_of; parity pinned
        by tests/test_engine_events.py::test_event_path_parity_*)."""
        prefix = list(path)

        def bridge(doc, events, g=guid):
            for ev in events:
                if ev["path"][: len(prefix)] == prefix:
                    self._m_events.inc()
                    callback(g, ev)

        doc = self.doc_id(guid)
        self.engine.observe(doc, bridge)

        def unobserve():
            self.engine.unobserve(doc, bridge)

        return unobserve

    # -- update plumbing ----------------------------------------------------

    def _trace_ingress(self, update: bytes) -> "obs_dist.TraceContext":
        """Establish the causal trace context for one ingress update
        (ISSUE 11): adopt the in-flight context when a session envelope
        or fleet seam already installed one, else mint deterministically
        from the update bytes — every provider hashing the same bytes
        computes the same trace id and sampling verdict."""
        ctx = obs_dist.current_context()
        origin = "adopted"
        if ctx is None:
            ctx = obs_dist.mint_for_update(bytes(update))
            origin = "minted"
        m = obs_dist.trace_metrics()
        m.contexts.labels(origin=origin).inc()
        if ctx.sampled:
            m.sampled.inc()
        return ctx

    def receive_update(
        self, guid: str, update: bytes, v2: bool = False,
        undoable: bool = False, internal: bool = False,
    ) -> bool:
        """Queue one room update.  ``undoable=True`` marks it for the
        room's undo stack when :meth:`enable_undo` is active (the server
        decides which origins' edits count — reference trackedOrigins,
        UndoManager.js:19-41).

        Returns True when the update was accepted.  False means it was
        diverted to the engine's dead-letter queue instead (the room is
        quarantined, or a CPU-served apply failed) — recoverable via
        :meth:`replay_dead_letters`; the undo replica is only fed
        accepted updates so it cannot diverge from the room.

        With admission control enabled (ISSUE 10) the update passes the
        per-tenant/per-doc token buckets first: over-rate traffic is
        journaled and parked in the weighted-fair queue (still True —
        it WILL integrate, on a later flush drain), and a rejected
        update raises the typed
        :class:`~yjs_tpu.admission.AdmissionRejected` before any state
        changes — internal traffic (migration, failover, recovery)
        bypasses the gate with ``internal=True``."""
        adm = self.admission
        verdict = "admit"
        if adm.enabled and not internal:
            # gate BEFORE doc_id: a rejected writer must not allocate a
            # slot, and a queued update takes its slot at drain time
            verdict = adm.admit_update(self, guid, len(update))
        ctx = self._trace_ingress(update)
        if verdict == "queue":
            if self.wal is not None:
                # journaled at ENQUEUE: the queue is host memory, and
                # zero acked-update loss must hold across a crash.  SLO
                # bookkeeping waits for the drain — queue age is traffic
                # the controller chose to shed, and letting it page the
                # interactive SLO would feed the brownout its own
                # shedding as an overload signal (self-sustaining
                # degradation, the flap hysteresis exists to prevent)
                self.wal.append(KIND_UPDATE, guid, update, v2=v2)
                self.cost.wal_bytes(guid, len(update))
            self._m_updates_rx.inc()
            self._m_ingress_bytes.inc(len(update))
            adm.enqueue(
                self, guid, bytes(update), v2, undoable, None, trace=ctx
            )
            return True
        doc = self.doc_id(guid)
        with obs_dist.use_context(ctx), self.engine.obs.tracer.span(
            "ytpu.provider.receive_update", guid=guid,
            **({"trace": ctx.trace_hex} if ctx.sampled else {}),
        ):
            key = self.slo.receive(update, v2=v2, guid=guid, trace=ctx)
            if self.wal is not None:
                # journal BEFORE integrating (write-ahead): a crash between
                # append and flush replays the update; the reverse order
                # could integrate state the log never saw
                self.wal.append(KIND_UPDATE, guid, update, v2=v2)
                self.cost.wal_bytes(guid, len(update))
            accepted = self.engine.queue_update(doc, update, v2=v2)
            self._m_updates_rx.inc()
            self._m_ingress_bytes.inc(len(update))
            if not accepted:
                self.slo.rejected(key)
                return False
            self.slo.integrated(key)
            self.cost.staged(guid, len(update))
            self._dirty = True
            ru = self._undo.get(guid)
            if ru is not None:
                ru.apply_update(update, tracked=undoable, v2=v2)
            return True

    def _integrate_admitted(
        self, guid: str, update: bytes, v2: bool, undoable: bool, slo_key
    ) -> bool:
        """Integrate one update popped from the admission queue.  The
        update was journaled at enqueue; it enters the SLO window only
        now (``slo_key=None``), so shed traffic's queue age is invisible
        to the interactive convergence verdict."""
        if slo_key is None:
            ctx = obs_dist.current_context() or obs_dist.mint_for_update(
                bytes(update)
            )
            slo_key = self.slo.receive(update, v2=v2, guid=guid, trace=ctx)
        try:
            doc = self.doc_id(guid)
        except ProviderFullError as e:
            self.admission.note_full("provider")
            self.slo.rejected(slo_key)
            self.engine._dead_letter(
                -1, update, v2, f"admission-full: {e}"
            )
            return False
        if not self.engine.queue_update(doc, update, v2=v2):
            self.slo.rejected(slo_key)
            return False
        self.slo.integrated(slo_key)
        # journaled (and WAL-costed) at enqueue; staged bytes count now,
        # when the update actually enters the next flush's batch
        self.cost.staged(guid, len(update))
        self._dirty = True
        ru = self._undo.get(guid)
        if ru is not None:
            ru.apply_update(update, tracked=undoable, v2=v2)
        return True

    # -- server-side undo ---------------------------------------------------

    def enable_undo(
        self,
        guid: str,
        scopes=None,
        capture_timeout: float = 500,
        delete_filter=None,
    ) -> "RoomUndoHandle":
        """Attach a server-side undo/redo stack to one room (reference
        UndoManager semantics, run against an opt-in CPU replica — see
        utils/server_undo.py for the design rationale).  The room itself
        stays device-resident.  Idempotent for identical settings; a
        repeat call with DIFFERENT settings raises."""
        from .utils.server_undo import RoomUndo

        norm_scopes = (
            tuple(scopes) if scopes is not None
            else (("text", self.engine.root_name),)
        )
        # idempotency compares scopes/capture_timeout only: callables have
        # no useful equality (a lambda re-created at each call site would
        # spuriously fail an identity check), so a repeat call may pass any
        # delete_filter — the one from the first call stays in effect
        settings = (norm_scopes, capture_timeout)
        if guid in self._undo:
            if self._undo_settings[guid] != settings:
                raise ValueError(
                    f"undo already enabled for {guid!r} with different "
                    "settings; disable_undo() first to reconfigure"
                )
            return RoomUndoHandle(self, guid)
        self.flush()
        i = self.doc_id(guid)
        ru = RoomUndo(
            self.engine.encode_state_as_update(i),
            scopes=norm_scopes,
            capture_timeout=capture_timeout,
            delete_filter=delete_filter,
        )
        self._undo[guid] = ru
        self._undo_settings[guid] = settings
        return RoomUndoHandle(self, guid)

    def disable_undo(self, guid: str) -> None:
        """Detach and free the room's undo replica (the room itself is
        unaffected).  No-op if undo was never enabled."""
        self._undo.pop(guid, None)
        self._undo_settings.pop(guid, None)

    def _room_undo(self, guid: str):
        ru = self._undo.get(guid)
        if ru is None:
            raise ValueError(f"undo not enabled for room {guid!r}")
        return ru

    def undo(self, guid: str) -> bytes | None:
        """Revert the room's last undoable change.  The reverting update
        is applied to the device-resident room through the normal flush
        path — peers receive it via the ``on_update`` broadcast seam like
        any other change; do NOT also send the returned bytes.  The
        return value reports what was reverted (None = nothing to
        undo)."""
        ru = self._room_undo(guid)
        u = ru.undo()
        if u is not None:
            self._m_undo.labels(op="undo").inc()
            doc = self.doc_id(guid)
            if self.wal is not None:
                # the reverting bytes are room traffic like any other:
                # recovery must replay the undo, not resurrect the text
                self.wal.append(KIND_UPDATE, guid, u)
            self.engine.queue_update(doc, u)
            self._dirty = True
            self.flush()
        return u

    def redo(self, guid: str) -> bytes | None:
        ru = self._room_undo(guid)
        u = ru.redo()
        if u is not None:
            self._m_undo.labels(op="redo").inc()
            doc = self.doc_id(guid)
            if self.wal is not None:
                self.wal.append(KIND_UPDATE, guid, u)
            self.engine.queue_update(doc, u)
            self._dirty = True
            self.flush()
        return u

    def flush(self) -> None:
        """Run one batched device integration step over all pending docs.

        Under ``backend='device'`` this raises while ANY demoted doc
        exists (not just on the flush that demoted it): the demoted docs
        stay served by the CPU core so no data is lost, but the operator
        is alerted on every flush until they act."""
        adm = self.admission
        if adm.enabled:
            # integrate queued over-rate traffic first (weighted-fair,
            # bounded batch) so it rides this flush's device step
            adm.drain_for(self)
        if self._dirty:
            # reset BEFORE the engine call and restore only if it fails:
            # raising after the engine integrated (as the device-policy
            # check below does) must not leave the provider re-flushing
            # already-integrated work forever
            self._dirty = False
            tracer = self.engine.obs.tracer
            try:
                with tracer.span("ytpu.provider.flush"):
                    self.engine.flush()
                    # visibility stamps (and the flow-arrow landings)
                    # belong INSIDE the flush span: this is the moment
                    # the queued updates became readable
                    self.slo.visible(tracer=tracer)
                # cost attribution (ISSUE 19): split this flush's
                # device/host seconds across the docs staged since the
                # last one, weighted by staged bytes
                self.cost.on_flush(self.engine.last_flush_metrics)
            except Exception as e:
                self._dirty = True  # flush incomplete: retry next call
                # an unhandled flush exception is exactly what the
                # black box exists for: snapshot the ring before the
                # error unwinds into the caller (ISSUE 11)
                bb = self.engine.obs.blackbox
                bb.record(
                    "provider", "flush_exception", severity="error",
                    shard=self.shard_id,
                    error=f"{type(e).__name__}: {e}",
                )
                bb.dump("flush_exception", shard=self.shard_id)
                raise
        if self.backend == "device" and self.engine.fallback:
            d = self.engine.demotions[0]
            raise RuntimeError(
                f"backend='device' forbids CPU fallback: doc "
                f"{self._guid_of.get(d['doc'], d['doc'])!r} demoted "
                f"({d['reason']}); {len(self.engine.fallback)} doc(s) on "
                f"the CPU path"
            )

    def flush_tick(self, now: float | None = None) -> bool:
        """Adaptive flush tick (ISSUE 12): flush only when the current
        batch window has elapsed.

        The window comes from :class:`FlushTickController` — tightened
        to the minimum while the SLO burn verdict is not "ok", widened
        geometrically while ticks find nothing dirty, and scaled (or
        pinned to the maximum under ``force_coalesce``) by the brownout
        level.  ``now`` is injectable for deterministic tests.  Returns
        True when a flush actually ran."""
        if now is None:
            now = time.monotonic()
        ticks = self.flush_ticks
        adm = self.admission
        scale = float(getattr(adm, "flush_interval_scale", 1.0))
        coalesce = bool(getattr(adm, "force_coalesce", False))
        w = ticks.window(self.slo.state(), scale, coalesce)
        if not ticks.due(now, w):
            return False
        if adm.enabled:
            adm.drain_for(self)
        busy = self._dirty
        if busy:
            self.flush()
        ticks.applied(now, w, busy)
        return busy

    # -- y-protocols sync framing ------------------------------------------

    def sync_step1(self, guid: str) -> bytes:
        """Message announcing this doc's state vector (sync step 1)."""
        enc = Encoder()
        encoding.write_var_uint(enc, protocol.MESSAGE_YJS_SYNC_STEP_1)
        encoding.write_var_uint8_array(enc, self.engine.encode_state_vector(self.doc_id(guid)))
        self._m_step1.inc()
        return enc.to_bytes()

    def handle_sync_message(self, guid: str, message: bytes) -> bytes | None:
        """Process one sync message for a doc; returns the reply, if any.

        Integrates pending traffic before answering step 1 so the emitted
        diff reflects everything received so far.
        """
        dec = Decoder(message)
        doc = self.doc_id(guid)
        try:
            msg_type = decoding.read_var_uint(dec)
        except Exception as e:
            self._m_sync_msgs.labels(type="bad").inc()
            self.engine._dead_letter(
                doc, message, False, f"bad-frame: {type(e).__name__}: {e}"
            )
            return None
        if msg_type == protocol.MESSAGE_YJS_SYNC_STEP_1:
            self._m_sync_msgs.labels(type="step1").inc()
            self.flush()
            try:
                remote_sv = decoding.read_var_uint8_array(dec)
                diff = self.engine.encode_state_as_update(doc, remote_sv)
            except Exception as e:
                # truncated frame or garbage state vector: dead-letter
                # and stay silent — the peer re-requests on reconnect
                self._m_sync_msgs.labels(type="bad").inc()
                self.engine._dead_letter(
                    doc, message, False,
                    f"bad-frame: {type(e).__name__}: {e}",
                )
                return None
            enc = Encoder()
            encoding.write_var_uint(enc, protocol.MESSAGE_YJS_SYNC_STEP_2)
            encoding.write_var_uint8_array(enc, diff)
            reply = enc.to_bytes()
            self._m_step2.inc()
            self._m_step2_bytes.inc(len(reply))
            return reply
        if msg_type in (protocol.MESSAGE_YJS_SYNC_STEP_2, protocol.MESSAGE_YJS_UPDATE):
            self._m_sync_msgs.labels(
                type="step2"
                if msg_type == protocol.MESSAGE_YJS_SYNC_STEP_2
                else "update"
            ).inc()
            try:
                u = decoding.read_var_uint8_array(dec)
                validate_update(u)
            except Exception as e:
                # truncated frame or undecodable payload: the transport
                # handed us damage — keep the whole frame recoverable in
                # the dead-letter queue and keep serving the room (the
                # peer's next sync step repairs the gap).  Validating at
                # the network seam keeps transport damage out of the
                # engine entirely: no rollback, no demotion.
                self._m_sync_msgs.labels(type="bad").inc()
                self.engine._dead_letter(
                    doc, message, False,
                    f"bad-frame: {type(e).__name__}: {e}",
                )
                return None
            self._m_ingress_bytes.inc(len(u))
            ctx = self._trace_ingress(u)
            adm = self.admission
            if adm.enabled:
                # the admission seam for session DATA / plain update
                # frames: a veto becomes a BUSY/retry-after envelope
                # reply (enhanced peers back off and coalesce; plain
                # y-protocols readers skip it) — never a silent drop
                try:
                    verdict = adm.admit_update(self, guid, len(u))
                except AdmissionRejected as e:
                    self._m_sync_msgs.labels(type="rejected").inc()
                    return encode_busy(e.retry_after)
                if verdict == "queue":
                    # journaled now (durability), SLO-received at drain
                    # (shed traffic must not page the interactive SLO)
                    if self.wal is not None:
                        self.wal.append(KIND_UPDATE, guid, u)
                    adm.enqueue(
                        self, guid, bytes(u), False, False, None,
                        trace=ctx,
                    )
                    return None
            with obs_dist.use_context(ctx):
                key = self.slo.receive(u, guid=guid, trace=ctx)
                if self.wal is not None:
                    # journal the PAYLOAD, post-validation: transport
                    # damage (dead-lettered above) never enters the
                    # durable log
                    self.wal.append(KIND_UPDATE, guid, u)
                if self.engine.queue_update(doc, u):
                    self._dirty = True
                    self.slo.integrated(key)
                else:
                    self.slo.rejected(key)
            return None
        # unknown frame type (newer protocol revision, or a corrupted
        # type varint): count and skip — a hostile peer must not be able
        # to crash the room by sending one unknown frame
        self._m_sync_msgs.labels(type="unknown").inc()
        self.engine._dead_letter(
            doc, message, False, f"unknown-frame: type {msg_type}"
        )
        return None

    def handle_sync_step1_batch(
        self, messages: list[tuple[str, bytes]]
    ) -> list[bytes]:
        """Answer many concurrent sync-step-1 messages with ONE device
        dispatch (the server's fan-in moment: N clients reconnect, N diffs
        computed by one ``diff_mask_kernel`` call).  Returns the framed
        step-2 reply per message."""
        from .updates import decode_state_vector

        self.flush()
        requests = []
        for guid, message in messages:
            dec = Decoder(message)
            msg_type = decoding.read_var_uint(dec)
            if msg_type != protocol.MESSAGE_YJS_SYNC_STEP_1:
                raise ValueError("batch handler only accepts sync step 1")
            remote_sv = decode_state_vector(decoding.read_var_uint8_array(dec))
            requests.append((self.doc_id(guid), remote_sv))
        updates = self.engine.sync_step2_batch(requests)
        replies = []
        for u in updates:
            enc = Encoder()
            encoding.write_var_uint(enc, protocol.MESSAGE_YJS_SYNC_STEP_2)
            encoding.write_var_uint8_array(enc, u)
            replies.append(enc.to_bytes())
        self._m_sync_msgs.labels(type="step1").inc(len(messages))
        self._m_step2.inc(len(replies))
        self._m_step2_bytes.inc(sum(len(rep) for rep in replies))
        return replies

    # -- peer sessions (ISSUE 5) --------------------------------------------

    def _ensure_session_bridge(self) -> None:
        """Lazily register the flush-emitted-update → sessions fan-out
        (only providers that actually host sessions pay the listener)."""
        if self._sessions_bridged:
            return
        self._sessions_bridged = True

        def bridge(doc, update):
            g = self._guid_of.get(doc)
            if g is None:
                return
            self.slo.origin(update)
            for (sg, _peer), sess in list(self._sessions.items()):
                if sg == g:
                    sess.send_update(update)

        self.engine.on_update(bridge)

    def session(
        self, guid: str, peer: str = "peer",
        config: SessionConfig | None = None,
    ) -> SyncSession:
        """Get-or-create the :class:`SyncSession` for (room, peer).

        The session shares the provider's ``ytpu_net_*`` metric
        families, receives the room's flush-emitted updates, routes
        inbound frames through :meth:`handle_sync_message`, journals
        ack floors to the WAL, and — after :meth:`recover` — starts
        armed with the journaled resume hint so its first HELLO asks
        the surviving peer for delta catch-up, not a full resync.
        Attach a transport with ``session.connect(transport)`` and
        drive :meth:`tick_sessions` at the server's cadence."""
        key = (guid, str(peer))
        sess = self._sessions.get(key)
        if sess is not None:
            if not sess._closed:
                return sess
            # drop the closed carcass BEFORE admission: if doc_id vetoes
            # below, the registry must hold nothing for this key — a
            # half-registered peer would be ticked/snapshotted forever
            del self._sessions[key]
        # admission is atomic with registration: doc_id either allocates
        # the slot or raises ProviderFullError with no bridge registered
        # and no registry entry left behind
        self.doc_id(guid)  # allocate (or veto: ProviderFullError) now
        # an attached peer is a stronger liveness signal than a stray
        # read: weight the touch so sessioned rooms out-heat idle ones
        self.tiers.touch(guid, self.tiers.config.session_weight)
        self._ensure_session_bridge()
        host = _ProviderSessionHost(self, guid, str(peer))
        sess = SyncSession(
            host, config=config, metrics=self._session_metrics,
            peer=str(peer),
        )
        hint = self._recovered_acks.get(key)
        if hint is not None:
            sess.set_resume_hint(*hint)
        # sessions read the live brownout flags (coalesce, anti-entropy
        # pause) straight off the controller every tick
        sess.policy = self.admission
        self._sessions[key] = sess
        return sess

    def close_session(self, guid: str, peer: str) -> None:
        sess = self._sessions.pop((guid, str(peer)), None)
        if sess is not None:
            sess.close()
        self._session_metrics.set_state_gauges(self._sessions.values())

    def tick_sessions(self) -> None:
        """One session-time tick for every peer session (retransmit
        backoff, heartbeats, liveness, anti-entropy) + gauge refresh.
        Also advances the admission/brownout clock when this provider
        owns it (a fleet claims the tick for itself)."""
        self.admission.maybe_tick(self)
        for sess in list(self._sessions.values()):
            sess.tick()
        self._session_metrics.set_state_gauges(self._sessions.values())

    def sessions_snapshot(self) -> list[dict]:
        """Per-peer session rows (guid, state, outbox depth,
        retransmits, last-ack age, ...) — the ``ytpu_top`` feed."""
        rows = []
        for (guid, _peer), sess in sorted(self._sessions.items()):
            row = sess.snapshot()
            row["guid"] = guid
            rows.append(row)
        self._session_metrics.set_state_gauges(self._sessions.values())
        return rows

    def journal_session_ack(
        self, guid: str, peer: str, sid: int, seq: int
    ) -> None:
        """Journal "room ``guid`` holds peer session ``sid`` up to
        ``seq``" (KIND_ACK).  Recovery replays these into resume hints:
        a rebuilt provider's sessions resume retransmission from the
        floor instead of forcing a full resync."""
        if self.wal is None or not sid:
            return
        payload = json.dumps(
            {"peer": peer, "sid": sid, "seq": seq}
        ).encode("utf-8")
        self.wal.append(KIND_ACK, guid, payload)

    def journal_geo_link(
        self, peer: str, sid: int, seq: int, epoch: int
    ) -> None:
        """Journal a geo link floor (KIND_GEO): "our WAN session with
        region ``peer`` holds ``sid`` up to ``seq`` at fencing epoch
        ``epoch``".  Region-scoped (empty guid); the last record per
        peer stands.  Recovery replays the floors into
        ``_recovered_geo`` so a kill -9'd region's GeoReplicator
        resumes its links instead of full-resyncing the doc space."""
        if self.wal is None or not sid:
            return
        payload = json.dumps(
            {"peer": str(peer), "sid": int(sid), "seq": int(seq),
             "epoch": int(epoch)},
            separators=(",", ":"),
        ).encode("utf-8")
        self.wal.append(KIND_GEO, "", payload)

    def journal_migration(self, guid: str, dst: int, epoch: int) -> None:
        """Journal a migration intent (KIND_MIGRATE): "room ``guid`` is
        moving to shard ``dst`` at routing epoch ``epoch``".  Written by
        the fleet BEFORE any state reaches the destination; the later
        release record marks the handoff complete.  Recovery surfaces
        intents with no matching release as ``migrations_pending`` so
        :meth:`yjs_tpu.fleet.FleetRouter.recover` can resolve ownership
        to exactly one shard (no-op without a WAL — migration is then
        safe only against in-process failures, same as every other
        journal seam)."""
        if self.wal is None:
            return
        payload = json.dumps(
            {"dst": int(dst), "epoch": int(epoch)}
        ).encode("utf-8")
        self.wal.append(KIND_MIGRATE, guid, payload)

    def journal_repl_role(
        self, guid: str, role: str, epoch: int, primary: int | None = None
    ) -> None:
        """Journal a replication role marker (KIND_REPL): "this WAL
        holds ``guid`` as a ``replica`` copy" or "this shard owns
        ``guid`` as of fencing epoch ``epoch``" (promotion).  The last
        marker for a guid stands; a release record clears it.  Recovery
        surfaces the markers so replica journals are never mistaken for
        split-brain owners and a stale primary's claim loses to a newer
        promotion epoch."""
        if self.wal is None:
            return
        info: dict = {"role": str(role), "epoch": int(epoch)}
        if primary is not None:
            info["primary"] = int(primary)
        self.wal.append(
            KIND_REPL, guid,
            json.dumps(info, separators=(",", ":")).encode("utf-8"),
        )

    def journal_admission(
        self, level: str, reason: str, tick: int
    ) -> None:
        """Journal a brownout level transition (KIND_ADM): "the
        admission controller entered ``level`` at controller tick
        ``tick`` because ``reason``".  Fleet-scoped (empty guid);
        recovery surfaces a count and the last level for forensics —
        the live level always restarts at normal."""
        if self.wal is None:
            return
        payload = json.dumps(
            {"level": str(level), "reason": str(reason), "tick": int(tick)},
            separators=(",", ":"),
        ).encode("utf-8")
        self.wal.append(KIND_ADM, "", payload)

    def journal_replica_record(
        self, kind: int, guid: str, payload: bytes, v2: bool = False
    ) -> bool:
        """Append one fanned-out replication record to this shard's WAL
        without touching the engine (replica copies are journal-only
        until promotion materializes them).  Returns False when the
        shard has no WAL — the caller then falls back to its in-memory
        mirror so availability survives journal-less fleets."""
        if self.wal is None:
            return False
        self.wal.append(kind, guid, payload, v2=v2)
        return True

    def heartbeat(self) -> dict:
        """Cheap liveness probe for the fleet failure detector: touches
        no engine state, answers from host-side bookkeeping only.  A
        dead shard's stub raises instead."""
        return {
            "shard": self.shard_id,
            "docs": len(self._guids),
            "resident": self.resident_docs,
        }

    def _journal_ack_floors(self) -> None:
        """Re-append every known ack floor (live sessions win over
        recovered hints) — called after checkpoint compaction drops the
        journaled history the floors lived in."""
        if self.wal is None:
            return
        floors = dict(self._recovered_acks)
        for (guid, peer), sess in self._sessions.items():
            if sess._peer_sid:
                floors[(guid, peer)] = (sess._peer_sid, sess._recv_cum)
        for (guid, peer), (sid, seq) in sorted(floors.items()):
            payload = json.dumps(
                {"peer": peer, "sid": sid, "seq": seq}
            ).encode("utf-8")
            self.wal.append(KIND_ACK, guid, payload)

    def _journal_geo_floors(self) -> None:
        """Re-append every known geo link floor (live links win over
        recovered hints) after checkpoint compaction — same idiom as
        :meth:`_journal_ack_floors`."""
        if self.wal is None:
            return
        floors = dict(self._recovered_geo)
        if self.geo is not None:
            floors.update(self.geo.link_floors())
        for peer, f in sorted(floors.items()):
            self.journal_geo_link(
                peer, f.get("sid", 0), f.get("seq", 0), f.get("epoch", 0)
            )

    # -- state accessors ----------------------------------------------------

    def text(self, guid: str) -> str:
        self.flush()
        return self.engine.text(self.doc_id(guid))

    def to_delta(
        self,
        guid: str,
        snapshot=None,
        prev_snapshot=None,
        compute_ychange=None,
    ) -> list:
        """Attributed rich-text delta of the room's root text (reference
        YText.toDelta) — served from the mirror, no CPU replay.  With
        ``snapshot``/``prev_snapshot``, the point-in-time / two-snapshot
        diff view with ychange attribution (YText.js:936-1030)."""
        self.flush()
        return self.engine.to_delta(
            self.doc_id(guid),
            snapshot=snapshot,
            prev_snapshot=prev_snapshot,
            compute_ychange=compute_ychange,
        )

    def snapshot(self, guid: str):
        """Capture the room's point-in-time Snapshot (SV + DS) without
        demoting it off the device (reference Snapshot.js snapshot())."""
        self.flush()
        return self.engine.snapshot(self.doc_id(guid))

    def create_doc_from_snapshot(self, guid: str, snap, new_doc=None):
        """Rewind the room to ``snap`` as a standalone CPU Doc (reference
        Snapshot.js:162-202); the device-resident room is untouched."""
        self.flush()
        return self.engine.create_doc_from_snapshot(
            self.doc_id(guid), snap, new_doc
        )

    # -- user attribution (PermanentUserData queries) -----------------------

    def user_data(self, guid: str, store_name: str = "users"):
        """Attribution view over the room's PermanentUserData map
        (reference src/utils/PermanentUserData.js:15-142), served from
        mirror columns — the room stays device-resident.

        Deployment model (same as the reference's): editing CLIENTS call
        setUserMapping on their own docs, so the ``users`` map arrives as
        ordinary update traffic and the mirror hosts it like any root
        type.  The server answers ``user_by_client_id`` /
        ``user_by_deleted_id`` by reading the map straight out of the
        mirror (ids arrays, encoded-DeleteSet blobs) — no CPU doc, no
        observers, no replica.  The handle is memoized per (guid,
        store_name) so the per-query call pattern
        ``prov.user_data(g).user_by_client_id(c)`` actually hits the
        content_gen parse cache."""
        key = (guid, store_name)
        rud = self._user_data.get(key)
        if rud is None:
            rud = RoomUserData(self, guid, store_name)
            self._user_data[key] = rud
        return rud

    # -- cursors (relative positions) ---------------------------------------

    def create_relative_position(self, guid: str, index: int,
                                 name: str | None = None):
        """Stable cursor at ``index`` of the room's root type ``name``
        (reference createRelativePositionFromTypeIndex,
        RelativePosition.js:85-104), computed from the device-resident
        room's mirror columns — no CPU-doc materialization per keystroke.
        The result is wire/JSON compatible with JS peers
        (encode_relative_position / to_json)."""
        self.flush()
        return self.engine.relative_position_from_index(
            self.doc_id(guid), index, name
        )

    def resolve_relative_position(self, guid: str, rpos) -> int | None:
        """Resolve a cursor to the current index (reference
        createAbsolutePositionFromRelativePosition,
        RelativePosition.js:214-262).  None = anchor unknown/GC'd.

        Rooms with server-side undo enabled resolve through their CPU
        replica, which runs the reference follow-redone walk verbatim —
        cursors anchored in undone-then-redone content land on the
        redone items.  ``redone`` pointers exist ONLY where an
        UndoManager performed the redo (they are never on the wire), so
        rooms without undo have no chains to follow and resolve straight
        from mirror columns."""
        from .utils.relative_position import (
            create_absolute_position_from_relative_position,
        )

        self.flush()
        ru = self._undo.get(guid)
        if ru is not None:
            a = create_absolute_position_from_relative_position(
                rpos, ru.replica
            )
            return None if a is None else a.index
        return self.engine.absolute_index_from_relative(
            self.doc_id(guid), rpos
        )

    def xml_string(self, guid: str) -> str:
        """XML serialization of the room's root fragment (reference
        YXmlFragment.toString) — served from the mirror."""
        self.flush()
        return self.engine.xml_string(self.doc_id(guid))

    def state_vector(self, guid: str) -> dict[int, int]:
        self.flush()
        return self.engine.state_vector(self.doc_id(guid))

    def encode_state_as_update(self, guid: str, target_sv: bytes | None = None) -> bytes:
        self.flush()
        return self.engine.encode_state_as_update(self.doc_id(guid), target_sv)

    @property
    def n_fallback_docs(self) -> int:
        return len(self.engine.fallback)

    @property
    def demotions(self) -> list[dict]:
        """Every device→CPU demotion with its reason, keyed by room guid —
        scope gaps are measurable, not silent."""
        return [
            {"guid": self._guid_of.get(d["doc"], d["doc"]),
             "reason": d["reason"]}
            for d in self.engine.demotions
        ]

    @property
    def metrics(self) -> dict | None:
        """Host per-phase timers + batch stats of the last flush, as a
        DEFENSIVE COPY (mutating the returned dict cannot corrupt the
        engine's flush history; before this was the live dict).

        The key set is stable across every flush mode (apply / levels /
        seq / ``YTPU_NO_NATIVE_PLAN``) and is exactly
        ``yjs_tpu.obs.FLUSH_METRICS_SCHEMA``: counts ``n_docs_flushed``,
        ``n_demoted``, ``n_rolled_back``, ``n_fallback_docs``, ``n_rows_max``,
        ``n_sched_entries``, ``n_levels``, ``level_width``,
        ``n_pending_docs``, ``pending_depth``, ``plan_threads``; the
        ``schedule_occupancy`` ratio; and the per-phase second timers
        ``t_compact_s``, ``t_plan_s``, ``t_pack_s``, ``t_dispatch_s``,
        ``t_emit_s``, ``t_total_s``.  ``None`` before the first flush."""
        m = self.engine.last_flush_metrics
        return None if m is None else dict(m)

    @property
    def metrics_history(self) -> list[dict]:
        """Per-flush metric dicts, oldest to newest (copies), for the last
        ``YTPU_OBS_HISTORY`` flushes."""
        return self.engine.obs.history.snapshot()

    def metrics_text(self) -> str:
        """Prometheus exposition-format dump of the whole stack: provider
        counters, engine flush metrics, sync-protocol frame counters."""
        return self.engine.metrics_text()

    def metrics_snapshot(self) -> dict:
        """JSON-able snapshot of the whole stack (see
        BatchEngine.metrics_snapshot), plus the provider's convergence
        SLO state under ``"slo"``."""
        # tier snapshot FIRST: it refreshes the ytpu_tier_* gauges the
        # engine snapshot is about to read
        tiers = self.tiers.snapshot()
        snap = self.engine.metrics_snapshot()
        snap["slo"] = self.slo.snapshot()
        snap["sessions"] = self.sessions_snapshot()
        snap["tiers"] = tiers
        snap["admission"] = self.admission.snapshot()
        snap["cost"] = self.cost.snapshot()
        if self.geo is not None:
            snap["geo"] = self.geo.snapshot()
        return snap

    def slo_snapshot(self) -> dict:
        """Convergence-SLO state: target, per-window burn rates, and the
        ok/warning/page verdict (see :class:`yjs_tpu.obs.slo.ConvergenceTracker`)."""
        return self.slo.snapshot()

    # -- admin-plane surface (ISSUE 16) -------------------------------------

    def residue_fraction(self) -> float | None:
        """Fraction of last-flush planned structs handed to the
        sequential YATA conflict fallback (``None`` before the first
        flush with planner work) — the ROADMAP's top hot-spot number."""
        m = self.engine.last_flush_metrics or {}
        planned = (
            m.get("plan_segment_fast", 0) + m.get("plan_segment_residue", 0)
        )
        if not planned:
            return None
        return m.get("plan_segment_residue", 0) / planned

    def statusz(self) -> dict:
        """The one-page JSON status the admin plane serves at
        ``/statusz``: identity, occupancy across tiers, session table,
        SLO verdict, brownout level, plan-cache hit rate, and the
        segment-residue fraction."""
        from .obs import global_registry

        reg = global_registry()

        def _val(name):
            return getattr(reg.get(name), "value", 0)

        hits = _val("ytpu_plan_cache_hits_total")
        probes = hits + _val("ytpu_plan_cache_misses_total")
        adm = self.admission.snapshot()
        rec = self.last_recovery or {}
        frac = self.residue_fraction()
        return {
            "role": "provider" if self.shard_id is None else "shard",
            "shard": self.shard_id,
            "docs": len(self._guids),
            "capacity": self.engine.n_docs,
            "occupancy": round(self.occupancy, 4),
            "resident_docs": self.resident_docs,
            "fallback_docs": self.n_fallback_docs,
            "tiers": self.tier_snapshot(),
            "sessions": self.sessions_snapshot(),
            "slo": self.slo_snapshot(),
            "health": self.health(),
            "admission": {
                "level": adm["level"],
                "level_name": adm["level_name"],
                "queue_depth": adm["queue_depth"],
            },
            "plan_cache_hit_rate": (
                round(hits / probes, 4) if probes else None
            ),
            "residue_fraction": (
                None if frac is None else round(frac, 4)
            ),
            "recovering": self.recovering,
            "recovered_records": rec.get("records_applied", 0),
            "geo": None if self.geo is None else self.geo.snapshot(),
        }

    def readiness(self) -> dict:
        """The ``/readyz`` verdict: ready iff recovery is complete and
        the brownout ladder sits below reject-writes.  Reads only plain
        attributes — a readiness probe must never contend on engine
        locks (liveness is ``/healthz``'s job; this answers "should you
        route traffic here")."""
        level = self.admission.brownout.level
        ready = (not self.recovering) and level < 3
        return {
            "ready": ready,
            "checks": {
                "recovery_complete": not self.recovering,
                "brownout_level": level,
                "accepting_writes": level < 3,
            },
        }

    def trace_events(self) -> list[dict]:
        """Bounded recent-span dump for the admin plane's
        ``/debug/trace``."""
        return self.engine.obs.tracer.trace_events()

    # -- tiering surface (ISSUE 7) ------------------------------------------

    def demote_doc(self, guid: str, tier: str = "warm") -> bool:
        """Manually push a hot room down a tier (``"warm"`` exports its
        columns to host and frees the slot; ``"cold"`` additionally folds
        it into a WAL tier record).  The room stays addressable — the
        next :meth:`doc_id` touch promotes it back.  Raises KeyError for
        an unknown guid, ValueError for an undemotable one (CPU-fallback
        or observed rooms are slot-bound)."""
        return self.tiers.demote(guid, tier)

    def tick_tiering(self) -> None:
        """Periodic tier maintenance: enforce the warm-tier bound and
        run one tombstone/GC compaction pass over eligible hot docs.
        No-op when tiering is disabled; the fleet router calls this from
        its own ``tick()``."""
        self.tiers.tick()

    def tier_snapshot(self) -> dict:
        """JSON-able tier occupancy: per-tier doc counts, host/cold
        byte footprints, and the active ``YTPU_TIER_*`` config."""
        return self.tiers.snapshot()

    # -- resilience surface (ISSUE 2) ---------------------------------------

    def health(self, guid: str | None = None) -> dict:
        """Health of one room (``{"state", "consecutive_failures", ...}``;
        rooms never seen failing report healthy), or — with no guid —
        the fleet summary ``{"degraded", "quarantined", "tick"}``."""
        h = self.engine.health
        if guid is None:
            return h.summary()
        rec = h.record(self.doc_id(guid))
        rec["guid"] = guid
        return rec

    def dead_letters(self, guid: str | None = None) -> list[dict]:
        """Dead letters (oldest-first, JSON-able views), optionally for
        one room.  Raw bytes stay in the engine's queue — replay them
        with :meth:`replay_dead_letters`."""
        doc = None if guid is None else self.doc_id(guid)
        out = []
        for e in self.engine.dead_letters.list(doc=doc):
            d = e.as_dict()
            d["guid"] = self._guid_of.get(e.doc)
            out.append(d)
        return out

    def replay_dead_letters(
        self, guid: str | None = None, seqs=None, repair=None,
        readmit: bool = True, max_letters: int | None = None,
    ) -> dict:
        """Re-inject dead letters (one room, or all) through the normal
        ingestion path after a fix — see
        :meth:`BatchEngine.replay_dead_letters`.  ``readmit`` defaults
        to True here: an operator replaying a room's letters means "I
        fixed it", which should override the quarantine backoff."""
        doc = None if guid is None else self.doc_id(guid)
        if self.wal is not None:
            # replayed letters re-enter via engine.queue_update, below
            # the provider's journal seam — wrap the repair hook so the
            # bytes actually replayed are journaled like fresh traffic
            inner = repair

            def repair(e, _inner=inner):
                fixed = _inner(e) if _inner is not None else e.update
                if fixed is not None:
                    g = self._guid_of.get(e.doc)
                    if g is not None:
                        self.wal.append(
                            KIND_UPDATE, g, bytes(fixed), v2=e.v2
                        )
                return fixed

        res = self.engine.replay_dead_letters(
            doc=doc, seqs=seqs, repair=repair, readmit=readmit,
            max_letters=max_letters,
        )
        if res["replayed"]:
            self._dirty = True
        return res

    def resilience_snapshot(self) -> dict:
        """JSON-able failure-isolation state with room guids attached."""
        snap = self.engine.resilience_snapshot()
        for rec in snap["docs"]:
            rec["guid"] = self._guid_of.get(rec["doc"])
        return snap

    # -- durability surface (ISSUE 3) ---------------------------------------

    def checkpoint(self) -> dict | None:
        """Fold the WAL into per-doc snapshots + the DLQ dump and
        truncate the journaled history (see
        :meth:`yjs_tpu.persistence.WriteAheadLog.checkpoint`).  One
        batched ``encode_states_batched`` dispatch snapshots the whole
        fleet.  Returns the compaction stats (None without a WAL)."""
        if self.wal is None:
            return None
        self.flush()
        docs = sorted(self._guid_of)
        snaps = self.engine.encode_states_batched(docs)
        pairs = [(self._guid_of[i], s) for i, s in zip(docs, snaps)]
        # demoted docs join the checkpoint too (materializing cold
        # locators BEFORE compaction deletes the segments they point at)
        pairs.extend(self.tiers.demoted_snapshots())
        res = self.wal.checkpoint(pairs, self._dump_dlq())
        # compaction dropped the segments the session ack floors lived
        # in: re-journal them so a crash after this checkpoint still
        # resumes peer retransmission instead of full-resyncing
        self._journal_ack_floors()
        self._journal_geo_floors()
        # same idiom for the tier demote markers + cold locators
        self.tiers.rejournal()
        return res

    def close(self, checkpoint: bool = True) -> None:
        """Orderly shutdown: flush, write a final checkpoint (so restart
        recovery is one snapshot read, no tail replay), seal the WAL.
        Safe without a WAL (just flushes)."""
        self.flush()
        if self.wal is not None:
            if checkpoint:
                self.checkpoint()
            self.wal.close()
        if self.admin is not None:
            self.admin.close()

    def release_doc(self, guid: str) -> bytes:
        """Evict a room and free its engine slot for reuse (the typed
        answer to :class:`ProviderFullError`).  The room's final state
        is snapshotted, journaled as a release record (recovery then
        knows the room left DELIBERATELY and must not resurrect it),
        and returned — the caller archives it or hands it to another
        provider.  The slot's dead letters are PRESERVED (ISSUE 7
        satellite; they were silently dropped before): each is re-tagged
        to the unattributed doc=-1 with the room named in its reason —
        never misattributed to the slot's next tenant, never lost — and
        the re-tagged set rides a journaled DLQ record so recovery
        keeps it too.  A demoted room releases from its tier the same
        way, without ever touching a slot."""
        i = self._guids.get(guid)
        if i is None:
            # the room may be demoted (ISSUE 7): release from its tier
            released = self.tiers.release(guid)
            if released is None:
                raise KeyError(f"unknown room {guid!r}")
            final, letters = released
            if self.wal is not None:
                self.wal.append(KIND_RELEASE, guid, final)
            self._preserve_released_letters(guid, letters)
            self._undo.pop(guid, None)
            self._undo_settings.pop(guid, None)
            self._user_data = {
                k: v for k, v in self._user_data.items() if k[0] != guid
            }
            self._m_evicted.inc()
            return final
        self.flush()
        final = self.engine.encode_state_as_update(i)
        if self.wal is not None:
            self.wal.append(KIND_RELEASE, guid, final)
        letters = [
            {
                "v2": bool(e.v2),
                "reason": e.reason,
                "update": base64.b64encode(e.update).decode("ascii"),
            }
            for e in self.engine.dead_letters.take(doc=i)
        ]
        self._preserve_released_letters(guid, letters)
        self.engine.reset_doc(i)
        del self._guids[guid]
        del self._guid_of[i]
        self._undo.pop(guid, None)
        self._undo_settings.pop(guid, None)
        self._user_data = {
            k: v for k, v in self._user_data.items() if k[0] != guid
        }
        self._free.append(i)
        self.tiers.forget(guid)
        self._m_evicted.inc()
        return final

    def _preserve_released_letters(
        self, guid: str, letters: list[dict]
    ) -> None:
        """Re-enqueue an evicted room's dead letters unattributed
        (doc=-1, room named in the reason) and journal them (KIND_DLQ)
        so recovery preserves the set past the release record."""
        if not letters:
            return
        dlq = self.engine.dead_letters
        dumped = []
        for e in letters:
            reason = f"evicted {guid!r}: {e.get('reason', '')}"
            dlq.append(
                -1, base64.b64decode(e.get("update", "")),
                bool(e.get("v2")),
                reason,
            )
            dumped.append(
                {"v2": bool(e.get("v2")), "reason": reason,
                 "update": e.get("update", "")}
            )
        if self.wal is not None:
            # guid-less letters restore to doc=-1 (see _restore_dlq)
            self.wal.append(
                KIND_DLQ, "",
                json.dumps({"schema": 1, "letters": dumped}).encode(
                    "utf-8"
                ),
            )

    def _apply_release_record(self, guid: str) -> None:
        """Recovery saw a release record: forget the room (its snapshot
        payload is the archived state, not live traffic).  The slot's
        replay-time letters are re-tagged unattributed, mirroring
        :meth:`release_doc` (the journaled KIND_DLQ record that follows
        a live release re-adds the originals)."""
        i = self._guids.pop(guid, None)
        if i is None:
            return
        for e in self.engine.dead_letters.take(doc=i):
            self.engine.dead_letters.append(
                -1, e.update, e.v2, f"evicted {guid!r}: {e.reason}"
            )
        self.engine.reset_doc(i)
        del self._guid_of[i]
        self._free.append(i)
        self.tiers.forget(guid)
        self._m_evicted.inc()

    def _dump_dlq(self) -> dict:
        """Checkpoint-grade DLQ dump with doc slots translated to guids
        (slot numbers are not stable across a recovery)."""
        state = self.engine.dead_letters.snapshot(letters=True)
        for e in state.get("letters") or []:
            e["guid"] = self._guid_of.get(e.pop("doc"))
        return state

    def _restore_dlq(self, state: dict) -> int:
        """Re-enqueue a checkpoint's DLQ dump, mapping guids back to
        this process's slots (letters for unknown/evicted rooms keep
        doc=-1, same as other unattributable letters)."""
        for e in state.get("letters") or []:
            g = e.pop("guid", None)
            if g is None:
                e["doc"] = -1
                continue
            try:
                e["doc"] = self.doc_id(g)
            except ProviderFullError:
                e["doc"] = -1
        return self.engine.dead_letters.restore(state)

    @classmethod
    def recover(
        cls,
        path,
        n_docs: int | None = None,
        root_name: str = "text",
        mesh=None,
        gc: bool = False,
        backend: str = "auto",
        wal_config: WalConfig | None = None,
        tier_config=None,
        admission_config=None,
    ) -> "TpuProvider":
        """Rebuild a provider from a crashed predecessor's WAL directory.

        Replays snapshot-then-tail (see
        :func:`yjs_tpu.persistence.replay_wal`): torn final-segment
        tails are truncated, mid-log corrupt records are dead-lettered,
        and the rebuilt provider journals onward into the SAME
        directory (its appends start a fresh segment past the replayed
        history).  ``n_docs=None`` sizes the fleet from the distinct
        guids in the log.  The replay stats land in
        ``provider.last_recovery``."""
        from .persistence import count_guids, replay_wal

        if n_docs is None:
            n_docs = max(1, count_guids(path))
        prov = cls(
            n_docs,
            root_name=root_name,
            mesh=mesh,
            gc=gc,
            backend=backend,
            wal_dir=path,
            wal_config=wal_config,
            tier_config=tier_config,
            admission_config=admission_config,
        )
        prov.recovering = True
        try:
            prov.last_recovery = replay_wal(
                prov, path, exclude_from=prov.wal.first_index
            )
        finally:
            prov.recovering = False
        return prov


class RoomUndoHandle:
    """Guid-bound view of one room's server-side undo stack.

    All reverting operations route through the provider so the
    device-resident room and the undo replica can never diverge — the
    raw RoomUndo's own undo()/redo() would revert only the replica."""

    __slots__ = ("_provider", "_guid")

    def __init__(self, provider: TpuProvider, guid: str):
        self._provider = provider
        self._guid = guid

    def undo(self) -> bytes | None:
        return self._provider.undo(self._guid)

    def redo(self) -> bytes | None:
        return self._provider.redo(self._guid)

    @property
    def can_undo(self) -> bool:
        return self._provider._room_undo(self._guid).can_undo

    @property
    def can_redo(self) -> bool:
        return self._provider._room_undo(self._guid).can_redo

    def stop_capturing(self) -> None:
        self._provider._room_undo(self._guid).stop_capturing()

    def clear(self) -> None:
        self._provider._room_undo(self._guid).clear()

    @property
    def manager(self):
        """The underlying reference UndoManager (event subscription —
        stack-item-added / stack-item-popped)."""
        return self._provider._room_undo(self._guid).manager


class RoomUserData:
    """Read-side twin of the reference PermanentUserData
    (PermanentUserData.js:15-142) for a device-resident room: the
    ``users`` map — ``{description: {"ids": [clientid...],
    "ds": [encoded DeleteSet...]}}``, written by editing clients with
    setUserMapping — is read from mirror columns on demand.

    The parse is cached against the mirror's change counter
    (``content_gen``), which bumps on every integrated mutation —
    delete-only updates and compaction included.

    Deviation (documented): the reference PermanentUserData accumulates
    mappings in observer-fed dicts and never forgets them, so a deleted
    users-map entry still resolves there; this view reads the CURRENT
    map, so deleting a user's entry removes the attribution.  Reading
    live state is the defensible server behavior (the reference marks
    PermanentUserData @experimental); the difference is pinned in
    tests/test_permanent_user_data.py."""

    __slots__ = ("_provider", "_guid", "_store", "_gen_seen", "_clients",
                 "_dss")

    def __init__(self, provider: TpuProvider, guid: str, store_name: str):
        self._provider = provider
        self._guid = guid
        self._store = store_name
        self._gen_seen = -1
        self._clients: dict[int, str] = {}
        self._dss: dict = {}

    def _refresh(self) -> None:
        from .coding import DSDecoderV1
        from .core import DeleteSet, merge_delete_sets, read_delete_set
        from .lib0.decoding import Decoder

        prov = self._provider
        prov.flush()
        i = prov.doc_id(self._guid)
        eng = prov.engine
        fb = eng.fallback.get(i)
        if fb is None:
            gen = eng.mirrors[i].content_gen()
            if gen == self._gen_seen:
                return
        else:
            # demoted room: no cheap change counter — always reparse
            gen = -1
        users = (
            fb.get_map(self._store).to_json()
            if fb is not None
            else eng.map_json(i, self._store)
        )
        clients: dict[int, str] = {}
        dss: dict = {}
        for desc, rec in users.items():
            if not isinstance(rec, dict):
                continue
            for cid in rec.get("ids") or []:
                if isinstance(cid, int):
                    clients[cid] = desc
            sets = [
                read_delete_set(DSDecoderV1(Decoder(bytes(b))))
                for b in rec.get("ds") or []
                if isinstance(b, (bytes, bytearray))
            ]
            dss[desc] = merge_delete_sets(sets) if sets else DeleteSet()
        self._clients = clients
        self._dss = dss
        self._gen_seen = gen

    def user_by_client_id(self, clientid: int) -> str | None:
        """reference getUserByClientId (PermanentUserData.js:126-128)."""
        self._refresh()
        return self._clients.get(clientid)

    def user_by_deleted_id(self, id) -> str | None:
        """reference getUserByDeletedId (PermanentUserData.js:134-141)."""
        from .core import is_deleted

        self._refresh()
        for desc, ds in self._dss.items():
            if is_deleted(ds, id):
                return desc
        return None

    @property
    def clients(self) -> dict[int, str]:
        self._refresh()
        return dict(self._clients)

    @property
    def dss(self) -> dict:
        self._refresh()
        return dict(self._dss)
