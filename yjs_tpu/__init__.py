"""y-tpu: a TPU-native shared-editing CRDT framework.

Public API surface mirrors the reference's export contract
(reference src/index.js:2-76): Doc, the shared types, struct/content
classes, update/state-vector codecs (V1+V2), snapshots, undo manager,
relative positions, and helpers — plus the batch extensions
(``merge_updates``/``diff_update``) that feed the TPU engine.

Camel-case aliases are provided for the most common entry points so code
written against the JS API maps 1:1.
"""

from .coding import (  # noqa: F401
    DSDecoderV1,
    DSDecoderV2,
    DSEncoderV1,
    DSEncoderV2,
    UpdateDecoderV1,
    UpdateDecoderV2,
    UpdateEncoderV1,
    UpdateEncoderV2,
    use_v1_encoding,
    use_v2_encoding,
)
from .core import (  # noqa: F401
    GC,
    AbstractStruct,
    ContentAny,
    ContentBinary,
    ContentDeleted,
    ContentDoc,
    ContentEmbed,
    ContentFormat,
    ContentJSON,
    ContentString,
    ContentType,
    DeleteItem,
    DeleteSet,
    Doc,
    Item,
    StructStore,
    Transaction,
    add_to_delete_set,
    create_delete_set,
    create_delete_set_from_struct_store,
    find_index_ss,
    generate_new_client_id,
    get_item,
    get_state,
    get_state_vector,
    is_deleted,
    is_parent_of,
    iterate_deleted_structs,
    log_type,
    merge_delete_sets,
    read_delete_set,
    sort_and_merge_delete_set,
    transact,
    try_gc,
    write_delete_set,
)
from .ids import ID, compare_ids, create_id, find_root_type_key  # noqa: F401
from .types import (  # noqa: F401
    AbstractType,
    YArray,
    YArrayEvent,
    YEvent,
    YMap,
    YMapEvent,
    YText,
    YTextEvent,
    YXmlElement,
    YXmlEvent,
    YXmlFragment,
    YXmlHook,
    YXmlText,
)
from .types.abstract import get_type_children  # noqa: F401
from .types.abstract import (  # noqa: F401
    type_list_to_array_snapshot,
    type_map_get_snapshot,
)
from .types.ytext import cleanup_ytext_formatting  # noqa: F401
from .updates import (  # noqa: F401
    apply_update,
    apply_update_v2,
    convert_update_format,
    decode_state_vector,
    decode_state_vector_v2,
    diff_update,
    diff_update_v2,
    encode_state_as_update,
    encode_state_as_update_v2,
    encode_state_vector,
    encode_state_vector_from_update,
    encode_state_vector_v2,
    merge_updates,
    merge_updates_v2,
    read_update,
    read_update_v2,
)
from .utils.abstract_connector import AbstractConnector  # noqa: F401
from .utils.permanent_user_data import PermanentUserData  # noqa: F401
from .utils.relative_position import (  # noqa: F401
    AbsolutePosition,
    RelativePosition,
    compare_relative_positions,
    create_absolute_position_from_relative_position,
    create_relative_position_from_json,
    create_relative_position_from_type_index,
    decode_relative_position,
    encode_relative_position,
    read_relative_position,
    write_relative_position,
)
from .utils.snapshot import (  # noqa: F401
    Snapshot,
    create_doc_from_snapshot,
    create_snapshot,
    decode_snapshot,
    decode_snapshot_v2,
    empty_snapshot,
    encode_snapshot,
    encode_snapshot_v2,
    equal_snapshots,
    is_visible,
    snapshot,
)
from .utils.undo import UndoManager  # noqa: F401

__version__ = "0.1.0"

# -- camelCase + JS-name aliases (reference src/index.js:2-76 contract) -----
# pinned by tests/test_exports.py against the reference export list
Array = YArray
Map = YMap
Text = YText
XmlText = YXmlText
XmlHook = YXmlHook
XmlElement = YXmlElement
XmlFragment = YXmlFragment
applyUpdate = apply_update
applyUpdateV2 = apply_update_v2
readUpdate = read_update
readUpdateV2 = read_update_v2
encodeStateAsUpdate = encode_state_as_update
encodeStateAsUpdateV2 = encode_state_as_update_v2
encodeStateVector = encode_state_vector
encodeStateVectorV2 = encode_state_vector_v2
decodeStateVector = decode_state_vector
decodeStateVectorV2 = decode_state_vector_v2
mergeUpdates = merge_updates
mergeUpdatesV2 = merge_updates_v2
diffUpdate = diff_update
diffUpdateV2 = diff_update_v2
createDocFromSnapshot = create_doc_from_snapshot
cleanupYTextFormatting = cleanup_ytext_formatting
getTypeChildren = get_type_children
createRelativePositionFromTypeIndex = create_relative_position_from_type_index
createRelativePositionFromJSON = create_relative_position_from_json
createAbsolutePositionFromRelativePosition = (
    create_absolute_position_from_relative_position
)
compareRelativePositions = compare_relative_positions
writeRelativePosition = write_relative_position
readRelativePosition = read_relative_position
createID = create_id
compareIDs = compare_ids
getState = get_state
createSnapshot = create_snapshot
createDeleteSet = create_delete_set
createDeleteSetFromStructStore = create_delete_set_from_struct_store
emptySnapshot = empty_snapshot
findRootTypeKey = find_root_type_key
getItem = get_item
typeListToArraySnapshot = type_list_to_array_snapshot
typeMapGetSnapshot = type_map_get_snapshot
iterateDeletedStructs = iterate_deleted_structs
decodeSnapshot = decode_snapshot
encodeSnapshot = encode_snapshot
decodeSnapshotV2 = decode_snapshot_v2
encodeSnapshotV2 = encode_snapshot_v2
isDeleted = is_deleted
isParentOf = is_parent_of
equalSnapshots = equal_snapshots
tryGc = try_gc
logType = log_type
